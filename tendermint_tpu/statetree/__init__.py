"""Authenticated app-state tree (round 13, docs/state-tree.md).

`VersionedTree` is the canonical app-state commitment: a persistent
(copy-on-write) merkleized treap over byte keys with O(log n) expected
insert/update/delete, one immutable root per committed height, and
membership/absence proofs whose pure verifier lives in
merkle/statetree_proof.py (light clients import only that). Dirty-node
recompute at commit batches through the ops/gateway.Hasher plane — the
same streamed devd `hash_stream` route the part-set tree rides.
"""

from tendermint_tpu.merkle.statetree_proof import TreeProof
from tendermint_tpu.statetree.tree import VersionedTree

__all__ = ["TreeProof", "VersionedTree"]
