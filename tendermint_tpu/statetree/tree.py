"""Versioned authenticated key/value tree: a persistent merkleized treap.

Structure (proof side + hash domains: merkle/statetree_proof.py): every
node holds one key/value entry; BST order on raw key bytes, max-heap
order on `key_priority(key)` — a hash of the key, so the tree SHAPE is a
pure function of the key set. That canonical-shape property is what lets
a node restored from a snapshot's sorted map, a node that applied a
delta chain, and a node that replayed every tx from genesis land on
byte-identical roots (the consensus requirement an insertion-order-
dependent AVL/IAVL shape would break without a separate tree-import
protocol).

Persistence is copy-on-write path copying: mutating ops copy the
O(log n) nodes on the search path (plus rotation/merge spines) and share
everything else, so `commit(version)` pins an immutable root per
committed height at O(changes) extra memory. Committed nodes are never
mutated; a node is "dirty" exactly while its `hash` is None.

Hashing at commit is batched: dirty nodes are grouped into child-first
waves and each wave's preimages go through ONE `Hasher.part_leaf_hashes`
call (the streamed devd `hash_stream` plane when a daemon serves, AVX
batch / CPU behind the shared breaker otherwise — ops/gateway routing).
A bulk load (snapshot restore) is a single O(n) Cartesian-tree build
whose n node hashes ride the same waves, which is where the streamed
plane wins big (benches/bench_statetree.py).

Thread safety: one RLock around every public op — reads included, since
the RPC query path proves against versions the consensus thread is
concurrently committing/pruning.
"""

from __future__ import annotations

import threading

from tendermint_tpu.codec.binary import encode_bytes
from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.libs.envknob import env_number
from tendermint_tpu.merkle.statetree_proof import (
    EMPTY_HASH,
    ProofStep,
    TreeProof,
    key_priority,
)

_LEAF_PREFIX = b"\x00"
_NODE_PREFIX = b"\x01"

# below this many preimages a wave hashes on the CPU: the gateway call's
# fixed dispatch overhead loses on narrow waves (same spirit as the
# Hasher's own min-batch floor)
_GATEWAY_WAVE_MIN = 32

DEFAULT_KEEP_VERSIONS = 64


class _Node:
    __slots__ = ("key", "value", "prio", "left", "right", "vh", "hash")

    def __init__(self, key: bytes, value: bytes, prio: bytes, left, right,
                 vh: bytes | None = None):
        self.key = key
        self.value = value
        self.prio = prio
        self.left = left
        self.right = right
        self.vh = vh  # ripemd160 of the value, leaf domain
        self.hash: bytes | None = None  # None == dirty (uncommitted)


def _copy(node: _Node) -> _Node:
    """A dirty copy sharing the children (and the value hash — the value
    is unchanged when only the shape around a node moves)."""
    return _Node(node.key, node.value, node.prio, node.left, node.right,
                 vh=node.vh)


class TreeError(Exception):
    pass


class VersionedTree:
    def __init__(self, hasher=None, keep_recent: int | None = None):
        self.hasher = hasher
        if keep_recent is None:
            keep_recent = int(env_number(
                "TENDERMINT_STATETREE_KEEP_VERSIONS", DEFAULT_KEEP_VERSIONS,
                cast=int,
            ))
        self.keep_recent = max(int(keep_recent), 1)
        self._mtx = threading.RLock()
        self._root: _Node | None = None
        self._size = 0
        self._versions: dict[int, _Node | None] = {}
        self._version_order: list[int] = []  # ascending
        self._version_sizes: dict[int, int] = {}
        # per-commit changed-key journal: diff(v0, v1) folds these — the
        # exact O(changes) record a delta snapshot needs, with no tree
        # walk at all
        self._journal: dict[int, frozenset[bytes]] = {}
        self._pending: set[bytes] = set()
        # gauges (statetree_* via node/telemetry.py)
        self._stats = {
            "commits": 0, "sets": 0, "deletes": 0,
            "nodes_created": 0, "hashed_nodes": 0, "hash_waves": 0,
            "gateway_nodes": 0, "proofs": 0,
            "last_commit_nodes": 0, "bulk_loads": 0,
        }

    # -- reads ---------------------------------------------------------------

    @property
    def size(self) -> int:
        with self._mtx:
            return self._size

    def versions(self) -> list[int]:
        with self._mtx:
            return list(self._version_order)

    def latest_version(self) -> int | None:
        with self._mtx:
            return self._version_order[-1] if self._version_order else None

    def has_version(self, version: int) -> bool:
        with self._mtx:
            return version in self._versions

    def _resolve_root(self, version: int | None) -> _Node | None:
        if version is None:
            return self._root
        if version not in self._versions:
            raise TreeError(f"version {version} not retained")
        return self._versions[version]

    def get(self, key: bytes, version: int | None = None) -> bytes | None:
        with self._mtx:
            node = self._resolve_root(version)
            while node is not None:
                if key == node.key:
                    return node.value
                node = node.left if key < node.key else node.right
            return None

    def entries(self, version: int | None = None) -> list[tuple[bytes, bytes]]:
        """All (key, value) pairs in sorted key order (iterative inorder)."""
        with self._mtx:
            out: list[tuple[bytes, bytes]] = []
            stack: list[_Node] = []
            node = self._resolve_root(version)
            while stack or node is not None:
                while node is not None:
                    stack.append(node)
                    node = node.left
                node = stack.pop()
                out.append((node.key, node.value))
                node = node.right
            return out

    def root_hash(self, version: int | None = None) -> bytes:
        """The committed root at `version` (latest committed when None).
        Raises on an uncommitted/unretained version — the working root's
        hash does not exist until commit()."""
        with self._mtx:
            if version is None:
                version = self.latest_version()
                if version is None:
                    return EMPTY_HASH
            root = self._resolve_root(version)
            if root is None:
                return EMPTY_HASH
            if root.hash is None:  # pragma: no cover - commit() always hashes
                raise TreeError(f"version {version} root is unhashed")
            return root.hash

    # -- writes (staging; visible at the next commit) ------------------------

    def set(self, key: bytes, value: bytes, prio: bytes | None = None) -> None:
        """`prio`, when given, MUST equal key_priority(key) — it lets a
        batch caller (the round-14 sharded kvstore apply) precompute the
        priorities through the gateway's batched RIPEMD plane instead of
        one hashlib call per new key; the shape (and therefore the root)
        is byte-identical by construction."""
        if not isinstance(key, bytes) or not isinstance(value, bytes):
            raise TypeError("tree keys and values are bytes")
        with self._mtx:
            self._stats["sets"] += 1
            self._pending.add(key)
            self._root = self._insert(self._root, key, value, prio=prio)

    def delete(self, key: bytes) -> bool:
        with self._mtx:
            if self.get(key) is None:
                return False
            self._stats["deletes"] += 1
            self._pending.add(key)
            self._root = self._remove(self._root, key)
            self._size -= 1
            return True

    def _new_node(self, key, value, prio, left, right, vh=None) -> _Node:
        self._stats["nodes_created"] += 1
        return _Node(key, value, prio, left, right, vh=vh)

    def _dirty_copy(self, node: _Node) -> _Node:
        self._stats["nodes_created"] += 1
        return _copy(node)

    def _insert(self, root: _Node | None, key: bytes, value: bytes,
                prio: bytes | None = None) -> _Node:
        # iterative COW descent: copy every node on the search path
        path: list[tuple[_Node, int]] = []  # (fresh copy, side taken: 0/1)
        node = root
        while node is not None and node.key != key:
            c = self._dirty_copy(node)
            side = 0 if key < node.key else 1
            path.append((c, side))
            node = node.left if side == 0 else node.right
        if node is not None:
            # value replacement: same key, same priority, same shape
            cur = self._new_node(key, value, node.prio, node.left, node.right)
        else:
            cur = self._new_node(
                key, value,
                prio if prio is not None else key_priority(key),
                None, None,
            )
            self._size += 1
        # link upward; a NEW node bubbles up by rotation while its
        # priority beats its parent's (treap heap repair)
        while path:
            parent, side = path.pop()
            if side == 0:
                parent.left = cur
            else:
                parent.right = cur
            if cur.prio > parent.prio:
                # rotate cur above parent (both are fresh copies)
                if side == 0:
                    parent.left = cur.right
                    cur.right = parent
                else:
                    parent.right = cur.left
                    cur.left = parent
            else:
                cur = parent
                while path:  # heap order holds above; just link
                    parent, side = path.pop()
                    if side == 0:
                        parent.left = cur
                    else:
                        parent.right = cur
                    cur = parent
                break
        return cur

    def _remove(self, root: _Node, key: bytes) -> _Node | None:
        path: list[tuple[_Node, int]] = []
        node = root
        while node.key != key:
            c = self._dirty_copy(node)
            side = 0 if key < node.key else 1
            path.append((c, side))
            node = node.left if side == 0 else node.right
        cur = self._merge(node.left, node.right)
        while path:
            parent, side = path.pop()
            if side == 0:
                parent.left = cur
            else:
                parent.right = cur
            cur = parent
        return cur

    def _merge(self, a: _Node | None, b: _Node | None) -> _Node | None:
        """Join two treaps where every key in `a` < every key in `b`,
        copying only the merge spine."""
        root: _Node | None = None
        attach: tuple[_Node, int] | None = None
        while True:
            if a is None or b is None:
                res = a if b is None else b
                break
            if a.prio > b.prio:
                c = self._dirty_copy(a)
                a = a.right
                side = 1
            else:
                c = self._dirty_copy(b)
                b = b.left
                side = 0
            if attach is None:
                root = c
            else:
                parent, pside = attach
                if pside == 0:
                    parent.left = c
                else:
                    parent.right = c
            attach = (c, side)
        if attach is None:
            return res
        parent, pside = attach
        if pside == 0:
            parent.left = res
        else:
            parent.right = res
        return root

    # -- bulk load -----------------------------------------------------------

    def load_entries(self, entries: dict[bytes, bytes] | list) -> None:
        """Replace the working tree wholesale with `entries` (snapshot
        restore). O(n) Cartesian-tree construction over the sorted keys;
        the resulting shape is identical to n incremental inserts in any
        order (canonical-shape property — tested against the oracle)."""
        items = sorted(entries.items() if isinstance(entries, dict) else entries)
        with self._mtx:
            self._stats["bulk_loads"] += 1
            spine: list[_Node] = []  # right spine, priorities decreasing
            root: _Node | None = None
            for key, value in items:
                n = self._new_node(key, value, key_priority(key), None, None)
                last_popped: _Node | None = None
                while spine and spine[-1].prio < n.prio:
                    last_popped = spine.pop()
                n.left = last_popped
                if spine:
                    spine[-1].right = n
                else:
                    root = n
                spine.append(n)
            self._root = root
            self._size = len(items)
            self._pending = {k for k, _ in items}

    @classmethod
    def from_entries(cls, entries, version: int, hasher=None,
                     keep_recent: int | None = None) -> "VersionedTree":
        t = cls(hasher=hasher, keep_recent=keep_recent)
        t.load_entries(entries)
        t.commit(version)
        return t

    # -- commit / versions ---------------------------------------------------

    def commit(self, version: int) -> bytes:
        """Hash every dirty node (batched waves through the gateway when
        wired), pin the working root as `version`, and return the root
        hash (EMPTY_HASH for an empty tree). Versions must strictly
        increase; retention drops the oldest beyond keep_recent."""
        with self._mtx:
            last = self.latest_version()
            if last is not None and version <= last:
                raise TreeError(
                    f"commit version {version} <= latest {last}"
                )
            n_hashed = self._hash_dirty(self._root)
            self._versions[version] = self._root
            self._version_order.append(version)
            self._version_sizes[version] = self._size
            self._journal[version] = frozenset(self._pending)
            self._pending = set()
            self._stats["commits"] += 1
            self._stats["last_commit_nodes"] = n_hashed
            while len(self._version_order) > self.keep_recent:
                old = self._version_order.pop(0)
                self._versions.pop(old, None)
                self._version_sizes.pop(old, None)
                self._journal.pop(old, None)
            root = self._versions[version]
            return root.hash if root is not None else EMPTY_HASH

    def rollback_to(self, version: int | None = None) -> None:
        """Discard uncommitted staging AND any versions newer than
        `version` (latest remaining when None) — the failed-delta-apply
        escape hatch: a delta whose recomputed root contradicts the
        verified app hash must leave the tree exactly at its base."""
        with self._mtx:
            if version is not None:
                while self._version_order and self._version_order[-1] > version:
                    v = self._version_order.pop()
                    self._versions.pop(v, None)
                    self._version_sizes.pop(v, None)
                    self._journal.pop(v, None)
            last = self.latest_version()
            self._root = self._versions[last] if last is not None else None
            self._size = self._version_sizes.get(last, 0) if last is not None else 0
            self._pending = set()

    def _hash_dirty(self, root: _Node | None) -> int:
        if root is None or root.hash is not None:
            return 0
        # dirty nodes are upward-closed (path copying), so a preorder
        # walk that only descends into dirty children finds them all;
        # reversed preorder puts every descendant before its ancestor
        dirty: list[_Node] = []
        stack = [root]
        while stack:
            n = stack.pop()
            dirty.append(n)
            for c in (n.left, n.right):
                if c is not None and c.hash is None:
                    stack.append(c)
        wave_of: dict[int, int] = {}
        waves: list[list[_Node]] = []
        need_vh: list[_Node] = []
        for n in reversed(dirty):
            w = 0
            for c in (n.left, n.right):
                if c is not None and c.hash is None:
                    w = max(w, wave_of[id(c)] + 1)
            wave_of[id(n)] = w
            while len(waves) <= w:
                waves.append([])
            waves[w].append(n)
            if n.vh is None:
                need_vh.append(n)
        # wave -1: the value hashes (one batch for every new value)
        if need_vh:
            digests = self._hash_batch(
                [_LEAF_PREFIX + encode_bytes(n.value) for n in need_vh]
            )
            for n, d in zip(need_vh, digests):
                n.vh = d
        # child-first node waves: within a wave no node depends on
        # another, so each wave is one gateway batch
        for wave in waves:
            pre = [
                _NODE_PREFIX
                + encode_bytes(n.key)
                + encode_bytes(n.vh)
                + encode_bytes(n.left.hash if n.left is not None else EMPTY_HASH)
                + encode_bytes(n.right.hash if n.right is not None else EMPTY_HASH)
                for n in wave
            ]
            for n, d in zip(wave, self._hash_batch(pre)):
                n.hash = d
        self._stats["hashed_nodes"] += len(dirty)
        self._stats["hash_waves"] += len(waves) + (1 if need_vh else 0)
        return len(dirty)

    def _hash_batch(self, preimages: list[bytes]) -> list[bytes]:
        if self.hasher is not None and len(preimages) >= _GATEWAY_WAVE_MIN:
            self._stats["gateway_nodes"] += len(preimages)
            # part_leaf_hashes = batched raw RIPEMD-160 (streamed devd /
            # AVX / CPU behind the shared breaker — never raises)
            return self.hasher.part_leaf_hashes(preimages)
        return [ripemd160(p) for p in preimages]

    # -- diffs (delta snapshots) ---------------------------------------------

    def diff(self, v0: int, v1: int) -> tuple[dict[bytes, bytes], list[bytes]]:
        """(upserts, deletes) taking version v0's tree to v1's, folded
        from the commit journals — exact and O(changed log n). Raises
        TreeError when either version (or any journal between) was
        pruned; callers (the snapshot producer) fall back to a full
        snapshot."""
        with self._mtx:
            if v0 not in self._versions or v1 not in self._versions:
                raise TreeError(f"diff versions {v0}..{v1} not retained")
            if not v0 < v1:
                raise TreeError(f"diff needs v0 < v1, got {v0}..{v1}")
            changed: set[bytes] = set()
            for v in self._version_order:
                if v0 < v <= v1:
                    changed.update(self._journal[v])
            upserts: dict[bytes, bytes] = {}
            deletes: list[bytes] = []
            for k in sorted(changed):
                new = self.get(k, v1)
                old = self.get(k, v0)
                if new is None:
                    if old is not None:
                        deletes.append(k)
                elif new != old:
                    upserts[k] = new
            return upserts, deletes

    # -- proofs --------------------------------------------------------------

    def prove(self, key: bytes, version: int | None = None) -> TreeProof:
        """Membership (key present) or absence proof against the
        committed root at `version` (latest when None). Raises TreeError
        for unretained versions."""
        with self._mtx:
            if version is None:
                version = self.latest_version()
                if version is None:
                    return TreeProof(key, None, [])
            node = self._resolve_root(version)
            path: list[_Node] = []
            value: bytes | None = None
            while node is not None:
                path.append(node)
                if key == node.key:
                    value = node.value
                    break
                node = node.left if key < node.key else node.right
            steps = [
                ProofStep(
                    n.key, n.vh,
                    n.left.hash if n.left is not None else EMPTY_HASH,
                    n.right.hash if n.right is not None else EMPTY_HASH,
                )
                for n in reversed(path)
            ]
            self._stats["proofs"] += 1
            return TreeProof(key, value, steps)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        with self._mtx:
            out = dict(self._stats)
            out["size"] = self._size
            out["versions_retained"] = len(self._version_order)
            last = self.latest_version()
            out["latest_version"] = last if last is not None else 0
            return out
