"""Snapshot restore: verify a manifest + chunks against the light-client
header chain, apply the app state, and seed state DB + block store at the
snapshot height — after which the ordinary fast-sync reactor replays only
the tail.

Trust model (docs/state-sync.md): NOTHING in a snapshot is trusted on
its own. The manifest binds to two light-verified headers —

    manifest.header_hash == hash(header H)
    manifest.app_hash    == header (H+1).app_hash

(the app hash resulting from block H's commit is carried by header H+1)
— and every claim inside the payload is checked against those headers:
the embedded state's last_block_id, app_hash, validator sets (via
validators_hash of H and H+1), the block-H meta/parts (proof-verified
against the parts root the seen commit SIGNED), and the seen commit
itself (+2/3 of the verified height-H set). Chunk digests batch-verify
against the manifest through the hashing gateway (streamed devd plane
when a daemon serves, CPU fallback behind the breaker), so transport
corruption is caught per chunk, before reassembly.

The light client walks sequentially from its trust anchor (genesis
trust-on-first-use, or an operator-pinned height), so restore cost is
one commit-verify per height from anchor to H+1 plus the snapshot apply
— against fast-sync's verify + EXECUTE + store per height from genesis.
"""

from __future__ import annotations

import json
import logging
import time

from tendermint_tpu.statesync.snapshot import (
    KIND_DELTA,
    KIND_FULL,
    Manifest,
    chunk_digest,
)

logger = logging.getLogger("statesync.restore")


class RestoreError(Exception):
    pass


class ManifestBindingError(RestoreError):
    """The manifest CONTRADICTS the light-verified chain (wrong chain id,
    header hash, or app hash) — proof the peer that served it lied, as
    opposed to a light-walk failure, which says nothing about the peer."""


class SnapshotRejected(RestoreError):
    """The snapshot CONTENT is proven bad (payload verification failed)
    or the height is permanently unverifiable (behind the light trust) —
    the reactor blacklists the height. A plain RestoreError is treated
    as transient (timeout, no peers, transport) and retried."""


def verify_chunk_batch(
    manifest: Manifest, indexed_chunks: list[tuple[int, bytes]], hasher=None
) -> list[int]:
    """Digest-check received chunks against the manifest in ONE batch
    (the gateway's streamed hash plane when wired). Returns the indices
    whose digest MISMATCHES — the caller's refetch/peer-ban list.
    Out-of-range indices raise: the caller already validated them."""
    for idx, _ in indexed_chunks:
        if not 0 <= idx < manifest.chunks:
            raise RestoreError(f"chunk index {idx} out of range")
    payloads = [c for _, c in indexed_chunks]
    if hasher is not None and payloads:
        digests = hasher.part_leaf_hashes(payloads)
    else:
        digests = [chunk_digest(c) for c in payloads]
    return [
        idx
        for (idx, _), got in zip(indexed_chunks, digests)
        if got != manifest.chunk_digests[idx]
    ]


class Restorer:
    """Pure verify/apply logic, transport-agnostic: the p2p reactor (and
    tests/benches) feed it a manifest + chunks however they obtained
    them. `light_client` is an rpc/light.LightClient positioned at or
    before the snapshot height; pass trust_manifest=True ONLY in tests
    that verify other layers."""

    def __init__(
        self,
        genesis_doc,
        app,
        state_db,
        block_store,
        hasher=None,
        light_client=None,
        batch_verifier=None,
        trust_manifest: bool = False,
    ):
        if light_client is None and not trust_manifest:
            raise ValueError("Restorer needs a light client (or trust_manifest=True)")
        self.genesis_doc = genesis_doc
        self.app = app
        self.state_db = state_db
        self.block_store = block_store
        self.hasher = hasher
        self.light_client = light_client
        self.batch_verifier = batch_verifier
        self.trust_manifest = trust_manifest
        # headers the light walk verified, by height — verify_manifest
        # may run more than once for the same snapshot (the reactor
        # pre-binds before downloading, restore() re-binds before
        # applying) and the light client cannot walk backwards
        self._verified_headers: dict = {}
        # gauges (statesync_* in the metrics RPC)
        self.chunks_verified = 0
        self.chunk_digest_failures = 0
        self.restore_seconds = 0.0
        self.restored_height = 0
        self.deltas_applied = 0
        self.delta_entries_applied = 0
        self.delta_proof_failures = 0

    # -- verification ------------------------------------------------------

    def verify_manifest(self, manifest: Manifest):
        """Advance light-client trust through H+1 and bind the manifest
        to the verified headers. Returns (header_H, header_H1) — or
        (None, None) under trust_manifest. Raises RestoreError when the
        manifest contradicts the verified chain."""
        if self.light_client is None:
            return None, None
        from tendermint_tpu.rpc.light import LightClientError

        lc = self.light_client
        if lc.chain_id != manifest.chain_id:
            raise ManifestBindingError(
                f"manifest chain {manifest.chain_id!r} != trusted {lc.chain_id!r}"
            )
        h = manifest.height
        # walk a CLONE: a candidate snapshot whose walk or binding fails
        # must not advance the real trust — a forged high-height offer
        # would otherwise put every lower honest snapshot "behind the
        # light client" and force the genesis fast-sync fallback
        walker = None
        try:
            for height in (h, h + 1):
                if height not in self._verified_headers:
                    if walker is None:
                        walker = lc.copy()
                    # advance ONE height at a time, caching every header
                    # the walk verifies in passing: if this candidate
                    # later dies (chunks never arrive), a LOWER honest
                    # snapshot must still bind from the cache — the walk
                    # itself cannot go backwards
                    while walker.height < height:
                        step = walker.height + 1
                        try:
                            walker.advance(step)
                        except LightClientError:
                            raise
                        except Exception:
                            # a PRUNED source (round 19) cannot serve the
                            # one-height stride; aim the walk at its
                            # attested horizon instead — advance()'s
                            # pruned-gap signature rules carry the trust
                            # across, and everything below the horizon is
                            # uncacheable from this source regardless
                            floor = walker.horizon_floor()
                            if floor is None or not step < floor <= height:
                                raise
                            walker.advance(floor)
                        self._verified_headers[walker.height] = (
                            walker.trusted_header()
                        )
                    if walker.height != height:
                        # behind the anchor (or a prior walk) AND not in
                        # the cache: permanently unverifiable
                        raise SnapshotRejected(
                            f"snapshot height {height} is behind the light "
                            f"client's trust ({walker.height}); pick a newer one"
                        )
            header_h = self._verified_headers[h]
            header_h1 = self._verified_headers[h + 1]
        except LightClientError as exc:
            raise RestoreError(f"light verification to {h + 1} failed: {exc}")
        except RestoreError:
            raise
        except Exception as exc:  # noqa: BLE001 — transport/RPC failures
            # the walk rides a live RPC connection: a refused socket or
            # an RPC-client error is a TRANSIENT failure the driver must
            # retry, never a crash that abandons statesync for good
            raise RestoreError(
                f"light verification to {h + 1} failed (transport): {exc}"
            )
        if manifest.header_hash != header_h.hash():
            raise ManifestBindingError(
                f"manifest header hash {manifest.header_hash.hex()[:12]} != "
                f"verified header {header_h.hash().hex()[:12]} at {h}"
            )
        if manifest.app_hash != header_h1.app_hash:
            raise ManifestBindingError(
                f"manifest app hash does not match verified header {h + 1}"
            )
        if walker is not None:
            # the manifest bound: adopt the walked trust
            self.light_client = walker
        return header_h, header_h1

    def verify_chunks(self, manifest: Manifest, chunks: list[bytes]) -> None:
        if len(chunks) != manifest.chunks:
            raise RestoreError(
                f"{len(chunks)} chunk(s) for a {manifest.chunks}-chunk manifest"
            )
        bad = verify_chunk_batch(
            manifest, list(enumerate(chunks)), hasher=self.hasher
        )
        self.chunks_verified += len(chunks) - len(bad)
        self.chunk_digest_failures += len(bad)
        if bad:
            raise RestoreError(f"chunk digest mismatch at {bad}")

    def _parse_payload(self, manifest: Manifest, payload: bytes) -> dict:
        if len(payload) != manifest.total_bytes:
            raise RestoreError(
                f"payload is {len(payload)} bytes, manifest says {manifest.total_bytes}"
            )
        try:
            obj = json.loads(payload)
        except ValueError as exc:
            raise RestoreError(f"snapshot payload is not valid JSON: {exc}")
        if not isinstance(obj, dict) or obj.get("format") != manifest.format:
            raise RestoreError("snapshot payload format mismatch")
        if manifest.format >= 2 and obj.get("kind") != manifest.kind:
            raise RestoreError("snapshot payload kind mismatch")
        if obj.get("height") != manifest.height or obj.get("chain_id") != manifest.chain_id:
            raise RestoreError("snapshot payload height/chain mismatch")
        return obj

    def _verify_host(self, manifest: Manifest, obj: dict, header_h, header_h1):
        """Cross-check every host-section claim (embedded state, block H
        meta/parts, seen commit, validator history) against the verified
        headers. The seen commit comes from the PAYLOAD for format-1
        manifests and from the MANIFEST sidecar for format 2 (round 13:
        splitting it out of the digested bytes is what makes replica
        snapshot roots deterministic — it is re-verified here either
        way). Returns (state, meta, parts, seen_commit, validators_info)."""
        from tendermint_tpu.state.state import State
        from tendermint_tpu.types import PartSet
        from tendermint_tpu.types.agg_commit import commit_from_json
        from tendermint_tpu.types.block_meta import BlockMeta
        from tendermint_tpu.types.part_set import Part, PartSetError
        from tendermint_tpu.types.validator_set import CommitError

        h = manifest.height
        try:
            state = State.from_json_obj(
                self.state_db, self.genesis_doc, obj["state"]
            )
            meta = BlockMeta.from_json(obj["block"]["meta"])
            if manifest.format >= 2:
                if manifest.seen_commit is None:
                    raise ValueError("format-2 manifest carries no seen commit")
                seen_commit = commit_from_json(manifest.seen_commit)
            else:
                seen_commit = commit_from_json(obj["block"]["seen_commit"])
            parts_json = obj["block"]["parts"]
            validators_info = obj["validators_info"]
            if not isinstance(parts_json, list) or not isinstance(validators_info, dict):
                raise ValueError("bad parts/validators_info")
        except (KeyError, TypeError, ValueError) as exc:
            raise RestoreError(f"malformed snapshot payload: {exc}")

        if state.chain_id != manifest.chain_id or state.last_block_height != h:
            raise RestoreError("embedded state does not match manifest")
        # State.from_json_obj installs these without type checks, and the
        # restore path arithmetics on them (max() below, consensus time
        # math after handoff) — a non-int must refuse as a RestoreError,
        # not crash the driver
        lhc = state.last_height_validators_changed
        if not isinstance(lhc, int) or isinstance(lhc, bool) or not 0 <= lhc <= h + 1:
            raise RestoreError("bad state last_height_validators_changed")
        t_ns = state.last_block_time_ns
        if not isinstance(t_ns, int) or isinstance(t_ns, bool) or t_ns < 0:
            raise RestoreError("bad state block time")
        if state.last_block_id.hash != manifest.header_hash:
            raise RestoreError("embedded state's last block is not the verified header")
        if state.app_hash != manifest.app_hash:
            raise RestoreError("embedded state's app hash mismatch")
        if header_h is not None:
            if state.last_validators.hash() != header_h.validators_hash:
                raise RestoreError(
                    f"snapshot validator set at {h} does not match verified header"
                )
            if state.validators.hash() != header_h1.validators_hash:
                raise RestoreError(
                    f"snapshot validator set for {h + 1} does not match verified header"
                )
            if header_h1.last_block_id != state.last_block_id:
                raise RestoreError("verified header chain does not link the state")
        # the validator-history records seed load_validators and become
        # RPC-visible "historical truth", and seed_restored persists them
        # as-is — so every record is validated IN FULL here: the keys
        # must be exactly the heights the producer emits (lhc, H, H+1 —
        # validators_info_records), every record a well-formed
        # saveValidatorsInfo shape whose pointer resolves to a record in
        # this same payload, and every embedded set one of the two
        # header-verified ones
        allowed_keys = {str(max(lhc, 1)), str(h), str(h + 1)}
        if set(validators_info) - allowed_keys:
            raise RestoreError("validators_info carries unexpected heights")
        allowed = {state.validators.hash(), state.last_validators.hash()}
        for key, rec in validators_info.items():
            if not isinstance(rec, dict):
                raise RestoreError("malformed validators_info record")
            ptr = rec.get("last_height_changed")
            if (
                not isinstance(ptr, int) or isinstance(ptr, bool)
                or not 1 <= ptr <= int(key)
            ):
                raise RestoreError("bad validators_info pointer")
            if "validator_set" in rec:
                from tendermint_tpu.types.validator_set import ValidatorSet

                try:
                    vs = ValidatorSet.from_json(rec["validator_set"])
                except (KeyError, TypeError, ValueError) as exc:
                    raise RestoreError(f"malformed validators_info set: {exc}")
                if vs.hash() not in allowed:
                    raise RestoreError(
                        "validators_info record carries an unverified set"
                    )
            else:
                target = validators_info.get(str(ptr))
                if not isinstance(target, dict) or "validator_set" not in target:
                    raise RestoreError(
                        "validators_info pointer does not resolve to a set"
                    )
        # presence, not just shape: the records for H and H+1 MUST exist
        # (they are exactly what load_validators needs on the restored
        # node) — a stripped-empty validators_info would otherwise pass
        # every per-record check and restore a node whose /validators
        # queries raise forever
        for need in (str(h), str(h + 1)):
            if need not in validators_info:
                raise RestoreError(f"validators_info missing height {need}")

        # block H: meta must BE the verified header; parts must prove
        # into the parts root the seen commit signed (it signs the whole
        # BlockID, parts header included)
        if meta.header.hash() != manifest.header_hash:
            raise RestoreError("snapshot block meta is not the verified header")
        if meta.block_id != state.last_block_id:
            raise RestoreError("snapshot block meta id mismatch")
        ps = PartSet.from_header(meta.block_id.parts_header)
        try:
            for pj in parts_json:
                ps.add_part(Part.from_json(pj))
        except (PartSetError, ValueError) as exc:
            raise RestoreError(f"snapshot block parts invalid: {exc}")
        if not ps.is_complete():
            raise RestoreError("snapshot block parts incomplete")
        if seen_commit.block_id != meta.block_id:
            raise RestoreError("seen commit is not over the snapshot block")
        try:
            state.last_validators.verify_commit(
                state.chain_id, meta.block_id, h, seen_commit,
                batch_verifier=self.batch_verifier,
            )
        except CommitError as exc:
            raise RestoreError(f"seen commit verification failed: {exc}")
        parts = [ps.get_part(i) for i in range(ps.total)]
        return state, meta, parts, seen_commit, validators_info

    def _seed(self, state, meta, parts, seen_commit, validators_info) -> None:
        self.block_store.seed_snapshot(meta, parts, seen_commit)
        state.seed_restored(validators_info)

    # -- the whole path ----------------------------------------------------

    def restore(self, manifest: Manifest, chunks: list[bytes], seed: bool = True):
        """Verify everything, apply the app state, seed state DB + block
        store. Returns the restored State. Raises RestoreError; on any
        failure nothing was applied — all host-side verification
        precedes the first mutation, and the app's restore contract
        (abci/types.py) requires it to validate the payload against the
        verified (height, app_hash) before mutating in turn. `seed=False`
        applies the app only (a delta chain seeds store/state from its
        FINAL link — restore_chain)."""
        if manifest.kind != KIND_FULL:
            raise RestoreError("restore() takes a full snapshot; deltas go "
                               "through restore_delta()")
        t0 = time.perf_counter()
        header_h, header_h1 = self.verify_manifest(manifest)
        self.verify_chunks(manifest, chunks)
        obj = self._parse_payload(manifest, b"".join(chunks))
        state, meta, parts, seen_commit, validators_info = (
            self._verify_host(manifest, obj, header_h, header_h1)
        )
        try:
            app_state = bytes.fromhex(obj["app_state"])
        except (KeyError, TypeError, ValueError) as exc:
            raise RestoreError(f"malformed snapshot payload: {exc}")

        # -- apply: app first, then block store, then state — the state
        # key is what a restarting node loads, so it lands only over a
        # complete seed. The app gets the light-verified (height,
        # app_hash) to gate on: its restore contract (abci/types.py) is
        # to validate the payload against them BEFORE mutating, so a bad
        # app_state refuses with nothing applied or persisted
        info = self.app.info()
        if info.last_block_height == 0:
            try:
                self.app.restore(
                    app_state, height=manifest.height, app_hash=state.app_hash
                )
            except (KeyError, TypeError, ValueError) as exc:
                raise RestoreError(f"app refused the snapshot state: {exc}")
        elif (
            info.last_block_height == manifest.height
            and info.last_block_app_hash == state.app_hash
        ):
            # crash-window recovery: a previous restore persisted the app
            # (PersistentKVStoreApp._save) but died before the block
            # store/state seeded. The app already holds EXACTLY the
            # verified (height, app hash) — skipping the apply and
            # re-seeding the rest is idempotent; refusing would wedge the
            # node behind "needs a fresh app" forever
            logger.info(
                "app already at verified snapshot height %d; resuming the "
                "interrupted seed", manifest.height,
            )
        else:
            raise RestoreError(
                f"app already at height {info.last_block_height}; restore "
                "needs a fresh app"
            )
        info = self.app.info()
        if info.last_block_height != manifest.height:
            raise RestoreError(
                f"app restored to height {info.last_block_height}, "
                f"snapshot is {manifest.height}"
            )
        if info.last_block_app_hash != state.app_hash:
            raise RestoreError("restored app hash does not match verified state")

        if seed:
            self._seed(state, meta, parts, seen_commit, validators_info)

        self.restored_height = manifest.height
        self.restore_seconds = round(time.perf_counter() - t0, 4)
        logger.info(
            "restored snapshot at height %d: %d chunk(s), app hash %s (%.0f ms)",
            manifest.height, manifest.chunks,
            state.app_hash.hex()[:12], self.restore_seconds * 1000,
        )
        return state

    # -- delta restore (round 13, docs/state-tree.md) ----------------------

    def _decode_delta_entries(self, manifest: Manifest, chunks: list[bytes]):
        """Parse + PROVE every entry chunk against the manifest's light-
        bound app hash. Each upsert carries a membership proof for its
        (key, value); each delete an absence proof — so a chunk is
        verified against CONSENSUS the moment it's complete, not after
        the whole snapshot assembles (the trustless-resume property).
        Completeness (no omitted/extra change) is enforced later by the
        app recomputing the tree root. Returns (upserts, deletes)."""
        from tendermint_tpu.merkle.statetree_proof import (
            MAX_PROOF_STEPS,
            ProofStep,
            TreeProof,
        )

        upserts: dict[bytes, bytes] = {}
        deletes: list[bytes] = []
        seen_keys: set[bytes] = set()

        for ci, raw in enumerate(chunks[1:], start=1):
            try:
                grp = json.loads(raw)
            except ValueError as exc:
                raise RestoreError(f"delta chunk {ci} is not valid JSON: {exc}")
            if not isinstance(grp, dict) or grp.get("section") != "delta":
                raise RestoreError(f"delta chunk {ci} malformed")
            sets, dels = grp.get("sets"), grp.get("dels")
            raw_steps = grp.get("steps")
            if (
                not isinstance(sets, list) or not isinstance(dels, list)
                or not isinstance(raw_steps, list)
                or len(raw_steps) > (1 << 16)
            ):
                raise RestoreError(f"delta chunk {ci} malformed")
            try:
                steps = [ProofStep.from_json(s) for s in raw_steps]
            except ValueError as exc:
                raise RestoreError(f"malformed delta proof step: {exc}")

            def decode_proof(key, value, refs):
                # a proof is a bottom-up list of indices into the
                # chunk's shared step table (upper-tree steps dedupe
                # across every entry in the chunk)
                if (
                    not isinstance(refs, list)
                    or len(refs) > MAX_PROOF_STEPS
                    or any(
                        not isinstance(i, int) or isinstance(i, bool)
                        or not 0 <= i < len(steps)
                        for i in refs
                    )
                ):
                    raise RestoreError("malformed delta proof refs")
                return TreeProof(key, value, [steps[i] for i in refs])

            for entry in sets:
                if not isinstance(entry, list) or len(entry) != 3:
                    raise RestoreError("malformed delta upsert entry")
                try:
                    key, value = bytes.fromhex(entry[0]), bytes.fromhex(entry[1])
                except (TypeError, ValueError):
                    raise RestoreError("malformed delta upsert entry")
                proof = decode_proof(key, value, entry[2])
                if not proof.verify(manifest.app_hash):
                    self.delta_proof_failures += 1
                    raise RestoreError(
                        f"delta upsert proof failed against the verified "
                        f"app hash (chunk {ci})"
                    )
                if key in seen_keys:
                    raise RestoreError("duplicate key across delta chunks")
                seen_keys.add(key)
                upserts[key] = value
            for entry in dels:
                if not isinstance(entry, list) or len(entry) != 2:
                    raise RestoreError("malformed delta delete entry")
                try:
                    key = bytes.fromhex(entry[0])
                except (TypeError, ValueError):
                    raise RestoreError("malformed delta delete entry")
                proof = decode_proof(key, None, entry[1])
                if not proof.verify(manifest.app_hash):
                    self.delta_proof_failures += 1
                    raise RestoreError(
                        f"delta absence proof failed against the verified "
                        f"app hash (chunk {ci})"
                    )
                if key in seen_keys:
                    raise RestoreError("duplicate key across delta chunks")
                seen_keys.add(key)
                deletes.append(key)
        return upserts, deletes

    def _check_aux(self, aux, state) -> None:
        """The delta host section's app-private sidecar (e.g. the
        persistent kvstore's validator registry) is NOT covered by the
        tree root — cross-check it against the header-verified validator
        set before the app may apply it."""
        if aux is None:
            return
        if not isinstance(aux, dict):
            raise RestoreError("malformed delta app_aux")
        validators = aux.get("validators")
        if validators is None:
            return
        if not isinstance(validators, dict):
            raise RestoreError("malformed delta app_aux validators")
        try:
            claimed = {
                str(k).upper(): p for k, p in validators.items()
            }
        except (TypeError, ValueError):
            raise RestoreError("malformed delta app_aux validators")
        verified = {
            v.pub_key.raw.hex().upper(): v.voting_power
            for v in state.validators.validators
        }
        if claimed != verified:
            raise RestoreError(
                "delta app_aux validator registry does not match the "
                "header-verified set"
            )

    def restore_delta(self, manifest: Manifest, chunks: list[bytes],
                      seed: bool = True):
        """Advance an already-restored app from manifest.base_height to
        manifest.height by a verified delta. Every entry chunk proves
        its content against the light-bound app hash BEFORE the app
        applies anything, and the app's restore_delta contract re-derives
        the tree root and refuses (rolled back, nothing persisted) on any
        mismatch — an omitted or smuggled change cannot survive."""
        if manifest.kind != KIND_DELTA:
            raise RestoreError("restore_delta() takes a delta snapshot")
        t0 = time.perf_counter()
        header_h, header_h1 = self.verify_manifest(manifest)
        self.verify_chunks(manifest, chunks)
        if not chunks or sum(len(c) for c in chunks) != manifest.total_bytes:
            raise RestoreError("delta chunk bytes do not match the manifest")
        try:
            host = json.loads(chunks[0])
        except ValueError as exc:
            raise RestoreError(f"delta host section is not valid JSON: {exc}")
        if (
            not isinstance(host, dict)
            or host.get("format") != manifest.format
            or host.get("kind") != "delta"
            or host.get("section") != "host"
        ):
            raise RestoreError("delta host section malformed")
        if (
            host.get("height") != manifest.height
            or host.get("chain_id") != manifest.chain_id
            or host.get("base_height") != manifest.base_height
        ):
            raise RestoreError("delta host section contradicts the manifest")
        state, meta, parts, seen_commit, validators_info = (
            self._verify_host(manifest, host, header_h, header_h1)
        )
        upserts, deletes = self._decode_delta_entries(manifest, chunks)
        aux = host.get("app_aux")
        self._check_aux(aux, state)

        info = self.app.info()
        if (
            info.last_block_height == manifest.height
            and info.last_block_app_hash == state.app_hash
        ):
            # crash-window / chain-resume: this delta already applied
            # and persisted; re-seeding the rest is idempotent
            logger.info(
                "app already at verified delta height %d; resuming",
                manifest.height,
            )
        elif info.last_block_height != manifest.base_height:
            raise RestoreError(
                f"stale delta: app at height {info.last_block_height}, "
                f"delta bases on {manifest.base_height}"
            )
        else:
            apply = getattr(self.app, "restore_delta", None)
            if apply is None:
                raise RestoreError(
                    f"{type(self.app).__name__} cannot apply delta snapshots"
                )
            try:
                apply(upserts, deletes, manifest.height, state.app_hash, aux=aux)
            except (KeyError, TypeError, ValueError) as exc:
                raise RestoreError(f"app refused the delta: {exc}")
        info = self.app.info()
        if (
            info.last_block_height != manifest.height
            or info.last_block_app_hash != state.app_hash
        ):
            raise RestoreError("delta apply did not land on the verified state")

        if seed:
            self._seed(state, meta, parts, seen_commit, validators_info)

        self.deltas_applied += 1
        self.delta_entries_applied += len(upserts) + len(deletes)
        self.restored_height = manifest.height
        self.restore_seconds = round(time.perf_counter() - t0, 4)
        logger.info(
            "applied delta %d -> %d: %d upsert(s), %d delete(s) (%.0f ms)",
            manifest.base_height, manifest.height, len(upserts), len(deletes),
            self.restore_seconds * 1000,
        )
        return state

    def restore_step(self, manifest: Manifest, chunks: list[bytes],
                     seed: bool = True):
        """One link of a snapshot chain: full or delta by manifest kind."""
        if manifest.kind == KIND_DELTA:
            return self.restore_delta(manifest, chunks, seed=seed)
        return self.restore(manifest, chunks, seed=seed)

    def restore_chain(self, items: list[tuple[Manifest, list[bytes]]]):
        """Restore a full-then-deltas chain (ascending heights, each
        delta basing on the previous link). Only the FINAL link seeds the
        block store and state DB — intermediate links advance the app
        only. Links the app already passed (a crashed earlier run — the
        app persists per link) are skipped; any divergence a skip could
        hide is caught by the next delta's base check and root equality."""
        if not items:
            raise RestoreError("empty snapshot chain")
        for (prev, _), (cur, _) in zip(items, items[1:]):
            if cur.kind != KIND_DELTA or cur.base_height != prev.height:
                raise RestoreError("snapshot chain links do not connect")
        app_h = self.app.info().last_block_height
        resumable = app_h in {m.height for m, _ in items}
        state = None
        for i, (manifest, chunks) in enumerate(items):
            last = i == len(items) - 1
            if not last and resumable and app_h >= manifest.height:
                logger.info(
                    "skipping chain link %d (app already at %d)",
                    manifest.height, app_h,
                )
                continue
            state = self.restore_step(manifest, chunks, seed=last)
        return state

    def stats(self) -> dict:
        return {
            "chunks_verified": self.chunks_verified,
            "chunk_digest_failures": self.chunk_digest_failures,
            "restored_height": self.restored_height,
            "restore_seconds": self.restore_seconds,
            "deltas_applied": self.deltas_applied,
            "delta_entries_applied": self.delta_entries_applied,
            "delta_proof_failures": self.delta_proof_failures,
        }
