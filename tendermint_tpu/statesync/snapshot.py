"""Snapshot manifest, chunking, and the on-disk snapshot store.

A snapshot of height H is one deterministic byte payload (canonical JSON,
built by producer.py) split into fixed-size chunks. The manifest carries
the per-chunk RIPEMD-160 digests plus their simple-Merkle root
(merkle.simple.FlatTree — the same tree the part-set plane uses, so the
devd hash_stream kernel serves both), and the two hashes that tie the
snapshot to the light-verified header chain: the height-H header hash and
the post-H app hash (== header H+1's app_hash).

On disk (<db_dir>/snapshots/<height>/):
    manifest.json
    chunk-000000, chunk-000001, ...

Each chunk file is CRC-framed exactly like a WAL record
(libs/crc32c.py): magic ``TMSNAP1\\n`` then ``u32 crc32c(payload) |
u32 len(payload) | payload`` big-endian — a torn or bit-rotted chunk is
detected at load time and the whole snapshot is treated as damaged
(deleted, never served). The store is retention-bounded: `prune(keep)`
drops all but the newest `keep` snapshots.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import struct
import threading

from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.libs.crc32c import crc32c
from tendermint_tpu.merkle.simple import FlatTree

logger = logging.getLogger("statesync.snapshot")

FORMAT = 2
KIND_FULL = "full"
KIND_DELTA = "delta"
# a delta chain longer than this is garbage (the producer's full_every
# knob clamps far below); bounds the reactor's base-manifest recursion
MAX_DELTA_CHAIN = 32
CHUNK_MAGIC = b"TMSNAP1\n"
_FRAME = struct.Struct(">II")  # crc32c(payload), len(payload)
MANIFEST_FILE = "manifest.json"
# a chunk is bounded by the manifest's chunk_size; this is the absolute
# decode-time ceiling against garbage manifests/files. It must also FIT
# the wire: a chunk rides hex-encoded inside a JSON chunk_response, so
# the reactor's recv_message_capacity must cover 2x this plus framing —
# raise them together (reactor.get_channels notes the arithmetic)
MAX_CHUNK_BYTES = 4 * 1024 * 1024


class SnapshotError(Exception):
    pass


def chunk_payload(payload: bytes, chunk_size: int) -> list[bytes]:
    """Fixed-size split; at least one (possibly empty) chunk so a
    zero-byte payload still has a well-formed manifest."""
    if chunk_size <= 0:
        raise ValueError(f"chunk_size must be positive, got {chunk_size}")
    n = max((len(payload) + chunk_size - 1) // chunk_size, 1)
    return [payload[i * chunk_size : (i + 1) * chunk_size] for i in range(n)]


def chunk_digests_root(digests: list[bytes]) -> bytes:
    """Merkle root over the chunk digests via the flat builder — the one
    hash the manifest pins the whole chunk list to."""
    return FlatTree.from_leaf_digests(list(digests)).root()


class Manifest:
    """The snapshot's table of contents. `chunk_digests[i]` is the raw
    ripemd160 of chunk i's payload (the Part.Hash convention — NOT
    length-prefixed), `root` their simple-Merkle root.

    Format 2 (round 13): the node-local SEEN commit is carried HERE, as
    a sidecar the digested payload never includes — replica payloads
    (and so manifest roots) are byte-identical even when replicas saw
    different precommit subsets (the ROADMAP determinism item; the
    commit is re-verified at restore exactly as before, any +2/3 seen
    commit passes). Format 2 also adds `kind`: "full" manifests chunk
    one canonical payload by fixed size; "delta" manifests carry one
    host chunk plus self-verifying changed-entry chunks against
    `base_height`'s snapshot (docs/state-tree.md). Format 1 manifests
    (pre-round-13 homes) still decode and restore."""

    def __init__(
        self,
        height: int,
        chain_id: str,
        chunk_size: int,
        total_bytes: int,
        chunk_digests: list[bytes],
        header_hash: bytes,
        app_hash: bytes,
        format_: int = FORMAT,
        kind: str = KIND_FULL,
        base_height: int = 0,
        seen_commit: dict | None = None,
    ):
        self.format = format_
        self.height = height
        self.chain_id = chain_id
        self.chunk_size = chunk_size
        self.total_bytes = total_bytes
        self.chunk_digests = chunk_digests
        self.header_hash = header_hash
        self.app_hash = app_hash
        self.kind = kind
        self.base_height = base_height
        self.seen_commit = seen_commit  # JSON form (types.block.Commit)
        self.root = chunk_digests_root(chunk_digests)

    @property
    def chunks(self) -> int:
        return len(self.chunk_digests)

    def to_json(self) -> dict:
        out = {
            "format": self.format,
            "height": self.height,
            "chain_id": self.chain_id,
            "chunks": self.chunks,
            "chunk_size": self.chunk_size,
            "total_bytes": self.total_bytes,
            "chunk_digests": [d.hex().upper() for d in self.chunk_digests],
            "root": self.root.hex().upper(),
            "header_hash": self.header_hash.hex().upper(),
            "app_hash": self.app_hash.hex().upper(),
        }
        if self.format >= 2:
            out["kind"] = self.kind
            if self.kind == KIND_DELTA:
                out["base_height"] = self.base_height
            if self.seen_commit is not None:
                out["seen_commit"] = self.seen_commit
        return out

    def lite(self) -> dict:
        """The discovery form gossiped in snapshots_response / served by
        the RPC route — enough to pick a snapshot, not to verify one."""
        out = {
            "format": self.format,
            "height": self.height,
            "chain_id": self.chain_id,
            "chunks": self.chunks,
            "total_bytes": self.total_bytes,
            "root": self.root.hex().upper(),
            "header_hash": self.header_hash.hex().upper(),
            "kind": self.kind,
        }
        if self.kind == KIND_DELTA:
            out["base_height"] = self.base_height
        return out

    @classmethod
    def from_json(cls, obj) -> "Manifest":
        """Decode an UNTRUSTED manifest (it arrives over p2p). Every
        violation raises ValueError, the reactor's peer-error alphabet."""
        from tendermint_tpu.codec import jsonval as jv

        if not isinstance(obj, dict):
            raise ValueError("manifest must be an object")
        fmt = jv.int_field(obj, "format", 1, 1 << 16)
        height = jv.int_field(obj, "height", 1, jv.MAX_HEIGHT)
        chain_id = obj.get("chain_id")
        if not isinstance(chain_id, str) or len(chain_id) > 256:
            raise ValueError("bad manifest chain_id")
        chunk_size = jv.int_field(obj, "chunk_size", 1, MAX_CHUNK_BYTES)
        total_bytes = jv.int_field(obj, "total_bytes", 0, 1 << 40)
        raw = obj.get("chunk_digests")
        # 2^18 chunks at the 64 KiB default = a 16 GiB snapshot (1 TiB at
        # the 4 MiB ceiling); anything wider is garbage, not state — and
        # the digest list must fit a manifest_response inside the
        # reactor's recv_message_capacity
        if not isinstance(raw, list) or not 1 <= len(raw) <= (1 << 18) or any(
            not isinstance(d, str) or len(d) != 40 for d in raw
        ):
            raise ValueError("bad manifest chunk_digests")
        kind = obj.get("kind", KIND_FULL) if fmt >= 2 else KIND_FULL
        if kind not in (KIND_FULL, KIND_DELTA):
            raise ValueError(f"bad manifest kind {kind!r}")
        base_height = 0
        if kind == KIND_DELTA:
            base_height = jv.int_field(obj, "base_height", 1, jv.MAX_HEIGHT)
            if base_height >= height:
                raise ValueError("delta base_height must be below height")
        seen_commit = None
        if fmt >= 2 and "seen_commit" in obj:
            seen_commit = obj["seen_commit"]
            # validate NOW (it arrives over p2p); keep the JSON form —
            # restore re-parses and signature-verifies it. Polymorphic:
            # post-upgrade snapshots carry an AggregateCommit here
            # (docs/upgrade.md), dispatched on the "s_agg" key
            from tendermint_tpu.types.agg_commit import commit_from_json

            commit_from_json(jv.dict_field(obj, "seen_commit"))
        m = cls(
            height=height,
            chain_id=chain_id,
            chunk_size=chunk_size,
            total_bytes=total_bytes,
            chunk_digests=[bytes.fromhex(d) for d in raw],
            header_hash=jv.hex_field(obj, "header_hash", max_bytes=20),
            app_hash=jv.hex_field(obj, "app_hash", max_bytes=64),
            format_=fmt,
            kind=kind,
            base_height=base_height,
            seen_commit=seen_commit,
        )
        # full snapshots: total_bytes must agree with the chunk count —
        # exactly the last chunk may run short (chunk_payload's
        # fixed-size split, min 1). Delta chunks are semantic units
        # (host section + entry groups), not fixed-size slices; each is
        # still bounded by MAX_CHUNK_BYTES at every decode site.
        if m.kind == KIND_FULL and not (
            (m.chunks - 1) * m.chunk_size
            < max(m.total_bytes, 1)
            <= m.chunks * m.chunk_size
        ):
            raise ValueError("manifest total_bytes does not fit its chunk count")
        claimed_root = jv.hex_field(obj, "root", max_bytes=20)
        # the root must MATCH the digest list — a manifest whose root and
        # digests disagree can never verify, reject it at decode time
        if claimed_root != m.root:
            raise ValueError("manifest root does not match chunk digests")
        if len(m.header_hash) != 20:
            raise ValueError("bad manifest header_hash")
        return m


def frame_chunk(payload: bytes) -> bytes:
    return CHUNK_MAGIC + _FRAME.pack(crc32c(payload), len(payload)) + payload


def unframe_chunk(buf: bytes) -> bytes:
    """Inverse of frame_chunk; raises SnapshotError on any damage —
    wrong magic, bad length, trailing garbage, or CRC mismatch."""
    if not buf.startswith(CHUNK_MAGIC):
        raise SnapshotError("bad chunk magic")
    off = len(CHUNK_MAGIC)
    if len(buf) < off + _FRAME.size:
        raise SnapshotError("truncated chunk frame")
    crc, length = _FRAME.unpack_from(buf, off)
    if length > MAX_CHUNK_BYTES or len(buf) != off + _FRAME.size + length:
        raise SnapshotError("chunk length mismatch")
    payload = buf[off + _FRAME.size :]
    if crc32c(payload) != crc:
        raise SnapshotError("chunk crc mismatch")
    return payload


def chunk_digest(payload: bytes) -> bytes:
    return ripemd160(payload)


class SnapshotStore:
    """Retention-bounded directory of snapshots. Publication is atomic at
    directory granularity: a snapshot is assembled under a `.tmp` name
    and os.replace'd into place, so readers never see a half-written
    snapshot under its final name."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir
        self._mtx = threading.Lock()
        # parsed-manifest cache: from_json re-Merkles the whole digest
        # list, and the serving paths (snapshots_request, the RPC route)
        # are remotely triggerable — re-parsing trusted local files per
        # request would let any peer burn CPU with one-line messages
        self._manifest_cache: dict[int, Manifest] = {}
        os.makedirs(base_dir, exist_ok=True)
        # gauges (exported as statesync_* via the metrics RPC)
        self.chunks_served = 0
        self.load_failures = 0

    # -- paths -------------------------------------------------------------

    def _dir(self, height: int) -> str:
        return os.path.join(self.base_dir, f"{height:010d}")

    @staticmethod
    def chunk_name(index: int) -> str:
        return f"chunk-{index:06d}"

    # -- writing -----------------------------------------------------------

    def save(self, manifest: Manifest, chunks: list[bytes]) -> str:
        if len(chunks) != manifest.chunks:
            raise SnapshotError(
                f"{len(chunks)} chunks for a {manifest.chunks}-chunk manifest"
            )
        final = self._dir(manifest.height)
        tmp = final + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        for i, payload in enumerate(chunks):
            with open(os.path.join(tmp, self.chunk_name(i)), "wb") as f:
                f.write(frame_chunk(payload))
        # manifest last: its presence is what marks the dir complete
        with open(os.path.join(tmp, MANIFEST_FILE), "w") as f:
            json.dump(manifest.to_json(), f, sort_keys=True)
        with self._mtx:
            if os.path.isdir(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
            # deliberately NOT cached here: the first load after a save
            # parses the published file, so on-disk damage is still
            # detected once per process (the load-time contract tests
            # rely on); only load-verified manifests enter the cache
            self._manifest_cache.pop(manifest.height, None)
        return final

    def delete(self, height: int) -> None:
        with self._mtx:
            self._manifest_cache.pop(height, None)
            d = self._dir(height)
            if os.path.isdir(d):
                shutil.rmtree(d)

    def prune(self, keep_recent: int) -> list[int]:
        """Drop all but the newest `keep_recent` snapshots; returns the
        pruned heights."""
        pruned = []
        if keep_recent < 1:
            keep_recent = 1
        for h in self.heights()[:-keep_recent]:
            self.delete(h)
            pruned.append(h)
        return pruned

    # -- reading -----------------------------------------------------------

    def heights(self) -> list[int]:
        """Published snapshot heights, ascending (dirs with a manifest)."""
        out = []
        try:
            names = os.listdir(self.base_dir)
        except FileNotFoundError:
            return []
        for name in names:
            if name.isdigit() and os.path.exists(
                os.path.join(self.base_dir, name, MANIFEST_FILE)
            ):
                out.append(int(name))
        return sorted(out)

    def load_manifest(self, height: int) -> Manifest | None:
        with self._mtx:
            cached = self._manifest_cache.get(height)
        if cached is not None:
            return cached
        path = os.path.join(self._dir(height), MANIFEST_FILE)
        try:
            with open(path) as f:
                m = Manifest.from_json(json.load(f))
        except FileNotFoundError:
            return None
        except (ValueError, OSError) as exc:
            self.load_failures += 1
            logger.warning("damaged manifest at height %d: %s", height, exc)
            return None
        with self._mtx:
            self._manifest_cache[height] = m
        return m

    def load_chunk(self, height: int, index: int) -> bytes | None:
        """Chunk payload, CRC-verified. None when absent; raises
        SnapshotError on damage — the serving reactor then drops the
        whole snapshot rather than feed a peer bytes it KNOWS are bad."""
        path = os.path.join(self._dir(height), self.chunk_name(index))
        try:
            with open(path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return None
        payload = unframe_chunk(buf)
        self.chunks_served += 1
        return payload

    def stats(self) -> dict:
        heights = self.heights()
        return {
            "snapshots": len(heights),
            "last_height": heights[-1] if heights else 0,
            "chunks_served": self.chunks_served,
            "load_failures": self.load_failures,
        }
