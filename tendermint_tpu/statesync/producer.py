"""Snapshot producer: export a deterministic snapshot of (app state +
state + block-store tail) at configured height intervals.

Runs SYNCHRONOUSLY on the post-apply hook (consensus finalize_commit /
fast-sync _try_sync), between one block's Commit and the next height's
first DeliverTx — the only point where app.snapshot() is guaranteed to
observe exactly height H. The in-process apps serialize in microseconds
to low milliseconds at test scales; a deployment whose app state is
huge raises snapshot_interval, it does not move the hook.

Round 14 (pipelined execution, docs/execution-pipeline.md): under the
pipelined finalize the hook fires from the APPLY EXECUTOR thread, not
the consensus receive routine — and the quiesce guarantee holds by the
executor's ordering: apply(H+1), the only source of the next DeliverTx,
is queued behind this hook on the same single worker. Executor-thread
audit: `state` is the executor-local post-H copy; the block store is
lock-protected and block H was saved BEFORE the apply was submitted (the
stage-1 ordering invariant), so host_sections can always serve H; the
gateway hasher and the SnapshotStore take their own locks. Concurrent
mempool CheckTx against app.snapshot() predates the pipeline (CheckTx
never ran on the consensus thread either) and is read-only in the
kvstore family. The NEVER-RAISES contract of maybe_snapshot is what
keeps a producer failure from wedging the executor — and therefore the
join — regression-tested in tests/test_pipeline.py.

Round 13 (format 2, docs/state-tree.md):

- The node-local SEEN commit moved OUT of the digested payload into the
  manifest sidecar, so replica payloads — and manifest ROOTS — are
  byte-identical even when replicas saw different precommit subsets
  (deterministic snapshot roots, the ROADMAP item PR 12's real-TCP nets
  opened).
- Apps backed by the authenticated state tree emit DELTA snapshots
  between full ones (`full_every` controls the cadence): chunk 0 is the
  host section (state/validators_info/block H), chunks 1.. carry the
  changed entries SINCE the previous snapshot, each entry shipping with
  its membership (upsert) or absence (delete) proof against the NEW
  app hash — a restoring node verifies every chunk against consensus
  before anything applies, and resumes a crashed chain trustlessly.
  Any precondition miss (no tree, pruned base version, base snapshot
  gone, chain length at full_every) falls back to a full snapshot.

Full payload (format 2, canonical JSON, sort_keys — byte-identical
across replicas at the same height):

    {
      "format": 2, "kind": "full", "chain_id": ..., "height": H,
      "app_state": hex(app.snapshot()),
      "state": State.to_json() AFTER applying H,
      "validators_info": {height: saveValidatorsInfo record, ...},
      "block": {"meta": ..., "parts": [...]}      # NO seen commit here
    }

The block section carries height H itself (meta + parts; the seen
commit rides the manifest) so a restored node can serve /block and
/commit at its base height and seed a BlockStore whose head is real,
not a phantom watermark.
"""

from __future__ import annotations

import json
import logging
import time

from tendermint_tpu.libs.envknob import env_number
from tendermint_tpu.statesync.snapshot import (
    KIND_DELTA,
    MAX_CHUNK_BYTES,
    Manifest,
    SnapshotStore,
    chunk_payload,
)

logger = logging.getLogger("statesync.producer")

DEFAULT_CHUNK_SIZE = 64 * 1024
DEFAULT_FULL_EVERY = 4


def validators_info_records(state) -> dict:
    """The state-DB validator-history records a restored node needs so
    load_validators resolves for every height it can be asked about
    (>= the snapshot height): a self-contained full set at H (the set
    that SIGNED H), the current set at its last-changed height, and the
    pointer record for H+1 (state/state.py saveValidatorsInfo shape)."""
    h = state.last_block_height
    lhc = max(state.last_height_validators_changed, 1)
    records: dict = {}
    # the full current set lives where the last-changed pointer lands
    records[str(lhc)] = {
        "last_height_changed": lhc,
        "validator_set": state.validators.to_json(),
    }
    # height H resolves directly to the set that signed it (when lhc == H
    # the set changed entering H, so validators == last_validators
    # membership-wise and either record serves)
    records.setdefault(
        str(h),
        {"last_height_changed": h, "validator_set": state.last_validators.to_json()},
    )
    if str(h + 1) not in records:
        records[str(h + 1)] = {"last_height_changed": lhc}
    return records


def host_sections(state, block_store) -> tuple[dict, dict]:
    """(sections, seen_commit_json) for a snapshot at
    state.last_block_height: the embedded state, validator-history
    records, and block H (meta + parts). The seen commit is returned
    SEPARATELY — format 2 carries it in the manifest, outside the
    digested bytes, so replica roots don't diverge on per-node precommit
    subsets. Raises ValueError when the block store cannot serve the
    height (e.g. it was just pruned past it)."""
    h = state.last_block_height
    meta = block_store.load_block_meta(h)
    seen = block_store.load_seen_commit(h)
    if meta is None or seen is None:
        raise ValueError(f"block store cannot serve height {h} for snapshot")
    parts = []
    for i in range(meta.block_id.parts_header.total):
        part = block_store.load_block_part(h, i)
        if part is None:
            raise ValueError(f"missing part {i} of block {h}")
        parts.append(part.to_json())
    sections = {
        "state": state.to_json(),
        "validators_info": validators_info_records(state),
        "block": {"meta": meta.to_json(), "parts": parts},
    }
    return sections, seen.to_json()


def build_payload(state, app_state: bytes, block_store) -> tuple[dict, dict]:
    """(full-snapshot payload object, seen_commit_json) for a snapshot
    at state.last_block_height."""
    sections, seen_json = host_sections(state, block_store)
    obj = {
        "format": 2,
        "kind": "full",
        "chain_id": state.chain_id,
        "height": state.last_block_height,
        "app_state": app_state.hex(),
        **sections,
    }
    return obj, seen_json


def encode_payload(obj: dict) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


class SnapshotProducer:
    def __init__(
        self,
        store: SnapshotStore,
        app,
        block_store,
        hasher=None,
        interval: int = 0,
        keep_recent: int = 2,
        chunk_size: int | None = None,
        full_every: int | None = None,
    ):
        self.store = store
        self.app = app
        self.block_store = block_store
        self.hasher = hasher
        self.interval = interval
        if full_every is None:
            full_every = int(
                env_number(
                    "TENDERMINT_STATESYNC_FULL_EVERY", DEFAULT_FULL_EVERY,
                    cast=int,
                )
            )
        self.full_every = max(int(full_every), 1)
        from tendermint_tpu.statesync.snapshot import MAX_DELTA_CHAIN

        if self.full_every > MAX_DELTA_CHAIN:
            # every restorer hard-rejects chains past MAX_DELTA_CHAIN;
            # producing longer ones would make the freshest snapshots
            # unrestorable by construction
            logger.warning(
                "snapshot_full_every %d > restorable chain bound %d; clamping",
                self.full_every, MAX_DELTA_CHAIN,
            )
            self.full_every = MAX_DELTA_CHAIN
        # delta snapshots need the app's authenticated state tree (diff
        # + proofs); apps without one always produce full snapshots
        tree = getattr(app, "tree", None)
        self.tree = tree if hasattr(tree, "diff") else None
        if self.tree is not None and self.full_every > 1 and interval > 0:
            # the delta base is `interval` heights back: the tree must
            # retain at least that many versions or every delta falls
            # back to full on a pruned base
            self.tree.keep_recent = max(self.tree.keep_recent, interval + 2)
        if self.full_every > 1:
            # a delta chain is only servable while its full base (and
            # every intermediate delta) survives retention
            keep_recent = max(keep_recent, self.full_every + 1)
        self.keep_recent = keep_recent
        if chunk_size is None:
            chunk_size = int(
                env_number(
                    "TENDERMINT_STATESYNC_CHUNK_BYTES", DEFAULT_CHUNK_SIZE, cast=int
                )
            )
        if chunk_size < 1024:
            logger.warning(
                "statesync chunk size %d B < 1 KiB floor; clamping", chunk_size
            )
            chunk_size = 1024
        if chunk_size > MAX_CHUNK_BYTES:
            # a wider chunk would pass local framing but every peer's
            # manifest/chunk decode (and the wire capacity) rejects it —
            # clamp so the snapshots produced are actually servable
            logger.warning(
                "statesync chunk size %d B > %d ceiling; clamping",
                chunk_size, MAX_CHUNK_BYTES,
            )
            chunk_size = MAX_CHUNK_BYTES
        self.chunk_size = chunk_size
        # gauges (statesync_* in the metrics RPC)
        self.snapshots_taken = 0
        self.snapshot_failures = 0
        self.deltas_taken = 0
        self.delta_fallbacks = 0
        self.last_snapshot_height = 0
        self.last_snapshot_seconds = 0.0
        self.last_snapshot_bytes = 0

    def _chunk_digests(self, chunks: list[bytes]) -> list[bytes]:
        """Per-chunk RIPEMD-160 through the hashing gateway when one is
        wired (streamed devd plane / AVX batch / CPU fallback — the same
        routing ladder the part plane rides), plain CPU otherwise."""
        if self.hasher is not None:
            return self.hasher.part_leaf_hashes(chunks)
        from tendermint_tpu.statesync.snapshot import chunk_digest

        return [chunk_digest(c) for c in chunks]

    def maybe_snapshot(self, state, block=None) -> int | None:
        """The post-apply hook: snapshot when the just-applied height
        lands on the interval. NEVER raises — a snapshot failure must
        not take down the consensus or fast-sync path that called it."""
        h = state.last_block_height
        if self.interval <= 0 or h == 0 or h % self.interval != 0:
            return None
        try:
            return self.snapshot(state)
        except Exception:  # noqa: BLE001 — producer is best-effort
            self.snapshot_failures += 1
            logger.exception("snapshot at height %d failed", h)
            return None

    # -- delta production ----------------------------------------------------

    def _delta_base(self, h: int) -> Manifest | None:
        """The previous snapshot's manifest, iff a delta on top of it is
        allowed and possible: the app has a tree retaining both
        versions whose committed roots line up, and the chain of
        consecutive deltas stays under full_every."""
        if self.tree is None or self.full_every <= 1:
            return None
        heights = [x for x in self.store.heights() if x < h]
        if not heights:
            return None
        base = self.store.load_manifest(heights[-1])
        if base is None:
            return None
        # consecutive deltas ending at the base; a chain of
        # full_every - 1 deltas means this one must be full
        chain = 0
        walk = base
        while walk is not None and walk.kind == KIND_DELTA and chain < self.full_every:
            chain += 1
            walk = self.store.load_manifest(walk.base_height)
        if walk is None or chain >= self.full_every - 1:
            return None
        if not (self.tree.has_version(base.height) and self.tree.has_version(h)):
            return None
        try:
            if self.tree.root_hash(base.height) != base.app_hash:
                # the stored base predates this app instance (restart
                # rebuilt the tree with only the current version)
                return None
        except Exception:  # noqa: BLE001 — any doubt means full
            return None
        return base

    def _build_delta_chunks(
        self, state, base: Manifest
    ) -> tuple[list[bytes], dict] | None:
        """(delta chunk list, seen_commit_json) — host section first,
        then proof-carrying entry groups — or None when the diff is
        unavailable (pruned journal -> fall back to full)."""
        from tendermint_tpu.statetree.tree import TreeError

        h = state.last_block_height
        try:
            upserts, deletes = self.tree.diff(base.height, h)
        except TreeError as exc:
            logger.info("delta diff %d..%d unavailable (%s)", base.height, h, exc)
            return None
        sections, seen_json = host_sections(state, self.block_store)
        aux = None
        snapshot_aux = getattr(self.app, "snapshot_aux", None)
        if snapshot_aux is not None:
            aux = snapshot_aux()
        host = {
            "format": 2,
            "kind": "delta",
            "section": "host",
            "chain_id": state.chain_id,
            "height": h,
            "base_height": base.height,
            "app_aux": aux,
            **sections,
        }
        chunks = [encode_payload(host)]
        # entry groups: each entry ships with its proof against the NEW
        # root. Proof STEPS dedupe into a per-chunk table (the upper
        # tree levels are shared by every path in the chunk — inlining
        # them per entry made small deltas LARGER than full snapshots);
        # an entry's proof is its bottom-up list of step indices.
        group: dict = {"section": "delta", "steps": [], "sets": [], "dels": []}
        step_index: dict[str, int] = {}
        group_bytes = 64

        def flush():
            nonlocal group, step_index, group_bytes
            if group["sets"] or group["dels"]:
                chunks.append(encode_payload(group))
            group = {"section": "delta", "steps": [], "sets": [], "dels": []}
            step_index = {}
            group_bytes = 64

        def proof_refs(key) -> list[int]:
            nonlocal group_bytes
            refs = []
            for step in self.tree.prove(key, h).steps:
                sj = step.to_json()
                sk = "|".join(sj)
                idx = step_index.get(sk)
                if idx is None:
                    idx = len(group["steps"])
                    group["steps"].append(sj)
                    step_index[sk] = idx
                    group_bytes += len(sk) + 16
                refs.append(idx)
            return refs

        for key in sorted(upserts):
            entry = [key.hex().upper(), upserts[key].hex().upper(), proof_refs(key)]
            group["sets"].append(entry)
            group_bytes += len(entry[0]) + len(entry[1]) + 6 * len(entry[2])
            if group_bytes >= self.chunk_size:
                flush()
        for key in deletes:
            entry = [key.hex().upper(), proof_refs(key)]
            group["dels"].append(entry)
            group_bytes += len(entry[0]) + 6 * len(entry[1])
            if group_bytes >= self.chunk_size:
                flush()
        flush()
        if any(len(c) > MAX_CHUNK_BYTES for c in chunks):
            # a single oversized entry (or host section) cannot ride the
            # wire; a full snapshot chunks by size and always can
            logger.warning("delta chunk exceeds wire ceiling; going full")
            return None
        return chunks, seen_json

    # -- the whole path ------------------------------------------------------

    def snapshot(self, state) -> int:
        """Export a snapshot at state.last_block_height (delta against
        the previous one when possible, full otherwise). Returns the
        height. Raises on apps without snapshot support or a block store
        that cannot serve the height."""
        t0 = time.perf_counter()
        h = state.last_block_height
        base = self._delta_base(h)
        built = None
        if base is not None:
            built = self._build_delta_chunks(state, base)
            if built is None:
                self.delta_fallbacks += 1
        if built is not None:
            chunks, seen_json = built
            manifest = Manifest(
                height=h,
                chain_id=state.chain_id,
                chunk_size=self.chunk_size,
                total_bytes=sum(len(c) for c in chunks),
                chunk_digests=self._chunk_digests(chunks),
                header_hash=state.last_block_id.hash,
                app_hash=state.app_hash,
                kind=KIND_DELTA,
                base_height=base.height,
                seen_commit=seen_json,
            )
            self.deltas_taken += 1
            kind = "delta"
        else:
            app_state = self.app.snapshot()
            if app_state is None:
                raise ValueError(
                    f"{type(self.app).__name__} does not support snapshots"
                )
            obj, seen_json = build_payload(state, app_state, self.block_store)
            payload = encode_payload(obj)
            chunks = chunk_payload(payload, self.chunk_size)
            manifest = Manifest(
                height=h,
                chain_id=state.chain_id,
                chunk_size=self.chunk_size,
                total_bytes=len(payload),
                chunk_digests=self._chunk_digests(chunks),
                header_hash=state.last_block_id.hash,
                app_hash=state.app_hash,
                seen_commit=seen_json,
            )
            kind = "full"
        self.store.save(manifest, chunks)
        self.store.prune(self.keep_recent)
        self.snapshots_taken += 1
        self.last_snapshot_height = h
        self.last_snapshot_bytes = manifest.total_bytes
        self.last_snapshot_seconds = round(time.perf_counter() - t0, 4)
        logger.info(
            "%s snapshot at height %d: %d chunk(s), %d bytes, root %s (%.1f ms)",
            kind, h, manifest.chunks, manifest.total_bytes,
            manifest.root.hex()[:12], self.last_snapshot_seconds * 1000,
        )
        return h

    def stats(self) -> dict:
        """Producer-side gauges ONLY. The shared SnapshotStore's gauges
        are exported by the reactor's stats() (the reactor always exists
        on a node; round 11 removed the duplicate store fold-in here so
        the statesync_* wiring in node/telemetry.py is collision-free —
        no more setdefault ordering deciding which copy wins)."""
        return {
            "interval": self.interval,
            "snapshots_taken": self.snapshots_taken,
            "snapshot_failures": self.snapshot_failures,
            "deltas_taken": self.deltas_taken,
            "delta_fallbacks": self.delta_fallbacks,
            "last_snapshot_height": self.last_snapshot_height,
            "last_snapshot_seconds": self.last_snapshot_seconds,
            "last_snapshot_bytes": self.last_snapshot_bytes,
        }
