"""Snapshot producer: export a deterministic snapshot of (app state +
state + block-store tail) at configured height intervals.

Runs SYNCHRONOUSLY on the post-apply hook (consensus finalize_commit /
fast-sync _try_sync), between one block's Commit and the next height's
first DeliverTx — the only point where app.snapshot() is guaranteed to
observe exactly height H. The in-process apps serialize in microseconds
to low milliseconds at test scales; a deployment whose app state is
huge raises snapshot_interval, it does not move the hook.

Payload (format 1, canonical JSON, sort_keys — byte-identical across
replicas at the same height):

    {
      "format": 1, "chain_id": ..., "height": H,
      "app_state": hex(app.snapshot()),
      "state": State.to_json() AFTER applying H,
      "validators_info": {height: saveValidatorsInfo record, ...},
      "block": {"meta": ..., "seen_commit": ..., "parts": [...]}
    }

The block section carries height H itself (meta + parts + seen commit)
so a restored node can serve /block and /commit at its base height and
seed a BlockStore whose head is real, not a phantom watermark.
"""

from __future__ import annotations

import logging
import time

from tendermint_tpu.libs.envknob import env_number
from tendermint_tpu.statesync.snapshot import (
    MAX_CHUNK_BYTES,
    Manifest,
    SnapshotStore,
    chunk_payload,
)

logger = logging.getLogger("statesync.producer")

DEFAULT_CHUNK_SIZE = 64 * 1024


def validators_info_records(state) -> dict:
    """The state-DB validator-history records a restored node needs so
    load_validators resolves for every height it can be asked about
    (>= the snapshot height): a self-contained full set at H (the set
    that SIGNED H), the current set at its last-changed height, and the
    pointer record for H+1 (state/state.py saveValidatorsInfo shape)."""
    h = state.last_block_height
    lhc = max(state.last_height_validators_changed, 1)
    records: dict = {}
    # the full current set lives where the last-changed pointer lands
    records[str(lhc)] = {
        "last_height_changed": lhc,
        "validator_set": state.validators.to_json(),
    }
    # height H resolves directly to the set that signed it (when lhc == H
    # the set changed entering H, so validators == last_validators
    # membership-wise and either record serves)
    records.setdefault(
        str(h),
        {"last_height_changed": h, "validator_set": state.last_validators.to_json()},
    )
    if str(h + 1) not in records:
        records[str(h + 1)] = {"last_height_changed": lhc}
    return records


def build_payload(state, app_state: bytes, block_store) -> dict:
    """The JSON payload object for a snapshot at state.last_block_height.
    Raises SnapshotError-ish ValueError when the block store cannot serve
    the height (e.g. it was just pruned past it)."""
    h = state.last_block_height
    meta = block_store.load_block_meta(h)
    seen = block_store.load_seen_commit(h)
    if meta is None or seen is None:
        raise ValueError(f"block store cannot serve height {h} for snapshot")
    parts = []
    for i in range(meta.block_id.parts_header.total):
        part = block_store.load_block_part(h, i)
        if part is None:
            raise ValueError(f"missing part {i} of block {h}")
        parts.append(part.to_json())
    return {
        "format": 1,
        "chain_id": state.chain_id,
        "height": h,
        "app_state": app_state.hex(),
        "state": state.to_json(),
        "validators_info": validators_info_records(state),
        "block": {
            "meta": meta.to_json(),
            "seen_commit": seen.to_json(),
            "parts": parts,
        },
    }


def encode_payload(obj: dict) -> bytes:
    import json

    return json.dumps(obj, sort_keys=True).encode()


class SnapshotProducer:
    def __init__(
        self,
        store: SnapshotStore,
        app,
        block_store,
        hasher=None,
        interval: int = 0,
        keep_recent: int = 2,
        chunk_size: int | None = None,
    ):
        self.store = store
        self.app = app
        self.block_store = block_store
        self.hasher = hasher
        self.interval = interval
        self.keep_recent = keep_recent
        if chunk_size is None:
            chunk_size = int(
                env_number(
                    "TENDERMINT_STATESYNC_CHUNK_BYTES", DEFAULT_CHUNK_SIZE, cast=int
                )
            )
        if chunk_size < 1024:
            logger.warning(
                "statesync chunk size %d B < 1 KiB floor; clamping", chunk_size
            )
            chunk_size = 1024
        if chunk_size > MAX_CHUNK_BYTES:
            # a wider chunk would pass local framing but every peer's
            # manifest/chunk decode (and the wire capacity) rejects it —
            # clamp so the snapshots produced are actually servable
            logger.warning(
                "statesync chunk size %d B > %d ceiling; clamping",
                chunk_size, MAX_CHUNK_BYTES,
            )
            chunk_size = MAX_CHUNK_BYTES
        self.chunk_size = chunk_size
        # gauges (statesync_* in the metrics RPC)
        self.snapshots_taken = 0
        self.snapshot_failures = 0
        self.last_snapshot_height = 0
        self.last_snapshot_seconds = 0.0

    def _chunk_digests(self, chunks: list[bytes]) -> list[bytes]:
        """Per-chunk RIPEMD-160 through the hashing gateway when one is
        wired (streamed devd plane / AVX batch / CPU fallback — the same
        routing ladder the part plane rides), plain CPU otherwise."""
        if self.hasher is not None:
            return self.hasher.part_leaf_hashes(chunks)
        from tendermint_tpu.statesync.snapshot import chunk_digest

        return [chunk_digest(c) for c in chunks]

    def maybe_snapshot(self, state, block=None) -> int | None:
        """The post-apply hook: snapshot when the just-applied height
        lands on the interval. NEVER raises — a snapshot failure must
        not take down the consensus or fast-sync path that called it."""
        h = state.last_block_height
        if self.interval <= 0 or h == 0 or h % self.interval != 0:
            return None
        try:
            return self.snapshot(state)
        except Exception:  # noqa: BLE001 — producer is best-effort
            self.snapshot_failures += 1
            logger.exception("snapshot at height %d failed", h)
            return None

    def snapshot(self, state) -> int:
        """Export a snapshot at state.last_block_height. Returns the
        height. Raises on apps without snapshot support or a block store
        that cannot serve the height."""
        t0 = time.perf_counter()
        h = state.last_block_height
        app_state = self.app.snapshot()
        if app_state is None:
            raise ValueError(f"{type(self.app).__name__} does not support snapshots")
        payload = encode_payload(build_payload(state, app_state, self.block_store))
        chunks = chunk_payload(payload, self.chunk_size)
        manifest = Manifest(
            height=h,
            chain_id=state.chain_id,
            chunk_size=self.chunk_size,
            total_bytes=len(payload),
            chunk_digests=self._chunk_digests(chunks),
            header_hash=state.last_block_id.hash,
            app_hash=state.app_hash,
        )
        self.store.save(manifest, chunks)
        self.store.prune(self.keep_recent)
        self.snapshots_taken += 1
        self.last_snapshot_height = h
        self.last_snapshot_seconds = round(time.perf_counter() - t0, 4)
        logger.info(
            "snapshot at height %d: %d chunk(s), %d bytes, root %s (%.1f ms)",
            h, manifest.chunks, len(payload), manifest.root.hex()[:12],
            self.last_snapshot_seconds * 1000,
        )
        return h

    def stats(self) -> dict:
        """Producer-side gauges ONLY. The shared SnapshotStore's gauges
        are exported by the reactor's stats() (the reactor always exists
        on a node; round 11 removed the duplicate store fold-in here so
        the statesync_* wiring in node/telemetry.py is collision-free —
        no more setdefault ordering deciding which copy wins)."""
        return {
            "interval": self.interval,
            "snapshots_taken": self.snapshots_taken,
            "snapshot_failures": self.snapshot_failures,
            "last_snapshot_height": self.last_snapshot_height,
            "last_snapshot_seconds": self.last_snapshot_seconds,
        }
