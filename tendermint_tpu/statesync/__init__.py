"""State-sync snapshot subsystem (round 10).

A fresh node used to have exactly one way in: fast-sync every block from
genesis and re-execute it — an O(chain-length) cold start. This package
gives it a second one: restore a chunked, Merkle-rooted snapshot of app
state + block-store tail taken at a recent height, verify it against the
light-client header chain, and fast-sync only the tail.

Layout:
- snapshot.py  — manifest + chunking + the CRC-framed on-disk store
- producer.py  — exports snapshots at configured height intervals
- restore.py   — verify (light client + batched chunk digests) and apply
- reactor.py   — the p2p serving/fetching reactor + restore driver
- devchain.py  — deterministic single-validator chain builder (tests,
                 benches, dev seeding)

docs/state-sync.md has the wire format, manifest layout, trust model and
failure modes.
"""

from tendermint_tpu.statesync.snapshot import (  # noqa: F401
    Manifest,
    SnapshotError,
    SnapshotStore,
    chunk_payload,
)
from tendermint_tpu.statesync.producer import SnapshotProducer  # noqa: F401
from tendermint_tpu.statesync.restore import Restorer, RestoreError  # noqa: F401
