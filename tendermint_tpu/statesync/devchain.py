"""Deterministic single-validator chain builder.

Builds a REAL chain — blocks made by Block.make_block, commits signed by
the validator's privkey, every block stored via BlockStore.save_block and
applied through state.execution.apply_block against a live ABCI app — at
direct-call speed, with none of the consensus round-trip latency. Used by
the statesync tests and benches (a 1k-block signedkv home builds in
seconds) and usable for seeding dev networks.

The resulting home is byte-indistinguishable from one a consensus node
committed: fast-sync serves and verifies it, snapshots taken from it
restore against its light headers.
"""

from __future__ import annotations

from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.crypto.keys import gen_priv_key_ed25519
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.state.execution import apply_block
from tendermint_tpu.state.state import State
from tendermint_tpu.types import (
    GenesisDoc,
    GenesisValidator,
    PrivValidatorFS,
    Vote,
)
from tendermint_tpu.types.block import Block, Commit, empty_commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.services import MockMempool
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT


class DevChain:
    """One validator, one app, one block store, one state — drive it
    forward a block at a time with `commit_block(txs)`."""

    def __init__(
        self,
        app,
        chain_id: str = "devchain",
        seed: bytes | None = None,
        block_store_db=None,
        state_db=None,
        hasher=None,
        verifier=None,
    ):
        self.app = app
        self.pv = PrivValidatorFS(
            gen_priv_key_ed25519(seed or b"devchain-validator"), None
        )
        self.genesis_doc = GenesisDoc(
            genesis_time_ns=1_700_000_000_000_000_000,
            chain_id=chain_id,
            validators=[GenesisValidator(self.pv.get_pub_key(), 10, "dev")],
        )
        self.block_store_db = block_store_db if block_store_db is not None else MemDB()
        self.state_db = state_db if state_db is not None else MemDB()
        self.block_store = BlockStore(self.block_store_db)
        self.state = State.get_state(self.state_db, self.genesis_doc)
        self.hasher = hasher
        self.verifier = verifier
        self._last_seen_commit: Commit | None = None
        # state-tree-backed apps (round 13) batch commit hashing through
        # the same gateway the part plane uses, when one is wired
        app_tree = getattr(app, "tree", None)
        if hasher is not None and app_tree is not None:
            app_tree.hasher = hasher

        from tendermint_tpu.abci.client import LocalClient
        from tendermint_tpu.proxy.app_conn import AppConnConsensus
        import threading

        self._proxy = AppConnConsensus(LocalClient(app, threading.RLock()))

        # mirror the real node's genesis handshake: a fresh chain seeds
        # the app's InitChain with the genesis validator set (the
        # persistent kvstore's registry starts in sync with consensus —
        # the delta-snapshot aux cross-check depends on that)
        if (
            self.state.last_block_height == 0
            and app.info().last_block_height == 0
        ):
            from tendermint_tpu.abci.types import ABCIValidator

            app.init_chain([
                ABCIValidator(v.pub_key.to_json(), v.power)
                for v in self.genesis_doc.validators
            ])

    # -- block production --------------------------------------------------

    def _sign_commit(self, block: Block, parts_header) -> Commit:
        block_id = BlockID(block.hash(), parts_header)
        vote = Vote(
            validator_address=self.pv.get_address(),
            validator_index=0,
            height=block.header.height,
            round_=0,
            type_=VOTE_TYPE_PRECOMMIT,
            block_id=block_id,
        )
        return Commit(block_id, [self.pv.sign_vote(self.state.chain_id, vote)])

    def commit_block(self, txs: list[bytes] | None = None,
                     evidence=None) -> Block:
        """Make, store, and apply the next block; returns it. `evidence`
        embeds an EvidenceData section (round 12) — the devchain is how
        unit tests mint committed blocks that carry evidence."""
        height = self.state.last_block_height + 1
        last_commit = (
            empty_commit() if height == 1 else self._last_seen_commit
        )
        block, parts = Block.make_block(
            height=height,
            chain_id=self.state.chain_id,
            txs=list(txs or []),
            commit=last_commit,
            prev_block_id=self.state.last_block_id,
            val_hash=self.state.validators.hash(),
            app_hash=self.state.app_hash,
            part_size=self.state.params().block_gossip.block_part_size_bytes,
            time_ns=self.state.last_block_time_ns + 1_000_000_000,
            part_hasher=self.hasher.part_leaf_hashes if self.hasher else None,
            evidence=evidence,
        )
        seen_commit = self._sign_commit(block, parts.header())
        self.block_store.save_block(block, parts, seen_commit)
        apply_block(
            self.state,
            None,
            self._proxy,
            block,
            parts.header(),
            MockMempool(),
            batch_verifier=(
                self.verifier.commit_batch_verifier() if self.verifier else None
            ),
        )
        self._last_seen_commit = seen_commit
        return block

    def build(self, n_blocks: int, tx_fn=None) -> None:
        """Commit `n_blocks` blocks; `tx_fn(height) -> list[bytes]`
        supplies each block's txs."""
        for _ in range(n_blocks):
            h = self.state.last_block_height + 1
            self.commit_block(tx_fn(h) if tx_fn else None)

    # -- RPC-shaped serving (what a light client needs) --------------------

    def rpc_stub(self) -> "DevChainRPC":
        return DevChainRPC(self)


class DevChainRPC:
    """The commit/validators/status subset of the RPC surface, served
    straight off the DevChain's stores — a LightClient-compatible client
    for tests and benches (rpc/light.py only needs .commit/.validators)."""

    def __init__(self, chain: DevChain):
        self.chain = chain

    def commit(self, height):
        height = int(height)
        store = self.chain.block_store
        meta = store.load_block_meta(height)
        if meta is None:
            return {"header": None, "commit": None}
        if height == store.height():
            cmt = store.load_seen_commit(height)
            canonical = False
        else:
            cmt = store.load_block_commit(height)
            canonical = True
        return {
            "header": meta.header.to_json(),
            "commit": cmt.to_json() if cmt else None,
            "canonical_commit": canonical,
        }

    def validators(self, height=0):
        vs = self.chain.state.load_validators(int(height))
        return {"block_height": int(height), "validators": vs.to_json()}

    def status(self):
        return {"latest_block_height": self.chain.block_store.height()}

    def abci_query(self, data="", path="", height=0, prove=False):
        """The rpc/core abci_query shape, served straight off the app —
        what LightClient.verified_query drives in tests/benches."""
        res = self.chain.app.query(
            bytes.fromhex(data) if data else b"", path, int(height), bool(prove)
        )
        return {
            "response": {
                "code": res.code,
                "key": res.key.hex().upper(),
                "value": (res.value or b"").hex().upper(),
                "proof": (res.proof or b"").hex().upper(),
                "height": res.height,
                "log": res.log,
            }
        }


def build_kvstore_chain(n_blocks: int, txs_per_block: int = 2, **kw):
    """Convenience: a KVStore DevChain with deterministic txs."""
    from tendermint_tpu.abci.apps.kvstore import KVStoreApp

    chain = DevChain(KVStoreApp(), **kw)
    chain.build(
        n_blocks,
        tx_fn=lambda h: [
            b"k%d-%d=v%d" % (h, i, h) for i in range(txs_per_block)
        ],
    )
    return chain


def build_signedkv_chain(n_blocks: int, txs_per_block: int = 2, **kw):
    """A SignedKV DevChain: every tx carries a real Ed25519 envelope, so
    DeliverTx verifies signatures — the committee-verify workload the
    snapshot/restore bench compares against."""
    from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp, make_sig_tx

    signer = bytes(range(32))
    chain = DevChain(SignedKVStoreApp(), **kw)
    chain.build(
        n_blocks,
        tx_fn=lambda h: [
            make_sig_tx(signer, b"s%d-%d=v%d" % (h, i, h))
            for i in range(txs_per_block)
        ],
    )
    return chain
