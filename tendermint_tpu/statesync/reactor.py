"""State-sync p2p reactor: serve local snapshots to joining peers and
drive a restore from peers' snapshots, on channel 0x60 (beyond the
reference: v0.11 predates statesync; the offer/request/chunk shape
follows the later statesync reactor, JSON-framed like this codebase's
blockchain reactor).

Wire messages (every field is attacker input — any decode violation is a
peer error, never an exception escaping the p2p recv routine):

    {"type": "snapshots_request"}
    {"type": "snapshots_response", "snapshots": [manifest-lite, ...]}
    {"type": "manifest_request", "height": H}
    {"type": "manifest_response", "manifest": {...}} | {"type": "no_manifest", "height": H}
    {"type": "chunk_request", "height": H, "index": i}
    {"type": "chunk_response", "height": H, "index": i, "chunk": hex}
      | {"type": "no_chunk", "height": H, "index": i}

Restore driver (enabled nodes only): discover offers -> pick the highest
height -> light-verify the manifest (Restorer) -> download chunks in
windows, digest-verifying each window in ONE gateway batch; a chunk whose
digest mismatches bans the serving peer (stop_peer_for_error) and is
re-fetched from another -> Restorer.restore -> on_complete(state) hands
off to the fast-sync reactor for the tail. Downloads are resumable:
verified chunks persist CRC-framed under <snapshots>/restore-<height>/
and are reloaded (re-verified) after a restart. If no usable snapshot
appears within the fallback window, on_complete(None) lets the node fall
back to plain fast sync from genesis — statesync must never strand a
node that could have synced the slow way.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time

from tendermint_tpu.libs.envknob import env_number
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn import ChannelDescriptor
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.statesync.restore import (
    ManifestBindingError,
    RestoreError,
    SnapshotRejected,
    verify_chunk_batch,
)
from tendermint_tpu.statesync.snapshot import (
    KIND_DELTA,
    MAX_CHUNK_BYTES,
    MAX_DELTA_CHAIN,
    Manifest,
    SnapshotError,
    frame_chunk,
    unframe_chunk,
)

logger = logging.getLogger("statesync.reactor")

STATESYNC_CHANNEL = 0x60
MAX_OFFERED_SNAPSHOTS = 16  # per snapshots_response, decode-time cap


def _enc(obj: dict) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


class StateSyncReactor(Reactor, BaseService):
    def __init__(
        self,
        snapshot_store,
        restorer=None,
        enabled: bool = False,
        on_complete=None,
        chunk_window: int | None = None,
        chunk_timeout_s: float | None = None,
        chunk_retries: int | None = None,
        discovery_s: float | None = None,
        fallback_s: float | None = None,
    ):
        BaseService.__init__(self, name="statesync.reactor")
        self.store = snapshot_store
        self.restorer = restorer
        self.enabled = enabled and restorer is not None
        self.on_complete = on_complete
        # all statesync knobs parse via the shared defensive helper: a
        # typo'd env var warns and uses the default, never kills startup
        self.chunk_window = chunk_window if chunk_window is not None else int(
            env_number("TENDERMINT_STATESYNC_WINDOW", 8, cast=int)
        )
        if self.chunk_window < 1:
            self.chunk_window = 1
        self.chunk_timeout_s = (
            chunk_timeout_s if chunk_timeout_s is not None
            else env_number("TENDERMINT_STATESYNC_CHUNK_TIMEOUT_S", 10.0)
        )
        self.chunk_retries = chunk_retries if chunk_retries is not None else int(
            env_number("TENDERMINT_STATESYNC_RETRIES", 4, cast=int)
        )
        self.discovery_s = (
            discovery_s if discovery_s is not None
            else env_number("TENDERMINT_STATESYNC_DISCOVERY_S", 5.0)
        )
        self.fallback_s = (
            fallback_s if fallback_s is not None
            else env_number("TENDERMINT_STATESYNC_FALLBACK_S", 60.0)
        )

        # NB: a dedicated lock — BaseService owns self._mtx for the
        # start/stop lifecycle, and is_running() acquires it, so reusing
        # that name here would deadlock every is_running() call made
        # while holding the condition
        self._cv = threading.Condition()
        # height -> offering peer ids; only the HEIGHTS and WHO offers
        # them matter (manifests are fetched separately), and the lite
        # dicts are attacker-sized — storing them would let every peer
        # pin megabytes here. Heights that failed verification stay out.
        self._offers: dict[int, set[str]] = {}
        self._blacklist: set[int] = set()
        # (height, peer_id) the driver is currently awaiting a manifest
        # from — responses from anyone else are IGNORED, or a malicious
        # peer could race a forged manifest into the inbox and poison
        # the restore of a height an honest peer offered
        self._manifest_expect: tuple[int, str] | None = None
        self._manifest_inbox: dict[int, Manifest | None] = {}
        # (height, index) -> (peer_id, payload | None); only keys in
        # _chunk_expect (the window currently being fetched) are ever
        # stored — an unsolicited chunk_response must not grow memory,
        # 4 MiB at a time, on a 2^62x2^20 attacker-chosen key space
        self._chunk_inbox: dict[tuple[int, int], tuple[str, bytes | None]] = {}
        self._chunk_expect: set[tuple[int, int]] = set()
        self._thread: threading.Thread | None = None

        # adversarial-offerer hardening (round 19): a peer whose chunk
        # (or manifest) requests repeatedly time out unanswered is a
        # STALLER — it costs the restore a full window timeout per
        # strike, so after `stall_ban_after` unanswered requests it is
        # banned like a corrupt one. Any answer (chunk, no_chunk,
        # manifest) clears the peer's strikes.
        self.stall_ban_after = max(
            int(env_number("TENDERMINT_STATESYNC_STALL_BAN", 3, cast=int)), 1
        )
        self._stall_strikes: dict[str, int] = {}

        # gauges (statesync_* in the metrics RPC)
        self.restore_active = 0
        self.chunks_fetched = 0
        self.chunk_failures = 0
        self.peers_banned = 0
        self.offers_seen = 0
        # round 19: offerer bans by proven kind (scrape-visible — the
        # adversarial scenario matrix asserts on these)
        self.offerers_banned = 0
        self.offerer_bans_forged = 0
        self.offerer_bans_corrupt = 0
        self.offerer_bans_stall = 0

    # -- Reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                id=STATESYNC_CHANNEL,
                priority=3,
                send_queue_capacity=32,
                # the capacity must admit every LEGAL frame: a chunk
                # rides hex-encoded inside a JSON chunk_response (2x
                # MAX_CHUNK_BYTES = 8 MiB of hex at the 4 MiB ceiling),
                # and a maximal manifest carries 2^18 44-byte digest
                # entries (~11.5 MiB) — 21 MiB covers both with headroom
                recv_message_capacity=22020096,
            )
        ]

    def add_peer(self, peer) -> None:
        if self.enabled and self.restore_active:
            peer.try_send(STATESYNC_CHANNEL, _enc({"type": "snapshots_request"}))

    def remove_peer(self, peer, reason) -> None:
        with self._cv:
            for offers in self._offers.values():
                offers.discard(peer.id())
            self._cv.notify_all()

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        from tendermint_tpu.codec import jsonval as jv

        try:
            msg = json.loads(msg_bytes.decode())
            mtype = msg["type"]
            if mtype == "snapshots_request":
                self._serve_snapshots(peer)
            elif mtype == "snapshots_response":
                offers = jv.list_field(msg, "snapshots", MAX_OFFERED_SNAPSHOTS)
                self._note_offers(peer, offers)
            elif mtype == "manifest_request":
                self._serve_manifest(
                    peer, jv.int_field(msg, "height", 1, jv.MAX_HEIGHT)
                )
            elif mtype == "manifest_response":
                # decode FIRST (malformed = peer error even when
                # unsolicited), deliver only from the peer we asked
                manifest = Manifest.from_json(jv.dict_field(msg, "manifest"))
                with self._cv:
                    if self._manifest_expect == (manifest.height, peer.id()):
                        self._manifest_inbox[manifest.height] = manifest
                        self._cv.notify_all()
            elif mtype == "no_manifest":
                h = jv.int_field(msg, "height", 1, jv.MAX_HEIGHT)
                with self._cv:
                    # the peer disowning its own offer is always valid;
                    # the inbox wake-up only from the peer we asked
                    self._offers.get(h, set()).discard(peer.id())
                    if self._manifest_expect == (h, peer.id()):
                        self._manifest_inbox.setdefault(h, None)
                    self._cv.notify_all()
            elif mtype == "chunk_request":
                self._serve_chunk(
                    peer,
                    jv.int_field(msg, "height", 1, jv.MAX_HEIGHT),
                    jv.int_field(msg, "index", 0, jv.MAX_INDEX),
                )
            elif mtype == "chunk_response":
                h = jv.int_field(msg, "height", 1, jv.MAX_HEIGHT)
                i = jv.int_field(msg, "index", 0, jv.MAX_INDEX)
                chunk = jv.hex_field(msg, "chunk", max_bytes=MAX_CHUNK_BYTES)
                with self._cv:
                    if (h, i) in self._chunk_expect:
                        self._chunk_inbox[(h, i)] = (peer.id(), chunk)
                        self._cv.notify_all()
            elif mtype == "no_chunk":
                h = jv.int_field(msg, "height", 1, jv.MAX_HEIGHT)
                i = jv.int_field(msg, "index", 0, jv.MAX_INDEX)
                with self._cv:
                    if (h, i) in self._chunk_expect:
                        self._chunk_inbox[(h, i)] = (peer.id(), None)
                        self._cv.notify_all()
            else:
                raise ValueError(f"unknown statesync msg {mtype!r}")
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as exc:
            self.switch.stop_peer_for_error(peer, exc)

    # -- serving side ------------------------------------------------------

    def _serve_snapshots(self, peer) -> None:
        lites = []
        for h in reversed(self.store.heights()[-MAX_OFFERED_SNAPSHOTS:]):
            m = self.store.load_manifest(h)
            if m is not None:
                lites.append(m.lite())
        peer.try_send(
            STATESYNC_CHANNEL,
            _enc({"type": "snapshots_response", "snapshots": lites}),
        )

    def _serve_manifest(self, peer, height: int) -> None:
        m = self.store.load_manifest(height)
        if m is None:
            peer.try_send(
                STATESYNC_CHANNEL, _enc({"type": "no_manifest", "height": height})
            )
        else:
            peer.try_send(
                STATESYNC_CHANNEL,
                _enc({"type": "manifest_response", "manifest": m.to_json()}),
            )

    def _serve_chunk(self, peer, height: int, index: int) -> None:
        try:
            chunk = self.store.load_chunk(height, index)
        except SnapshotError as exc:
            # the LOCAL copy is damaged (bit rot / torn write): drop the
            # whole snapshot rather than serve bytes known to be bad —
            # the peer's digest check would just ban us
            logger.warning(
                "local snapshot %d damaged (%s); deleting", height, exc
            )
            self.store.delete(height)
            chunk = None
        if chunk is None:
            peer.try_send(
                STATESYNC_CHANNEL,
                _enc({"type": "no_chunk", "height": height, "index": index}),
            )
        else:
            peer.try_send(
                STATESYNC_CHANNEL,
                _enc({
                    "type": "chunk_response",
                    "height": height,
                    "index": index,
                    "chunk": chunk.hex().upper(),
                }),
            )

    def _note_offers(self, peer, offers: list) -> None:
        from tendermint_tpu.codec import jsonval as jv

        if not self.restore_active:
            # serve-only nodes never consume offers; storing them would
            # let any peer grow this dict forever
            return
        with self._cv:
            for lite in offers:
                h = jv.int_field(jv.require_dict(lite), "height", 1, jv.MAX_HEIGHT)
                if h in self._blacklist:
                    continue
                self._offers.setdefault(h, set()).add(peer.id())
                self.offers_seen += 1
            # bound per-peer state across messages: a peer holds at most
            # MAX_OFFERED_SNAPSHOTS heights, its lowest dropped first
            mine = sorted(h for h, off in self._offers.items() if peer.id() in off)
            for h in mine[:-MAX_OFFERED_SNAPSHOTS]:
                self._offers[h].discard(peer.id())
                if not self._offers[h]:
                    del self._offers[h]
            self._cv.notify_all()

    # -- restore driver ----------------------------------------------------

    def on_start(self) -> None:
        if self.enabled:
            self.restore_active = 1
            self._thread = threading.Thread(
                target=self._restore_routine, daemon=True, name="statesync.restore"
            )
            self._thread.start()

    def arm_restore(self, restorer) -> bool:
        """Arm a restore on an ALREADY-RUNNING serve-only reactor — the
        horizon-aware catchup fallback (round 19): a fast-syncing node
        whose next height every peer has pruned switches to statesync at
        runtime instead of spinning on no_block_response. Returns True
        when the restore thread launched (False: already restoring, or
        the reactor is not running)."""
        if not self.is_running():
            return False
        with self._cv:
            if self.restore_active or (
                self._thread is not None and self._thread.is_alive()
            ):
                return False
            self.restorer = restorer
            self.enabled = True
            self.restore_active = 1
        self._thread = threading.Thread(
            target=self._restore_routine, daemon=True, name="statesync.restore"
        )
        self._thread.start()
        return True

    def on_stop(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def _peers_for(self, height: int) -> list:
        with self._cv:
            ids = sorted(self._offers.get(height, ()))
        peers = []
        for pid in ids:
            peer = self.switch.peers.get(pid)
            if peer is not None:
                peers.append(peer)
        return peers

    def _serving_peers(self, height: int, also_ask: int | None = None) -> list:
        peers = self._peers_for(height)
        if also_ask is not None and also_ask != height:
            have = {p.id() for p in peers}
            peers += [p for p in self._peers_for(also_ask) if p.id() not in have]
        return peers

    def _ban_peer(self, peer_id: str, reason: str,
                  kind: str | None = None) -> None:
        self.peers_banned += 1
        if kind is not None:
            self.offerers_banned += 1
            attr = f"offerer_bans_{kind}"
            setattr(self, attr, getattr(self, attr) + 1)
        with self._cv:
            for offers in self._offers.values():
                offers.discard(peer_id)
            self._stall_strikes.pop(peer_id, None)
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            self.switch.stop_peer_for_error(peer, reason)

    def _note_stall(self, peer_id: str, what: str) -> None:
        """One unanswered request from `peer_id` timed out. Bans the
        peer after stall_ban_after strikes — a stalling offerer must not
        cost the restore a window timeout forever."""
        strikes = self._stall_strikes.get(peer_id, 0) + 1
        self._stall_strikes[peer_id] = strikes
        if strikes >= self.stall_ban_after:
            logger.warning(
                "peer %s stalled %d statesync request(s) (%s); banning",
                peer_id[:8], strikes, what,
            )
            self._ban_peer(
                peer_id, f"statesync stall ({what})", kind="stall"
            )

    def _clear_stall(self, peer_id: str) -> None:
        self._stall_strikes.pop(peer_id, None)

    def _restore_routine(self) -> None:
        deadline = time.monotonic() + self.fallback_s
        transient_fails: dict[int, int] = {}
        try:
            while self.is_running():
                height = self._pick_snapshot(deadline)
                if height is None:
                    if not self.is_running():
                        # stopping, not failing: keep scratch for the
                        # next start's resume, no fallback handoff
                        return
                    logger.warning(
                        "no usable snapshot within %.0fs; falling back to "
                        "fast sync from genesis", self.fallback_s,
                    )
                    self._finish(None)
                    return
                try:
                    state = self._restore_height(height)
                except SnapshotRejected as exc:
                    # content proven bad / permanently unverifiable:
                    # write the height off and drop its scratch chunks
                    logger.warning("snapshot %d rejected: %s", height, exc)
                    with self._cv:
                        self._blacklist.add(height)
                        self._offers.pop(height, None)
                    shutil.rmtree(self._scratch_dir(height), ignore_errors=True)
                    continue
                except RestoreError as exc:
                    # transient (manifest timeout, no peers, transport):
                    # the height stays eligible for a BOUNDED number of
                    # attempts — without the bound, one peer offering a
                    # forged unverifiable max-height would starve every
                    # honest lower snapshot for the whole fallback window
                    # (the picker always takes max). Scratch survives in
                    # case the height is re-offered later.
                    transient_fails[height] = transient_fails.get(height, 0) + 1
                    logger.warning(
                        "snapshot %d attempt %d failed: %s",
                        height, transient_fails[height], exc,
                    )
                    if transient_fails[height] >= 2:
                        logger.warning(
                            "snapshot %d: giving up after repeated transient "
                            "failures; trying lower offers", height,
                        )
                        with self._cv:
                            self._blacklist.add(height)
                            self._offers.pop(height, None)
                    continue
                if state is not None:
                    self._finish(state)
                    return
        except Exception:  # noqa: BLE001 — the driver must fail CLOSED
            logger.exception("statesync restore driver crashed")
            self._finish(None)

    def _finish(self, state) -> None:
        self.restore_active = 0
        if state is None:
            # fallback to fast sync: no restore will ever resume here —
            # drop every scratch dir or abandoned downloads leak forever
            try:
                for name in os.listdir(self.store.base_dir):
                    if name.startswith("restore-"):
                        shutil.rmtree(
                            os.path.join(self.store.base_dir, name),
                            ignore_errors=True,
                        )
            except OSError:
                pass
        if self.on_complete is not None:
            try:
                self.on_complete(state)
            except Exception:  # noqa: BLE001
                logger.exception("statesync on_complete handoff failed")

    def _pick_snapshot(self, deadline: float) -> int | None:
        """Broadcast discovery, collect offers for a full discovery_s
        window (so a slow peer's HIGHER snapshot beats the first
        responder's lower one), then pick the highest offered height.
        Re-broadcasts window by window until `deadline` when nothing
        usable shows up."""
        while self.is_running():
            self.switch.broadcast(
                STATESYNC_CHANNEL, _enc({"type": "snapshots_request"})
            )
            collect_until = min(time.monotonic() + self.discovery_s, deadline)
            with self._cv:
                while self.is_running():
                    remaining = collect_until - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(min(remaining, 0.25))
                usable = [h for h, off in self._offers.items() if off]
                if usable:
                    logger.debug("offers in hand: %s; picking %d", usable, max(usable))
                    return max(usable)
            if time.monotonic() >= deadline:
                return None
        return None

    def _fetch_manifest(self, height: int, also_ask: int | None = None) -> Manifest:
        """Fetch AND light-verify a manifest for `height`, one offering
        peer at a time. A manifest that contradicts the verified chain
        (ManifestBindingError) proves its SERVER lied: that peer is
        banned and the next offerer tried — the height is only given up
        on when the light walk itself fails or no peer serves.
        `also_ask` adds the offerers of ANOTHER height (a delta's base
        may not be separately offered, but whoever serves the delta
        holds its whole chain)."""
        for peer in self._serving_peers(height, also_ask):
            with self._cv:
                self._manifest_inbox.pop(height, None)
                self._manifest_expect = (height, peer.id())
            logger.debug("requesting manifest %d from %s", height, peer.id()[:8])
            peer.try_send(
                STATESYNC_CHANNEL, _enc({"type": "manifest_request", "height": height})
            )
            deadline = time.monotonic() + self.chunk_timeout_s
            with self._cv:
                while (
                    height not in self._manifest_inbox
                    and time.monotonic() < deadline
                    and self.is_running()
                ):
                    self._cv.wait(0.25)
                answered = height in self._manifest_inbox
                m = self._manifest_inbox.pop(height, None)
                self._manifest_expect = None
            if not answered:
                # never answered at all: a stall strike (an honest
                # no_manifest answered and costs nothing) — but only
                # when the DEADLINE expired; a wait cut short by the
                # reactor stopping proves nothing about the peer
                if self.is_running() and time.monotonic() >= deadline:
                    self._note_stall(peer.id(), "manifest")
                continue
            self._clear_stall(peer.id())
            if m is None:
                continue
            try:
                self.restorer.verify_manifest(m)
            except ManifestBindingError as exc:
                logger.warning(
                    "manifest %d from %s contradicts the verified chain "
                    "(%s); banning", height, peer.id()[:8], exc,
                )
                self._ban_peer(
                    peer.id(), f"statesync manifest {height}: {exc}",
                    kind="forged",
                )
                continue
            return m
        raise RestoreError(f"no peer served a usable manifest for height {height}")

    # -- chunk download (windowed, batch-verified, resumable) --------------

    def _scratch_dir(self, height: int) -> str:
        return os.path.join(self.store.base_dir, f"restore-{height:010d}")

    def _load_scratch(self, manifest: Manifest) -> dict[int, bytes]:
        """Reload chunks a previous attempt persisted; anything damaged
        or digest-mismatching is discarded (it will re-download)."""
        d = self._scratch_dir(manifest.height)
        have: dict[int, bytes] = {}
        if not os.path.isdir(d):
            return have
        for i in range(manifest.chunks):
            path = os.path.join(d, self.store.chunk_name(i))
            try:
                with open(path, "rb") as f:
                    have[i] = unframe_chunk(f.read())
            except (OSError, SnapshotError):
                continue
        if have:
            items = sorted(have.items())
            bad = verify_chunk_batch(
                manifest, items, hasher=self.restorer.hasher
            )
            for i in bad:
                have.pop(i, None)
            logger.info(
                "resuming restore at height %d: %d/%d chunk(s) on disk",
                manifest.height, len(have), manifest.chunks,
            )
        return have

    def _save_scratch(self, height: int, index: int, payload: bytes) -> None:
        d = self._scratch_dir(height)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, self.store.chunk_name(index)), "wb") as f:
            f.write(frame_chunk(payload))

    def _restore_height(self, height: int):
        # _fetch_manifest binds the manifest to the light-verified header
        # chain BEFORE anything downloads: a forged manifest costs us two
        # RPC round-trips (and its server a ban), not a chunk download.
        # Delta manifests (round 13) pull in their base chain — fetched
        # TARGET-FIRST (the walk to height+1 caches every lower header,
        # so the bases bind off the cache), restored base-first.
        manifest = self._fetch_manifest(height)
        chain = [manifest]
        while chain[0].kind == KIND_DELTA:
            if len(chain) > MAX_DELTA_CHAIN:
                raise SnapshotRejected(
                    f"snapshot {height}: delta chain exceeds {MAX_DELTA_CHAIN}"
                )
            base = self._fetch_manifest(chain[0].base_height, also_ask=height)
            chain.insert(0, base)
        logger.debug(
            "snapshot %d bound (%d-link chain, %d chunk(s) at the head); "
            "downloading", height, len(chain), manifest.chunks,
        )

        # links the app already holds (a crashed earlier run persisted
        # the app per link) skip straight past download; any divergence
        # a skip could hide dies at the next delta's base/root checks.
        # Skips only apply when the app sits EXACTLY on a chain height —
        # an app at an unaligned height must hit the base restore's
        # "needs a fresh app" gate, not silently skip the base and die
        # with a misleading stale-delta error
        app_h = self.restorer.app.info().last_block_height
        resumable = app_h in {m.height for m in chain}
        state = None
        for k, m in enumerate(chain):
            last = k == len(chain) - 1
            if not last and resumable and app_h >= m.height:
                logger.info(
                    "resuming: skipping chain link %d (app at %d)",
                    m.height, app_h,
                )
                continue
            ordered = self._download_chunks(m, also_ask=height)
            try:
                state = self.restorer.restore_step(m, ordered, seed=last)
            except SnapshotRejected:
                raise
            except RestoreError as exc:
                # everything restore_step() touches is local and fully
                # downloaded: a failure here is CONTENT, not weather —
                # blacklist the TARGET height
                raise SnapshotRejected(str(exc))
        for m in chain:
            shutil.rmtree(self._scratch_dir(m.height), ignore_errors=True)
        return state

    def _download_chunks(self, manifest: Manifest, also_ask: int | None = None):
        """Windowed, digest-verified, scratch-resumable download of one
        manifest's chunks. Returns them in order; raises RestoreError
        when peers can't serve within the retry budget."""
        chunks = self._load_scratch(manifest)
        missing = [i for i in range(manifest.chunks) if i not in chunks]
        attempts: dict[int, int] = {}
        while missing and self.is_running():
            window, missing = (
                missing[: self.chunk_window], missing[self.chunk_window:],
            )
            got = self._fetch_window(manifest, window, attempts, also_ask=also_ask)
            retry = [i for i in window if i not in got]
            chunks.update(got)
            missing.extend(retry)
            for i in retry:
                attempts[i] = attempts.get(i, 0) + 1
                if attempts[i] > self.chunk_retries:
                    raise RestoreError(
                        f"chunk {i} unavailable after {self.chunk_retries} retries"
                    )
        if missing:
            raise RestoreError("reactor stopped mid-download")
        return [chunks[i] for i in range(manifest.chunks)]

    def _fetch_window(
        self, manifest: Manifest, window: list[int], attempts: dict[int, int],
        also_ask: int | None = None,
    ) -> dict[int, bytes]:
        """Request `window` chunks spread over the offering peers, wait,
        then digest-verify the arrivals in ONE gateway batch. Returns the
        verified chunks; a mismatching chunk bans its serving peer and is
        left for the caller to retry."""
        height = manifest.height
        peers = self._serving_peers(height, also_ask)
        if not peers:
            raise RestoreError(f"no peers left offering snapshot {height}")
        with self._cv:
            for i in window:
                self._chunk_inbox.pop((height, i), None)
            self._chunk_expect = {(height, i) for i in window}
        asked: dict[int, str] = {}
        for k, i in enumerate(window):
            peer = peers[(k + attempts.get(i, 0)) % len(peers)]
            asked[i] = peer.id()
            peer.try_send(
                STATESYNC_CHANNEL,
                _enc({"type": "chunk_request", "height": height, "index": i}),
            )
        deadline = time.monotonic() + self.chunk_timeout_s
        arrived: dict[int, tuple[str, bytes]] = {}
        answered: set[int] = set()  # incl. honest no_chunk — a window
        # whose every request is answered must not sit out the timeout
        answered_by: dict[int, str] = {}  # chunk -> actual RESPONDER
        with self._cv:
            while len(answered) < len(window) and self.is_running():
                for i in window:
                    if i in answered:
                        continue
                    entry = self._chunk_inbox.pop((height, i), None)
                    if entry is None:
                        continue
                    pid, payload = entry
                    answered.add(i)
                    answered_by[i] = pid
                    if payload is None:  # honest no_chunk
                        self._offers.get(height, set()).discard(pid)
                        self.chunk_failures += 1
                    else:
                        arrived[i] = (pid, payload)
                if len(answered) >= len(window) or time.monotonic() >= deadline:
                    break
                self._cv.wait(0.25)
            self._chunk_expect = set()
        # stall accounting (round 19): a request NOBODY answered (not
        # even a no_chunk) by the deadline strikes the peer it was asked
        # of; any answer clears the peer that ACTUALLY responded — never
        # the asked peer on someone else's answer, or a staller whose
        # chunks an accomplice keeps answering would launder its strikes
        # forever while the window still burned its timeout. A wait cut
        # short by the reactor STOPPING (not the deadline) strikes
        # nobody — an honest peer must not be banned at shutdown.
        for pid in answered_by.values():
            self._clear_stall(pid)
        if self.is_running() and time.monotonic() >= deadline:
            for i, pid in asked.items():
                if i not in answered_by:
                    self._note_stall(
                        pid, f"chunk {i} of snapshot {height}"
                    )
        if not arrived:
            self.chunk_failures += len(window)
            return {}
        items = sorted((i, payload) for i, (_pid, payload) in arrived.items())
        bad = set(
            verify_chunk_batch(manifest, items, hasher=self.restorer.hasher)
        )
        self.chunks_fetched += len(items) - len(bad)
        self.chunk_failures += len(bad)
        good: dict[int, bytes] = {}
        banned_this_pass: set[str] = set()
        for i, (pid, payload) in arrived.items():
            if i in bad:
                # the digest PROVES the peer served corrupt bytes for
                # the manifest it offered: penalize and refetch
                # elsewhere — ONCE per peer per pass, so a window of N
                # corrupt chunks counts one banned OFFERER, not N
                # (offerers_banned counts peers, the counter's contract)
                logger.warning(
                    "chunk %d of snapshot %d failed digest check; banning "
                    "peer %s", i, height, pid[:8],
                )
                if pid not in banned_this_pass:
                    banned_this_pass.add(pid)
                    self._ban_peer(
                        pid, f"statesync chunk {i} digest mismatch",
                        kind="corrupt",
                    )
            else:
                good[i] = payload
                self._save_scratch(height, i, payload)
        return good

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        out = {
            "restore_active": self.restore_active,
            "chunks_fetched": self.chunks_fetched,
            "chunk_failures": self.chunk_failures,
            "peers_banned": self.peers_banned,
            "offers_seen": self.offers_seen,
            # round 19: adversarial-offerer bans by proven kind
            "offerers_banned": self.offerers_banned,
            "offerer_bans_forged": self.offerer_bans_forged,
            "offerer_bans_corrupt": self.offerer_bans_corrupt,
            "offerer_bans_stall": self.offerer_bans_stall,
            **self.store.stats(),
        }
        if self.restorer is not None:
            out.update(self.restorer.stats())
        return out
