"""Pure verifier for authenticated state-tree proofs (round 13).

The app-state commitment (tendermint_tpu/statetree/) is a *merkleized
canonical treap*: a binary search tree over byte keys whose shape is a
pure function of the key SET (every node's heap priority is derived from
its key), so replicas that built their state through different operation
histories — replay from genesis, restore from a full snapshot's sorted
map, a delta chain — land on byte-identical roots. This module is the
proof side only: given a root (the committed ``app_hash``), verify that
a key maps to a value (membership) or that a key is NOT in the tree
(absence) — with no dependency on the tree implementation, so light
clients (rpc/light.py verified_query) and the statesync delta restore
path import just this.

Hash domains (RIPEMD-160, length-prefixed operands via codec.binary so
field boundaries can't be shifted by concatenation games):

    value_hash(v)            = H(0x00 || encode_bytes(v))
    node_hash(k, vh, lh, rh) = H(0x01 || encode_bytes(k) ||
                                 encode_bytes(vh) ||
                                 encode_bytes(lh) || encode_bytes(rh))

where lh/rh are the child subtree hashes (b"" for an empty child) and
every node — interior or leaf — carries a key/value pair (a treap, not a
leaf-only tree). The empty tree's root is b"".

A proof is the search path for the queried key, bottom-up:

- membership: path[0] is the node holding the key (its value revealed);
  each higher step carries the node's (key, value_hash, left, right)
  with the child hash on the query's side equal to the hash computed so
  far. Soundness: the chain of node_hash recomputations binds the whole
  path into the root, and unique keys mean no second location can hash
  to the same root.
- absence: the same path shape, but NO step's key equals the query and
  the terminal step's child pointer ON THE QUERY'S SIDE is empty. The
  verifier re-derives each step's direction from the query key itself
  (query < step.key -> left), so the path is forced to be exactly the
  BST search path the honest tree would take — and that search dying in
  an empty child proves the key is nowhere in the tree.

Adversarial-shape note: treap depth is O(log n) in expectation; an
attacker grinding keys whose priorities follow key order can deepen one
search path (cost ~O(depth^2) hash grinding). Proofs just grow with
depth; MAX_PROOF_STEPS bounds what a verifier will even decode.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.codec.binary import encode_bytes
from tendermint_tpu.crypto.hashing import ripemd160

# the empty tree / empty child commitment
EMPTY_HASH = b""

# decode-time ceilings against garbage proofs: 512 steps is a tree an
# attacker ground ~2^18 hashes per level to build — anything deeper is
# garbage, not state. Keys/values bounded like tx payloads.
MAX_PROOF_STEPS = 512
MAX_KEY_BYTES = 1 << 16
MAX_VALUE_BYTES = 1 << 22

_LEAF_PREFIX = b"\x00"
_NODE_PREFIX = b"\x01"
_PRIO_PREFIX = b"\x02"


def value_hash(value: bytes) -> bytes:
    return ripemd160(_LEAF_PREFIX + encode_bytes(value))


def node_hash(key: bytes, vh: bytes, left: bytes, right: bytes) -> bytes:
    return ripemd160(
        _NODE_PREFIX
        + encode_bytes(key)
        + encode_bytes(vh)
        + encode_bytes(left)
        + encode_bytes(right)
    )


def key_priority(key: bytes) -> bytes:
    """The canonical heap priority of a key (compared as raw bytes,
    larger = closer to the root). Deriving it from the key alone is what
    makes the tree shape history-independent."""
    return ripemd160(_PRIO_PREFIX + key)


@dataclass
class ProofStep:
    """One node on the search path: its key, its value's hash, and both
    child subtree hashes (EMPTY_HASH for an absent child)."""

    key: bytes
    vh: bytes
    left: bytes
    right: bytes

    def hash(self) -> bytes:
        return node_hash(self.key, self.vh, self.left, self.right)

    def to_json(self) -> list:
        return [
            self.key.hex().upper(),
            self.vh.hex().upper(),
            self.left.hex().upper(),
            self.right.hex().upper(),
        ]

    @classmethod
    def from_json(cls, obj) -> "ProofStep":
        if not isinstance(obj, list) or len(obj) != 4 or any(
            not isinstance(x, str) for x in obj
        ):
            raise ValueError("bad proof step")
        key, vh, left, right = (bytes.fromhex(x) for x in obj)
        if len(key) > MAX_KEY_BYTES:
            raise ValueError("proof step key too long")
        if len(vh) != 20:
            raise ValueError("proof step value hash must be 20 bytes")
        for child in (left, right):
            if child != EMPTY_HASH and len(child) != 20:
                raise ValueError("proof step child hash must be 0 or 20 bytes")
        return cls(key, vh, left, right)


@dataclass
class TreeProof:
    """Membership (value is bytes) or absence (value is None) proof for
    `key`, as the bottom-up search path `steps` (terminal node first,
    root last). Verification is pure: `verify(root)` needs only this
    object and the trusted root."""

    key: bytes
    value: bytes | None
    steps: list[ProofStep]

    @property
    def is_membership(self) -> bool:
        return self.value is not None

    def verify(self, root: bytes) -> bool:
        key = self.key
        steps = self.steps
        if not steps:
            # only the EMPTY tree has an empty search path, and it can
            # only prove absence
            return self.value is None and root == EMPTY_HASH
        term = steps[0]
        if self.value is not None:
            # membership: the terminal node must BE the entry
            if term.key != key or term.vh != value_hash(self.value):
                return False
        else:
            # absence: the search must die in an empty child at the
            # terminal node, and no step on the path may hold the key
            if term.key == key:
                return False
            side = term.left if key < term.key else term.right
            if side != EMPTY_HASH:
                return False
        h = term.hash()
        for step in steps[1:]:
            if step.key == key:
                # the query key at an interior step: for absence this is
                # a contradiction; for membership it would mean the key
                # appears twice — honest trees have unique keys
                return False
            # re-derive the direction from the QUERY key: this forces
            # the path to be the tree's actual search path for `key`
            expected = step.left if key < step.key else step.right
            if expected != h:
                return False
            h = step.hash()
        return h == root

    def to_json(self) -> dict:
        out = {
            "key": self.key.hex().upper(),
            "steps": [s.to_json() for s in self.steps],
        }
        if self.value is not None:
            out["value"] = self.value.hex().upper()
        return out

    @classmethod
    def from_json(cls, obj) -> "TreeProof":
        """Decode an UNTRUSTED proof; every violation raises ValueError
        (the peer-error / RPC-error alphabet)."""
        if not isinstance(obj, dict):
            raise ValueError("tree proof must be an object")
        key_hex = obj.get("key")
        if not isinstance(key_hex, str) or len(key_hex) > 2 * MAX_KEY_BYTES:
            raise ValueError("bad tree proof key")
        value = None
        if "value" in obj:
            value_hex = obj["value"]
            if not isinstance(value_hex, str) or len(value_hex) > 2 * MAX_VALUE_BYTES:
                raise ValueError("bad tree proof value")
            value = bytes.fromhex(value_hex)
        raw_steps = obj.get("steps")
        if not isinstance(raw_steps, list) or len(raw_steps) > MAX_PROOF_STEPS:
            raise ValueError("bad tree proof steps")
        return cls(
            bytes.fromhex(key_hex),
            value,
            [ProofStep.from_json(s) for s in raw_steps],
        )
