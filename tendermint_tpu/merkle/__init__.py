from tendermint_tpu.merkle.simple import (
    SimpleProof,
    inner_hash,
    leaf_hash,
    simple_hash_from_byteslices,
    simple_hash_from_hashes,
    simple_hash_from_map,
    simple_proofs_from_byteslices,
    simple_proofs_from_hashes,
)

__all__ = [
    "SimpleProof",
    "leaf_hash",
    "inner_hash",
    "simple_hash_from_hashes",
    "simple_hash_from_byteslices",
    "simple_hash_from_map",
    "simple_proofs_from_hashes",
    "simple_proofs_from_byteslices",
]
