"""Simple Merkle tree + SimpleProof (CPU reference implementation).

Equivalent of tmlibs/merkle (SURVEY.md 2.2), per the reference's merkle spec
(docs/specification/merkle.rst): a compact binary tree over a static list;
when the count is odd the LEFT side gets the extra leaf — the split point is
(n+1)//2, matching types/tx.go:33-46 and the spec's diagrams. Hashes are
RIPEMD-160 (20 bytes), computed over length-prefixed operands so leaf/inner
domains can't collide by concatenation games.

Builder layout (round 7): the production tree/proof path is FLAT — a
shape-cached level-order schedule over a preallocated node array
(`FlatTree`), with proofs as (tree, leaf-index) views into the shared
node buffer (`SharedProof`) instead of per-leaf copied aunt lists. The
pre-r7 recursive builder survives as `recursive_proofs_from_hashes`, the
parity oracle the flat path is tested (and benched) against: measured at
the 1 MB / 64 KB part-set shape (16 leaves) the flat build is ~6.7x the
recursive one (15.8 vs 106.5 us — the recursion's list-slice copies,
per-leaf aunt appends, and per-node encode_bytes churn were ~85% of the
build; the 15 compressions are ~17 us either way).

The vectorized TPU variant (tendermint_tpu/ops/merkle.py) must reproduce
these digests byte-for-byte; tests cross-check the two. Its node buffer
uses the SAME slot order as FlatTree (leaves 0..n-1, then internal nodes
in postorder), so device-built trees rehydrate host proofs with zero
host hashing (FlatTree.from_nodes — the devd hash_stream tree frame).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import lru_cache

from tendermint_tpu.codec.binary import encode_bytes, encode_string
from tendermint_tpu.crypto.hashing import (
    _HAVE_OPENSSL_RIPEMD,
    _RIPEMD_TEMPLATE,
    ripemd160,
)


def leaf_hash(item: bytes) -> bytes:
    """SimpleHashFromBinary equivalent: hash of the length-prefixed item."""
    return ripemd160(encode_bytes(item))


def inner_hash(left: bytes, right: bytes) -> bytes:
    """SimpleHashFromTwoHashes equivalent."""
    return ripemd160(encode_bytes(left) + encode_bytes(right))


def kv_hash(key: str, value: bytes) -> bytes:
    """KVPair leaf (used by Header.Hash / SimpleHashFromMap,
    types/block.go:173-188)."""
    return ripemd160(encode_string(key) + encode_bytes(value))


# -- flat level-order builder -------------------------------------------------
#
# Shape and hashing are separated: _flat_shape(n) is the pure tree shape
# (which slots combine into which), cached per leaf count — part-set and
# tx-set sizes repeat heavily, so steady-state builds pay hashing only.
# Slot order: leaves 0..n-1, internal nodes n..2n-2 in POSTORDER of the
# (n+1)//2 recursion (root last, slot 2n-2) — the same order
# ops/merkle._dense_schedule assigns, which is what lets a device-built
# node buffer stand in for a host build byte-for-byte.

# 0x01 0x14: the varint length prefix of a 20-byte digest (encode_bytes)
_INNER_PREFIX = b"\x01\x14"

# a level narrower than this hashes via per-node hashlib template copies;
# at or above it, one native AVX-512 ripemd160_x16 batch call per level
# wins (ctypes + marshal overhead ~40 us/call loses below this width)
_NATIVE_LEVEL_MIN = 64


@lru_cache(maxsize=256)
def _flat_shape(n: int):
    """(left, right, levels) for n >= 2 leaves.

    left[k]/right[k]: child slots of internal node n+k (postorder).
    levels: per height (bottom-up), a list of (out_slot, left_slot,
    right_slot) — every node in a level depends only on lower levels, so
    each level hashes as one batch."""
    left: list[int] = []
    right: list[int] = []
    heights: list[int] = []
    # iterative postorder of build(lo, hi): frame = [lo, hi, stage,
    # left_slot, left_height]; `ret` carries the just-built child up
    stack = [[0, n, 0, -1, 0]]
    ret, ret_h = -1, 0
    while stack:
        f = stack[-1]
        if f[1] - f[0] == 1:
            ret, ret_h = f[0], 0
            stack.pop()
            continue
        mid = f[0] + (f[1] - f[0] + 1) // 2
        if f[2] == 0:
            f[2] = 1
            stack.append([f[0], mid, 0, -1, 0])
        elif f[2] == 1:
            f[3], f[4], f[2] = ret, ret_h, 2
            stack.append([mid, f[1], 0, -1, 0])
        else:
            slot = n + len(left)
            left.append(f[3])
            right.append(ret)
            heights.append(max(f[4], ret_h) + 1)
            ret, ret_h = slot, heights[-1]
            stack.pop()
    by_height: dict[int, list[tuple[int, int, int]]] = {}
    for k, h in enumerate(heights):
        by_height.setdefault(h, []).append((n + k, left[k], right[k]))
    levels = tuple(tuple(by_height[h]) for h in sorted(by_height))
    return tuple(left), tuple(right), levels


def _build_nodes(hashes: list[bytes]) -> list[bytes]:
    """All 2n-1 node hashes (leaves + postorder internal) for n >= 2."""
    n = len(hashes)
    left, right, levels = _flat_shape(n)
    nodes: list[bytes] = list(hashes) + [b""] * (n - 1)
    pfx = _INNER_PREFIX
    if any(len(h) != 20 for h in hashes):
        # generic-width leaves (simple_hash_from_hashes is a public API;
        # the pre-r7 recursive builder length-prefixed operands' ACTUAL
        # lengths): same shape, real varint prefixes. Internal nodes are
        # always 20-byte digests, so only leaf operands differ.
        for level in levels:
            for o, l, r in level:
                nodes[o] = inner_hash(nodes[l], nodes[r])
        return nodes
    if _HAVE_OPENSSL_RIPEMD:
        template_copy = _RIPEMD_TEMPLATE.copy
        for level in levels:
            if len(level) >= _NATIVE_LEVEL_MIN:
                from tendermint_tpu import native

                # ready(), not available(): a tree build on the block
                # hot path must never block behind a lazy native build
                if native.ready():
                    pre = [
                        pfx + nodes[l] + pfx + nodes[r] for _, l, r in level
                    ]
                    for (o, _, _), d in zip(level, native.ripemd160_batch(pre)):
                        nodes[o] = d
                    continue
            for o, l, r in level:
                h = template_copy()
                h.update(pfx + nodes[l] + pfx + nodes[r])
                nodes[o] = h.digest()
    else:  # pragma: no cover - env without OpenSSL ripemd
        for level in levels:
            for o, l, r in level:
                nodes[o] = ripemd160(pfx + nodes[l] + pfx + nodes[r])
    return nodes


class FlatTree:
    """The full simple-Merkle node buffer over n leaves: one shared flat
    array (leaves 0..n-1, internal nodes postorder, root last) that every
    proof references instead of carrying copied aunt lists."""

    __slots__ = ("n", "nodes")

    def __init__(self, n: int, nodes: list[bytes]):
        self.n = n
        self.nodes = nodes

    @classmethod
    def from_leaf_digests(cls, digests: list[bytes]) -> "FlatTree":
        n = len(digests)
        if n <= 1:
            return cls(n, list(digests))
        return cls(n, _build_nodes(list(digests)))

    @classmethod
    def from_nodes(cls, n: int, nodes: list[bytes]) -> "FlatTree":
        """Rehydrate from an externally computed node buffer (the devd
        hash_stream tree frame / ops.merkle node buffer): leaves first,
        then internal nodes in postorder. Validates count only — digest
        parity is the producer's contract, enforced by tests."""
        want = max(2 * n - 1, n)
        if len(nodes) != want:
            raise ValueError(
                f"flat tree over {n} leaves needs {want} nodes, got {len(nodes)}"
            )
        return cls(n, list(nodes))

    def root(self) -> bytes:
        if self.n == 0:
            return b""
        return self.nodes[-1]

    def internal_nodes(self) -> list[bytes]:
        """The postorder internal-node hashes (what the devd tree frame
        carries; [] for n <= 1)."""
        return self.nodes[self.n:]

    def aunts_for(self, index: int) -> list[bytes]:
        """Bottom-up aunt hashes for one leaf: an O(log n) descent over
        the shared buffer — references, never copies."""
        n = self.n
        if not 0 <= index < n:
            raise IndexError(f"leaf {index} out of range (n={n})")
        if n == 1:
            return []
        left, right, _ = _flat_shape(n)
        nodes = self.nodes
        aunts: list[bytes] = []
        slot, lo, hi = 2 * n - 2, 0, n
        while hi - lo > 1:
            mid = lo + (hi - lo + 1) // 2
            l, r = left[slot - n], right[slot - n]
            if index < mid:
                aunts.append(nodes[r])
                slot, hi = l, mid
            else:
                aunts.append(nodes[l])
                slot, lo = r, mid
        aunts.reverse()
        return aunts

    def proofs(self) -> list["SimpleProof"]:
        return [SharedProof(self, i) for i in range(self.n)]


def simple_hash_from_hashes(hashes: list[bytes]) -> bytes:
    n = len(hashes)
    if n == 0:
        return b""
    if n == 1:
        return hashes[0]
    return _build_nodes(list(hashes))[-1]


def simple_hash_from_byteslices(items: list[bytes]) -> bytes:
    return simple_hash_from_hashes([leaf_hash(it) for it in items])


def simple_hash_from_map(kvs: dict[str, bytes]) -> bytes:
    """Merkle root of a string-keyed map: KVPair leaves in sorted key order."""
    return simple_hash_from_hashes([kv_hash(k, kvs[k]) for k in sorted(kvs)])


@dataclass(eq=False)
class SimpleProof:
    """Inclusion proof: the aunt hashes bottom-up (reference
    tmlibs/merkle SimpleProof; verified per part at types/part_set.go:204)."""

    aunts: list[bytes] = field(default_factory=list)

    def __eq__(self, other):
        # manual eq (not the dataclass one) so an eager SimpleProof and a
        # SharedProof view over the same tree compare equal
        if not isinstance(other, SimpleProof):
            return NotImplemented
        return list(self.aunts) == list(other.aunts)

    def verify(self, index: int, total: int, leaf: bytes, root: bytes) -> bool:
        if index < 0 or total <= 0 or index >= total:
            return False
        computed = _compute_hash_from_aunts(index, total, leaf, list(self.aunts))
        return computed is not None and computed == root

    def to_json(self):
        return {"aunts": [a.hex().upper() for a in self.aunts]}

    @classmethod
    def from_json(cls, obj) -> "SimpleProof":
        aunts = obj.get("aunts") if isinstance(obj, dict) else None
        # 64 aunts = a 2^64-leaf tree: anything deeper is garbage; each
        # aunt must be exactly one RIPEMD-160 digest (20 bytes / 40 hex
        # chars) — a wrong-width aunt can never verify, so reject it at
        # decode time instead of failing later at compare time
        if not isinstance(aunts, list) or len(aunts) > 64 or any(
            not isinstance(a, str) or len(a) != 40 for a in aunts
        ):
            raise ValueError("bad merkle proof aunts")
        return cls([bytes.fromhex(a) for a in aunts])


class SharedProof(SimpleProof):
    """SimpleProof as a (tree, leaf-index) view: aunts materialize
    lazily from the shared FlatTree buffer on first access (the gossip
    serialize path), so building n proofs is n tiny views, not n copied
    lists — the slice-copy blowup the recursive builder paid."""

    __slots__ = ("_tree", "_index", "_aunts")

    def __init__(self, tree: FlatTree, index: int):
        self._tree = tree
        self._index = index
        self._aunts: list[bytes] | None = None

    @property
    def aunts(self) -> list[bytes]:
        if self._aunts is None:
            self._aunts = self._tree.aunts_for(self._index)
        return self._aunts


def _compute_hash_from_aunts(
    index: int, total: int, leaf: bytes, aunts: list[bytes]
) -> bytes | None:
    if total == 1:
        if aunts:
            return None
        return leaf
    mid = (total + 1) // 2
    if not aunts:
        return None
    aunt = aunts[-1]
    rest = aunts[:-1]
    if index < mid:
        left = _compute_hash_from_aunts(index, mid, leaf, rest)
        if left is None:
            return None
        return inner_hash(left, aunt)
    right = _compute_hash_from_aunts(index - mid, total - mid, leaf, rest)
    if right is None:
        return None
    return inner_hash(aunt, right)


def simple_proofs_from_hashes(hashes: list[bytes]) -> tuple[bytes, list[SimpleProof]]:
    """Root + a proof per leaf (NewPartSetFromData builds these for every
    part, types/part_set.go:95-122). Flat builder + shared-aunt views;
    byte-identical to recursive_proofs_from_hashes (tests enforce)."""
    tree = FlatTree.from_leaf_digests(hashes)
    if tree.n == 0:
        return b"", []
    if tree.n == 1:
        return tree.nodes[0], [SimpleProof()]
    return tree.root(), tree.proofs()


def flat_tree_from_leaf_digests(digests: list[bytes]) -> FlatTree:
    return FlatTree.from_leaf_digests(digests)


def recursive_proofs_from_hashes(
    hashes: list[bytes],
) -> tuple[bytes, list[SimpleProof]]:
    """The pre-r7 recursive builder, kept verbatim as the parity oracle
    for the flat path (tests/test_merkle_flat.py) and the baseline of the
    host-builder bench row (benches/bench_partset.py)."""
    n = len(hashes)
    proofs = [SimpleProof() for _ in range(n)]
    root = _recursive_build(hashes, list(range(n)), proofs)
    return root, proofs


def _recursive_build(
    hashes: list[bytes], idxs: list[int], proofs: list[SimpleProof]
) -> bytes:
    n = len(hashes)
    if n == 0:
        return b""
    if n == 1:
        return hashes[0]
    mid = (n + 1) // 2
    left = _recursive_build(hashes[:mid], idxs[:mid], proofs)
    right = _recursive_build(hashes[mid:], idxs[mid:], proofs)
    for i in idxs[:mid]:
        proofs[i].aunts.append(right)
    for i in idxs[mid:]:
        proofs[i].aunts.append(left)
    return inner_hash(left, right)


def simple_proofs_from_byteslices(items: list[bytes]) -> tuple[bytes, list[SimpleProof]]:
    return simple_proofs_from_hashes([leaf_hash(it) for it in items])
