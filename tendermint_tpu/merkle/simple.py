"""Simple Merkle tree + SimpleProof (CPU reference implementation).

Equivalent of tmlibs/merkle (SURVEY.md 2.2), per the reference's merkle spec
(docs/specification/merkle.rst): a compact binary tree over a static list;
when the count is odd the LEFT side gets the extra leaf — the split point is
(n+1)//2, matching types/tx.go:33-46 and the spec's diagrams. Hashes are
RIPEMD-160 (20 bytes), computed over length-prefixed operands so leaf/inner
domains can't collide by concatenation games.

The vectorized TPU variant (tendermint_tpu/ops/merkle.py) must reproduce
these digests byte-for-byte; tests cross-check the two.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.codec.binary import encode_bytes, encode_string
from tendermint_tpu.crypto.hashing import ripemd160


def leaf_hash(item: bytes) -> bytes:
    """SimpleHashFromBinary equivalent: hash of the length-prefixed item."""
    return ripemd160(encode_bytes(item))


def inner_hash(left: bytes, right: bytes) -> bytes:
    """SimpleHashFromTwoHashes equivalent."""
    return ripemd160(encode_bytes(left) + encode_bytes(right))


def kv_hash(key: str, value: bytes) -> bytes:
    """KVPair leaf (used by Header.Hash / SimpleHashFromMap,
    types/block.go:173-188)."""
    return ripemd160(encode_string(key) + encode_bytes(value))


def simple_hash_from_hashes(hashes: list[bytes]) -> bytes:
    n = len(hashes)
    if n == 0:
        return b""
    if n == 1:
        return hashes[0]
    mid = (n + 1) // 2
    return inner_hash(
        simple_hash_from_hashes(hashes[:mid]), simple_hash_from_hashes(hashes[mid:])
    )


def simple_hash_from_byteslices(items: list[bytes]) -> bytes:
    return simple_hash_from_hashes([leaf_hash(it) for it in items])


def simple_hash_from_map(kvs: dict[str, bytes]) -> bytes:
    """Merkle root of a string-keyed map: KVPair leaves in sorted key order."""
    return simple_hash_from_hashes([kv_hash(k, kvs[k]) for k in sorted(kvs)])


@dataclass
class SimpleProof:
    """Inclusion proof: the aunt hashes bottom-up (reference
    tmlibs/merkle SimpleProof; verified per part at types/part_set.go:204)."""

    aunts: list[bytes] = field(default_factory=list)

    def verify(self, index: int, total: int, leaf: bytes, root: bytes) -> bool:
        if index < 0 or total <= 0 or index >= total:
            return False
        computed = _compute_hash_from_aunts(index, total, leaf, list(self.aunts))
        return computed is not None and computed == root

    def to_json(self):
        return {"aunts": [a.hex().upper() for a in self.aunts]}

    @classmethod
    def from_json(cls, obj) -> "SimpleProof":
        aunts = obj.get("aunts") if isinstance(obj, dict) else None
        # 64 aunts = a 2^64-leaf tree: anything deeper is garbage
        if not isinstance(aunts, list) or len(aunts) > 64 or any(
            not isinstance(a, str) or len(a) > 128 for a in aunts
        ):
            raise ValueError("bad merkle proof aunts")
        return cls([bytes.fromhex(a) for a in aunts])


def _compute_hash_from_aunts(
    index: int, total: int, leaf: bytes, aunts: list[bytes]
) -> bytes | None:
    if total == 1:
        if aunts:
            return None
        return leaf
    mid = (total + 1) // 2
    if not aunts:
        return None
    aunt = aunts[-1]
    rest = aunts[:-1]
    if index < mid:
        left = _compute_hash_from_aunts(index, mid, leaf, rest)
        if left is None:
            return None
        return inner_hash(left, aunt)
    right = _compute_hash_from_aunts(index - mid, total - mid, leaf, rest)
    if right is None:
        return None
    return inner_hash(aunt, right)


def simple_proofs_from_hashes(hashes: list[bytes]) -> tuple[bytes, list[SimpleProof]]:
    """Root + a proof per leaf (NewPartSetFromData builds these for every
    part, types/part_set.go:95-122)."""
    n = len(hashes)
    proofs = [SimpleProof() for _ in range(n)]
    root = _build(hashes, list(range(n)), proofs)
    return root, proofs


def _build(hashes: list[bytes], idxs: list[int], proofs: list[SimpleProof]) -> bytes:
    n = len(hashes)
    if n == 0:
        return b""
    if n == 1:
        return hashes[0]
    mid = (n + 1) // 2
    left = _build(hashes[:mid], idxs[:mid], proofs)
    right = _build(hashes[mid:], idxs[mid:], proofs)
    for i in idxs[:mid]:
        proofs[i].aunts.append(right)
    for i in idxs[mid:]:
        proofs[i].aunts.append(left)
    return inner_hash(left, right)


def simple_proofs_from_byteslices(items: list[bytes]) -> tuple[bytes, list[SimpleProof]]:
    return simple_proofs_from_hashes([leaf_hash(it) for it in items])
