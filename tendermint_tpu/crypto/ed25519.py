"""Ed25519 (RFC 8032) — pure-Python reference implementation plus an
OpenSSL-backed fast path (via the `cryptography` package) when available.

Why both:
- The fast path is the honest CPU baseline the TPU kernel is benchmarked
  against (BASELINE.md north star: >=10x VerifyCommit throughput vs a
  sequential CPU verify loop, the reference's types/validator_set.go:247-250).
- The pure-Python path provides the exact group/field math used to derive
  test vectors and the precomputed tables for the JAX kernel
  (tendermint_tpu/ops/ed25519.py), and serves as the fallback when neither
  OpenSSL nor a TPU is present.

All integers little-endian per RFC 8032.
"""

from __future__ import annotations

import hashlib

# -- curve constants --------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493  # group order
D = (-121665 * pow(121666, P - 2, P)) % P  # edwards d
I_SQRT = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# base point
_By = 4 * pow(5, P - 2, P) % P


def _recover_x(y: int, sign: int) -> int | None:
    if y >= P:
        return None
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    if x2 == 0:
        if sign:
            return None
        return 0
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * I_SQRT % P
    if (x * x - x2) % P != 0:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


_Bx = _recover_x(_By, 0)
B = (_Bx, _By, 1, _Bx * _By % P)  # extended coords (X, Y, Z, T)
IDENT = (0, 1, 1, 0)


def point_add(p, q):
    """Extended-coordinates addition (complete formula, RFC 8032 section 5.1.4)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_double(p):
    """Dedicated doubling (RFC 8032 section 5.1.4 dbl-2008-hwcd)."""
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    bb = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = (a + bb) % P
    e = (h - (x1 + y1) * (x1 + y1)) % P
    g = (a - bb) % P
    f = (c + g) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def scalar_mult(s: int, p):
    q = IDENT
    while s > 0:
        if s & 1:
            q = point_add(q, p)
        p = point_double(p)
        s >>= 1
    return q


def point_equal(p, q) -> bool:
    # cross-multiply to avoid inversion
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def point_compress(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, P - 2, P)
    x, y = x * zinv % P, y * zinv % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def point_decompress(s: bytes):
    if len(s) != 32:
        return None
    y = int.from_bytes(s, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


# -- sign / verify ----------------------------------------------------------


def _sha512_int(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little")


def _secret_expand(secret: bytes):
    if len(secret) != 32:
        raise ValueError("bad secret length")
    h = hashlib.sha512(secret).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key_py(secret: bytes) -> bytes:
    a, _ = _secret_expand(secret)
    return point_compress(scalar_mult(a, B))


def sign_py(secret: bytes, msg: bytes) -> bytes:
    a, prefix = _secret_expand(secret)
    pub = point_compress(scalar_mult(a, B))
    r = _sha512_int(prefix, msg) % L
    big_r = point_compress(scalar_mult(r, B))
    h = _sha512_int(big_r, pub, msg) % L
    s = (r + h * a) % L
    return big_r + int.to_bytes(s, 32, "little")


def verify_py(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if len(sig) != 64:
        return False
    a_pt = point_decompress(pub)
    if a_pt is None:
        return False
    r_pt = point_decompress(sig[:32])
    if r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    h = _sha512_int(sig[:32], pub, msg) % L
    # [s]B == R + [h]A
    lhs = scalar_mult(s, B)
    rhs = point_add(r_pt, scalar_mult(h, a_pt))
    return point_equal(lhs, rhs)


# -- OpenSSL fast path ------------------------------------------------------

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    _HAVE_OPENSSL = True
except ImportError:  # pragma: no cover - env dependent
    _HAVE_OPENSSL = False


def public_key(secret: bytes) -> bytes:
    if _HAVE_OPENSSL:
        priv = Ed25519PrivateKey.from_private_bytes(secret)
        from cryptography.hazmat.primitives import serialization

        return priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
    return public_key_py(secret)


def sign(secret: bytes, msg: bytes) -> bytes:
    if _HAVE_OPENSSL:
        return Ed25519PrivateKey.from_private_bytes(secret).sign(msg)
    return sign_py(secret, msg)


def verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """Single-signature CPU verify — the sequential baseline. The batched hot
    path is ops.gateway.verify_batch."""
    if _HAVE_OPENSSL:
        if len(sig) != 64 or len(pub) != 32:
            return False
        try:
            Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            return False
    return verify_py(pub, msg, sig)
