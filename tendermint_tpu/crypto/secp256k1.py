"""secp256k1 ECDSA keys (go-crypto's second key type; reference usage
types/validator.go:75-86 — any crypto.PubKey can be a validator key).

In-repo implementation (Jacobian-coordinate point math + RFC 6979
deterministic nonces + minimal strict DER) with the `cryptography`
package (OpenSSL) as an opportunistic fast path — the same
no-third-party-dependency contract as crypto/x25519.py and
crypto/chacha20poly1305.py: the runtime image lacks `cryptography`, and
a missing package must never take out a key type. Wire shapes are
IDENTICAL across backends:

- private key: the 32-byte big-endian scalar;
- public key: 33-byte compressed SEC1 point;
- signature: ASN.1/DER ECDSA over SHA-256 of the message (variable
  length, ~70-72 bytes), low-s normalized so a third party cannot
  malleate a stored signature into a "different" valid one.

The pure signer uses RFC 6979 nonces (deterministic — same key + msg =
same signature); OpenSSL's uses random nonces. Both verify under either
backend, which the cross-check test pins (tests/test_secure_transport.py
runs it whenever the native package is importable).

secp256k1 stays a CPU key type: ECDSA's per-signature modular inversion
and point recovery don't map onto the MXU the way the ed25519 batch
equation does, and validator sets are expected to be ed25519 (the
reference ships secp256k1 primarily for account keys). The gateway
partitions batches by key type and routes these to this module.

Side channels: the pure path is not constant-time (Python big ints);
see docs/secure-p2p.md for the threat-model discussion.
"""

from __future__ import annotations

import hashlib
import hmac
import os

# -- curve constants (SEC2 2.4.1) -------------------------------------------

_P = 2**256 - 2**32 - 977
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
_B = 7

_INF = (0, 1, 0)  # Jacobian point at infinity (Z == 0)


def gen_secret() -> bytes:
    """A uniformly random 32-byte scalar in [1, n-1]."""
    while True:
        d = int.from_bytes(os.urandom(32), "big")
        if 1 <= d < _N:
            return d.to_bytes(32, "big")


def secret_from_seed(seed: bytes) -> bytes:
    """Deterministic scalar from secret material (sha256-folded like
    gen_priv_key_ed25519; re-hash on the negligible out-of-range case)."""
    d = seed
    while True:
        d = hashlib.sha256(d).digest()
        v = int.from_bytes(d, "big")
        if 1 <= v < _N:
            return d


# -- Jacobian point arithmetic (y^2 = x^3 + 7, a = 0) -------------------------


def _jdouble(pt):
    x1, y1, z1 = pt
    if z1 == 0 or y1 == 0:
        return _INF
    a = x1 * x1 % _P
    b = y1 * y1 % _P
    c = b * b % _P
    d = 2 * ((x1 + b) * (x1 + b) - a - c) % _P
    e = 3 * a % _P
    x3 = (e * e - 2 * d) % _P
    y3 = (e * (d - x3) - 8 * c) % _P
    z3 = 2 * y1 * z1 % _P
    return (x3, y3, z3)


def _jadd(p1, p2):
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    if z1 == 0:
        return p2
    if z2 == 0:
        return p1
    z1z1 = z1 * z1 % _P
    z2z2 = z2 * z2 % _P
    u1 = x1 * z2z2 % _P
    u2 = x2 * z1z1 % _P
    s1 = y1 * z2 * z2z2 % _P
    s2 = y2 * z1 * z1z1 % _P
    if u1 == u2:
        if s1 != s2:
            return _INF
        return _jdouble(p1)
    h = (u2 - u1) % _P
    r = (s2 - s1) % _P
    hh = h * h % _P
    hhh = h * hh % _P
    v = u1 * hh % _P
    x3 = (r * r - hhh - 2 * v) % _P
    y3 = (r * (v - x3) - s1 * hhh) % _P
    z3 = z1 * z2 % _P * h % _P
    return (x3, y3, z3)


def _jmul(k: int, pt):
    q = _INF
    while k > 0:
        if k & 1:
            q = _jadd(q, pt)
        pt = _jdouble(pt)
        k >>= 1
    return q


def _to_affine(pt):
    x, y, z = pt
    if z == 0:
        return None
    zi = pow(z, _P - 2, _P)
    zi2 = zi * zi % _P
    return (x * zi2 % _P, y * zi2 % _P * zi % _P)


_G = (_GX, _GY, 1)


def _decompress(pub33: bytes):
    """Affine point from a 33-byte compressed SEC1 encoding, or None."""
    if len(pub33) != 33 or pub33[0] not in (2, 3):
        return None
    x = int.from_bytes(pub33[1:], "big")
    if x >= _P:
        return None
    y2 = (x * x % _P * x + _B) % _P
    y = pow(y2, (_P + 1) // 4, _P)  # p == 3 (mod 4)
    if y * y % _P != y2:
        return None  # not on the curve
    if (y & 1) != (pub33[0] & 1):
        y = _P - y
    return (x, y)


def _compress(x: int, y: int) -> bytes:
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


# -- DER (ASN.1 SEQUENCE of two INTEGERs, strict minimal encoding) ------------


def encode_der(r: int, s: int) -> bytes:
    def enc_int(v: int) -> bytes:
        raw = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
        if raw[0] & 0x80:
            raw = b"\x00" + raw
        return b"\x02" + bytes([len(raw)]) + raw

    body = enc_int(r) + enc_int(s)
    if len(body) > 0x7F:
        raise ValueError("DER signature body too long")
    return b"\x30" + bytes([len(body)]) + body


def decode_der(sig: bytes) -> tuple[int, int]:
    """(r, s) from a strict minimal DER ECDSA signature; raises
    ValueError on any malformation (trailing bytes, padded or negative
    integers, long-form lengths a 72-byte signature never needs)."""

    def dec_int(buf: bytes, off: int) -> tuple[int, int]:
        if off + 2 > len(buf) or buf[off] != 0x02:
            raise ValueError("DER: expected INTEGER")
        ln = buf[off + 1]
        if ln & 0x80 or ln == 0 or off + 2 + ln > len(buf):
            raise ValueError("DER: bad integer length")
        raw = buf[off + 2 : off + 2 + ln]
        if raw[0] & 0x80:
            raise ValueError("DER: negative integer")
        if ln > 1 and raw[0] == 0 and not raw[1] & 0x80:
            raise ValueError("DER: non-minimal integer")
        return int.from_bytes(raw, "big"), off + 2 + ln

    if len(sig) < 8 or sig[0] != 0x30:
        raise ValueError("DER: expected SEQUENCE")
    if sig[1] & 0x80 or sig[1] != len(sig) - 2:
        raise ValueError("DER: bad sequence length")
    r, off = dec_int(sig, 2)
    s, off = dec_int(sig, off)
    if off != len(sig):
        raise ValueError("DER: trailing bytes")
    return r, s


# -- RFC 6979 deterministic nonce ---------------------------------------------


def _rfc6979_k(secret: bytes, e: int):
    """Candidate nonces for (key, digest) per RFC 6979 section 3.2."""
    h1 = (e % _N).to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + secret + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + secret + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        yield int.from_bytes(v, "big")
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


# -- pure-Python ECDSA --------------------------------------------------------


def _digest_int(msg: bytes) -> int:
    return int.from_bytes(hashlib.sha256(msg).digest(), "big")


def public_key_py(secret32: bytes) -> bytes:
    d = int.from_bytes(secret32, "big")
    if not 1 <= d < _N:
        raise ValueError("secp256k1 secret out of range")
    x, y = _to_affine(_jmul(d, _G))
    return _compress(x, y)


def sign_py(secret32: bytes, msg: bytes) -> bytes:
    d = int.from_bytes(secret32, "big")
    if not 1 <= d < _N:
        raise ValueError("secp256k1 secret out of range")
    e = _digest_int(msg)
    for k in _rfc6979_k(secret32, e):
        if not 1 <= k < _N:
            continue
        pt = _to_affine(_jmul(k, _G))
        if pt is None:
            continue
        r = pt[0] % _N
        if r == 0:
            continue
        s = pow(k, _N - 2, _N) * (e + r * d) % _N
        if s == 0:
            continue
        if s > _N // 2:
            s = _N - s
        return encode_der(r, s)


def verify_py(pub33: bytes, msg: bytes, sig_der: bytes) -> bool:
    q = _decompress(pub33)
    if q is None:
        return False
    try:
        r, s = decode_der(sig_der)
    except ValueError:
        return False
    if not (1 <= r < _N and 1 <= s <= _N // 2):
        return False  # reject high-s (malleability) and degenerate sigs
    e = _digest_int(msg)
    si = pow(s, _N - 2, _N)
    u1 = e * si % _N
    u2 = r * si % _N
    pt = _to_affine(_jadd(_jmul(u1, _G), _jmul(u2, (q[0], q[1], 1))))
    if pt is None:
        return False
    return pt[0] % _N == r


# -- OpenSSL fast path --------------------------------------------------------

try:  # pragma: no cover - env dependent
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
        encode_dss_signature,
    )

    _CURVE = ec.SECP256K1()
    _HAVE_OPENSSL = True
except ImportError:  # pragma: no cover - env dependent
    _HAVE_OPENSSL = False


def public_key(secret32: bytes) -> bytes:
    """33-byte compressed SEC1 public point."""
    if not _HAVE_OPENSSL:
        return public_key_py(secret32)
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    priv = ec.derive_private_key(int.from_bytes(secret32, "big"), _CURVE)
    return priv.public_key().public_bytes(
        Encoding.X962, PublicFormat.CompressedPoint
    )


def sign(secret32: bytes, msg: bytes) -> bytes:
    """DER ECDSA-SHA256 signature, low-s normalized."""
    if not _HAVE_OPENSSL:
        return sign_py(secret32, msg)
    priv = ec.derive_private_key(int.from_bytes(secret32, "big"), _CURVE)
    der = priv.sign(msg, ec.ECDSA(hashes.SHA256()))
    r, s = decode_dss_signature(der)
    if s > _N // 2:
        s = _N - s
    return encode_dss_signature(r, s)


def verify(pub33: bytes, msg: bytes, sig_der: bytes) -> bool:
    if not _HAVE_OPENSSL:
        return verify_py(pub33, msg, sig_der)
    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(_CURVE, pub33)
        r, s = decode_dss_signature(sig_der)
        if not (1 <= r < _N and 1 <= s <= _N // 2):
            return False  # reject high-s (malleability) and degenerate sigs
        pub.verify(encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256()))
        return True
    except (InvalidSignature, ValueError):
        return False
