"""secp256k1 ECDSA keys (go-crypto's second key type; reference usage
types/validator.go:75-86 — any crypto.PubKey can be a validator key).

Backed by the `cryptography` package (OpenSSL). Wire shapes:
- private key: the 32-byte big-endian scalar;
- public key: 33-byte compressed SEC1 point;
- signature: ASN.1/DER ECDSA over SHA-256 of the message (variable
  length, ~70-72 bytes), low-s normalized so a third party cannot
  malleate a stored signature into a "different" valid one.

secp256k1 stays a CPU key type: ECDSA's per-signature modular inversion
and point recovery don't map onto the MXU the way the ed25519 batch
equation does, and validator sets are expected to be ed25519 (the
reference ships secp256k1 primarily for account keys). The gateway
partitions batches by key type and routes these to this module.
"""

from __future__ import annotations

import os

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    decode_dss_signature,
    encode_dss_signature,
)

_CURVE = ec.SECP256K1()
# group order n (SEC2): signatures are normalized to s <= n//2
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def gen_secret() -> bytes:
    """A uniformly random 32-byte scalar in [1, n-1]."""
    while True:
        d = int.from_bytes(os.urandom(32), "big")
        if 1 <= d < _N:
            return d.to_bytes(32, "big")


def secret_from_seed(seed: bytes) -> bytes:
    """Deterministic scalar from secret material (sha256-folded like
    gen_priv_key_ed25519; re-hash on the negligible out-of-range case)."""
    import hashlib

    d = seed
    while True:
        d = hashlib.sha256(d).digest()
        v = int.from_bytes(d, "big")
        if 1 <= v < _N:
            return d


def _priv(secret32: bytes) -> ec.EllipticCurvePrivateKey:
    return ec.derive_private_key(int.from_bytes(secret32, "big"), _CURVE)


def public_key(secret32: bytes) -> bytes:
    """33-byte compressed SEC1 public point."""
    from cryptography.hazmat.primitives.serialization import (
        Encoding,
        PublicFormat,
    )

    return _priv(secret32).public_key().public_bytes(
        Encoding.X962, PublicFormat.CompressedPoint
    )


def sign(secret32: bytes, msg: bytes) -> bytes:
    """DER ECDSA-SHA256 signature, low-s normalized."""
    der = _priv(secret32).sign(msg, ec.ECDSA(hashes.SHA256()))
    r, s = decode_dss_signature(der)
    if s > _N // 2:
        s = _N - s
    return encode_dss_signature(r, s)


def verify(pub33: bytes, msg: bytes, sig_der: bytes) -> bool:
    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(_CURVE, pub33)
        r, s = decode_dss_signature(sig_der)
        if not (1 <= r < _N and 1 <= s <= _N // 2):
            return False  # reject high-s (malleability) and degenerate sigs
        pub.verify(encode_dss_signature(r, s), msg, ec.ECDSA(hashes.SHA256()))
        return True
    except (InvalidSignature, ValueError):
        return False
