"""Typed keys/signatures with type-byte unions and RIPEMD-160 addresses —
the go-crypto equivalent (reference usage: types/validator.go:75-86,
types/priv_validator.go).

Wire shape kept from go-crypto: a key/signature serializes as a 1-byte type
tag followed by the raw bytes. Ed25519 (type byte 0x01) is the primary
validator key type with TPU-batched verification; Secp256k1 (0x02) is the
account-style second key type — bitcoin-shaped addresses
(ripemd160(sha256(compressed point))) and DER ECDSA signatures, verified
on CPU (see crypto/secp256k1.py for why it stays off the device).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.crypto.hashing import ripemd160

TYPE_ED25519 = 0x01
TYPE_SECP256K1 = 0x02


@dataclass(frozen=True)
class SignatureEd25519:
    raw: bytes  # 64 bytes

    TYPE = TYPE_ED25519

    def __post_init__(self):
        if len(self.raw) != 64:
            raise ValueError("ed25519 signature must be 64 bytes")

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "SignatureEd25519":
        if not isinstance(obj, (list, tuple)) or len(obj) != 2 or obj[0] != TYPE_ED25519:
            raise ValueError(f"unknown signature encoding {obj!r}")
        if not isinstance(obj[1], str) or len(obj[1]) != 128:
            raise ValueError("bad signature hex")
        return cls(bytes.fromhex(obj[1]))


@dataclass(frozen=True)
class PubKeyEd25519:
    raw: bytes  # 32 bytes

    TYPE = TYPE_ED25519

    def __post_init__(self):
        if len(self.raw) != 32:
            raise ValueError("ed25519 pubkey must be 32 bytes")

    def address(self) -> bytes:
        """20-byte account address: ripemd160 over the tagged key bytes
        (go-crypto PubKeyEd25519.Address equivalent)."""
        return ripemd160(self.bytes_())

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def verify_bytes(self, msg: bytes, sig: "SignatureEd25519") -> bool:
        """Sequential CPU verify — the reference hot path
        (types/vote_set.go:175). Batched verification goes through
        ops.gateway instead."""
        if not isinstance(sig, SignatureEd25519):
            return False
        return ed25519.verify(self.raw, msg, sig.raw)

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "PubKeyEd25519":
        # wire/handshake input: same shape contract as signature decoding
        # above — any violation is ValueError, never IndexError/TypeError
        if not isinstance(obj, (list, tuple)) or len(obj) != 2 or obj[0] != TYPE_ED25519:
            raise ValueError(f"unknown pubkey encoding {obj!r}")
        if not isinstance(obj[1], str) or len(obj[1]) != 64:
            raise ValueError("bad pubkey hex")
        return cls(bytes.fromhex(obj[1]))

    def __hash__(self):
        return hash(self.raw)


@dataclass(frozen=True)
class PrivKeyEd25519:
    raw: bytes  # 32-byte seed

    TYPE = TYPE_ED25519

    def __post_init__(self):
        if len(self.raw) != 32:
            raise ValueError("ed25519 privkey seed must be 32 bytes")

    def pub_key(self) -> PubKeyEd25519:
        return PubKeyEd25519(ed25519.public_key(self.raw))

    def sign(self, msg: bytes) -> SignatureEd25519:
        return SignatureEd25519(ed25519.sign(self.raw, msg))

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "PrivKeyEd25519":
        if obj[0] != TYPE_ED25519:
            raise ValueError(f"unknown privkey type {obj[0]}")
        return cls(bytes.fromhex(obj[1]))


@dataclass(frozen=True)
class SignatureSecp256k1:
    raw: bytes  # DER, variable length (~70-72 bytes)

    TYPE = TYPE_SECP256K1

    def __post_init__(self):
        if not 8 <= len(self.raw) <= 80:
            raise ValueError("implausible secp256k1 DER signature length")

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "SignatureSecp256k1":
        if not isinstance(obj, (list, tuple)) or len(obj) != 2 or obj[0] != TYPE_SECP256K1:
            raise ValueError(f"unknown signature encoding {obj!r}")
        if not isinstance(obj[1], str) or len(obj[1]) > 160:
            raise ValueError("bad signature hex")
        return cls(bytes.fromhex(obj[1]))


@dataclass(frozen=True)
class PubKeySecp256k1:
    raw: bytes  # 33-byte compressed SEC1 point

    TYPE = TYPE_SECP256K1

    def __post_init__(self):
        if len(self.raw) != 33:
            raise ValueError("secp256k1 pubkey must be 33 bytes (compressed)")

    def address(self) -> bytes:
        """Bitcoin-shaped: ripemd160(sha256(compressed point)) — the
        go-crypto PubKeySecp256k1.Address derivation
        (types/validator.go:75-86 consumes it opaquely)."""
        import hashlib

        from tendermint_tpu.crypto.hashing import ripemd160 as _r160

        return _r160(hashlib.sha256(self.raw).digest())

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def verify_bytes(self, msg: bytes, sig) -> bool:
        from tendermint_tpu.crypto import secp256k1

        if not isinstance(sig, SignatureSecp256k1):
            return False
        return secp256k1.verify(self.raw, msg, sig.raw)

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "PubKeySecp256k1":
        if obj[0] != TYPE_SECP256K1:
            raise ValueError(f"unknown pubkey type {obj[0]}")
        return cls(bytes.fromhex(obj[1]))

    def __hash__(self):
        return hash(self.raw)


@dataclass(frozen=True)
class PrivKeySecp256k1:
    raw: bytes  # 32-byte big-endian scalar

    TYPE = TYPE_SECP256K1

    def __post_init__(self):
        if len(self.raw) != 32:
            raise ValueError("secp256k1 privkey must be 32 bytes")

    def pub_key(self) -> PubKeySecp256k1:
        from tendermint_tpu.crypto import secp256k1

        return PubKeySecp256k1(secp256k1.public_key(self.raw))

    def sign(self, msg: bytes) -> SignatureSecp256k1:
        from tendermint_tpu.crypto import secp256k1

        return SignatureSecp256k1(secp256k1.sign(self.raw, msg))

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "PrivKeySecp256k1":
        if obj[0] != TYPE_SECP256K1:
            raise ValueError(f"unknown privkey type {obj[0]}")
        return cls(bytes.fromhex(obj[1]))


def gen_priv_key_secp256k1(seed: bytes | None = None) -> PrivKeySecp256k1:
    from tendermint_tpu.crypto import secp256k1

    if seed is None:
        return PrivKeySecp256k1(secp256k1.gen_secret())
    return PrivKeySecp256k1(secp256k1.secret_from_seed(seed))


def gen_priv_key_ed25519(seed: bytes | None = None) -> PrivKeyEd25519:
    """Random key, or a key derived from secret material. The secret is
    ALWAYS sha256-hashed regardless of its length (go-crypto
    GenPrivKeyEd25519FromSecret semantics) so derivation can't silently
    change behavior at the 32-byte boundary."""
    if seed is None:
        return PrivKeyEd25519(os.urandom(32))
    import hashlib

    return PrivKeyEd25519(hashlib.sha256(seed).digest())


def pub_key_from_bytes(b: bytes):
    """Type-tagged key bytes (the `bytes_()` encoding) back to a key
    object — wire input: any violation is ValueError."""
    if not isinstance(b, (bytes, bytearray)) or len(b) < 1:
        raise ValueError("empty pubkey bytes")
    if b[0] == TYPE_ED25519:
        return PubKeyEd25519(bytes(b[1:]))
    if b[0] == TYPE_SECP256K1:
        return PubKeySecp256k1(bytes(b[1:]))
    raise ValueError(f"unknown pubkey type {b[0]}")


def pub_key_from_json(obj):
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise ValueError(f"unknown pubkey encoding {obj!r}")
    if obj[0] == TYPE_ED25519:
        return PubKeyEd25519.from_json(obj)
    if obj[0] == TYPE_SECP256K1:
        return PubKeySecp256k1.from_json(obj)
    raise ValueError(f"unknown pubkey type {obj[0]}")


def priv_key_from_json(obj):
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise ValueError(f"unknown privkey encoding {obj!r}")
    if obj[0] == TYPE_ED25519:
        return PrivKeyEd25519.from_json(obj)
    if obj[0] == TYPE_SECP256K1:
        return PrivKeySecp256k1.from_json(obj)
    raise ValueError(f"unknown privkey type {obj[0]}")


def signature_from_json(obj):
    if not isinstance(obj, (list, tuple)) or len(obj) != 2:
        raise ValueError(f"unknown signature encoding {obj!r}")
    if obj[0] == TYPE_ED25519:
        return SignatureEd25519.from_json(obj)
    if obj[0] == TYPE_SECP256K1:
        return SignatureSecp256k1.from_json(obj)
    raise ValueError(f"unknown signature type {obj[0]}")


def verify_any(pubkey_bytes: bytes, msg: bytes, sig_bytes: bytes) -> bool:
    """Raw-bytes verification dispatching on key shape (32 = ed25519 seed
    point, 33 = compressed secp256k1). The CPU half of the gateway: batch
    items carry raw bytes, not typed objects."""
    if len(pubkey_bytes) == 32:
        from tendermint_tpu.crypto import ed25519

        return ed25519.verify(pubkey_bytes, msg, sig_bytes)
    if len(pubkey_bytes) == 33:
        from tendermint_tpu.crypto import secp256k1

        return secp256k1.verify(pubkey_bytes, msg, sig_bytes)
    return False
