"""Typed keys/signatures with type-byte unions and RIPEMD-160 addresses —
the go-crypto equivalent (reference usage: types/validator.go:75-86,
types/priv_validator.go).

Wire shape kept from go-crypto: a key/signature serializes as a 1-byte type
tag followed by the raw bytes; an address is ripemd160(tag || raw_pubkey).
Ed25519 is the validator key type (type byte 0x01); Secp256k1 (0x02) is
reserved and unimplemented here, gated the way the reference gates unused
key types.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from tendermint_tpu.crypto import ed25519
from tendermint_tpu.crypto.hashing import ripemd160

TYPE_ED25519 = 0x01
TYPE_SECP256K1 = 0x02


@dataclass(frozen=True)
class SignatureEd25519:
    raw: bytes  # 64 bytes

    TYPE = TYPE_ED25519

    def __post_init__(self):
        if len(self.raw) != 64:
            raise ValueError("ed25519 signature must be 64 bytes")

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "SignatureEd25519":
        if not isinstance(obj, (list, tuple)) or len(obj) != 2 or obj[0] != TYPE_ED25519:
            raise ValueError(f"unknown signature encoding {obj!r}")
        if not isinstance(obj[1], str) or len(obj[1]) != 128:
            raise ValueError("bad signature hex")
        return cls(bytes.fromhex(obj[1]))


@dataclass(frozen=True)
class PubKeyEd25519:
    raw: bytes  # 32 bytes

    TYPE = TYPE_ED25519

    def __post_init__(self):
        if len(self.raw) != 32:
            raise ValueError("ed25519 pubkey must be 32 bytes")

    def address(self) -> bytes:
        """20-byte account address: ripemd160 over the tagged key bytes
        (go-crypto PubKeyEd25519.Address equivalent)."""
        return ripemd160(self.bytes_())

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def verify_bytes(self, msg: bytes, sig: "SignatureEd25519") -> bool:
        """Sequential CPU verify — the reference hot path
        (types/vote_set.go:175). Batched verification goes through
        ops.gateway instead."""
        if not isinstance(sig, SignatureEd25519):
            return False
        return ed25519.verify(self.raw, msg, sig.raw)

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "PubKeyEd25519":
        if obj[0] != TYPE_ED25519:
            raise ValueError(f"unknown pubkey type {obj[0]}")
        return cls(bytes.fromhex(obj[1]))

    def __hash__(self):
        return hash(self.raw)


@dataclass(frozen=True)
class PrivKeyEd25519:
    raw: bytes  # 32-byte seed

    TYPE = TYPE_ED25519

    def __post_init__(self):
        if len(self.raw) != 32:
            raise ValueError("ed25519 privkey seed must be 32 bytes")

    def pub_key(self) -> PubKeyEd25519:
        return PubKeyEd25519(ed25519.public_key(self.raw))

    def sign(self, msg: bytes) -> SignatureEd25519:
        return SignatureEd25519(ed25519.sign(self.raw, msg))

    def bytes_(self) -> bytes:
        return bytes([self.TYPE]) + self.raw

    def to_json(self):
        return [self.TYPE, self.raw.hex().upper()]

    @classmethod
    def from_json(cls, obj) -> "PrivKeyEd25519":
        if obj[0] != TYPE_ED25519:
            raise ValueError(f"unknown privkey type {obj[0]}")
        return cls(bytes.fromhex(obj[1]))


def gen_priv_key_ed25519(seed: bytes | None = None) -> PrivKeyEd25519:
    """Random key, or a key derived from secret material. The secret is
    ALWAYS sha256-hashed regardless of its length (go-crypto
    GenPrivKeyEd25519FromSecret semantics) so derivation can't silently
    change behavior at the 32-byte boundary."""
    if seed is None:
        return PrivKeyEd25519(os.urandom(32))
    import hashlib

    return PrivKeyEd25519(hashlib.sha256(seed).digest())


def pub_key_from_json(obj):
    if obj[0] == TYPE_ED25519:
        return PubKeyEd25519.from_json(obj)
    raise ValueError(f"unknown pubkey type {obj[0]}")
