"""X25519 (RFC 7748) — in-repo Montgomery-ladder implementation plus an
OpenSSL-backed fast path (via the `cryptography` package) when available.

This closes the last third-party crypto hole in the repo: the p2p
SecretConnection (PAPER.md layer 2, station-to-station handshake) used to
import `cryptography` unconditionally, which the runtime image lacks —
every multi-node tier-1 test therefore rode loopback fabrics. The
pure-Python ladder below is pinned to the RFC 7748 section 5.2/6.1 test
vectors (tests/test_secure_transport.py, incl. the 1000-iteration ladder
vector) and the native backend, when importable, is used opportunistically
AND cross-checked byte-for-byte as a parity oracle.

Backend selection: TENDERMINT_SECRETCONN_BACKEND = auto|pure|native
(auto = native when importable, else pure; `native` without the package
raises loudly at first use — an operator pinning a backend must not get a
silent fallback).

Side channels: Python big-int arithmetic is not constant-time, so neither
is this ladder (the cswap is data-dependent). That is the documented
trade: the keys exchanged here are EPHEMERAL per-connection handshake
keys (docs/secure-p2p.md threat model), and hosts wanting hardened
primitives install `cryptography` and get the OpenSSL path.

All integers little-endian per RFC 7748.
"""

from __future__ import annotations

import os

from tendermint_tpu.libs.envknob import env_str

P = 2**255 - 19
_A24 = 121665
BASE_POINT = (9).to_bytes(32, "little")


class X25519Error(ValueError):
    """Malformed key bytes or an all-zero shared secret (low-order
    peer point — RFC 7748 section 6.1 MUST-check for this protocol)."""


def _decode_scalar(k: bytes) -> int:
    if len(k) != 32:
        raise X25519Error(f"x25519 scalar must be 32 bytes, got {len(k)}")
    a = bytearray(k)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(a, "little")


def _decode_u(u: bytes) -> int:
    if len(u) != 32:
        raise X25519Error(f"x25519 point must be 32 bytes, got {len(u)}")
    # mask the high bit (RFC 7748 section 5: implementations MUST)
    return int.from_bytes(u, "little") & ((1 << 255) - 1)


def scalar_mult(k: bytes, u: bytes) -> bytes:
    """RFC 7748 section 5 X25519: Montgomery ladder over Curve25519.
    Returns the raw 32-byte u-coordinate (possibly all-zero — the
    protocol-level check lives in `x25519`)."""
    key = _decode_scalar(k)
    x1 = _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        kt = (key >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        # one ladder step (RFC 7748 section 5 pseudocode)
        a = (x2 + z2) % P
        aa = a * a % P
        b = (x2 - z2) % P
        bb = b * b % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = d * a % P
        cb = c * b % P
        x3 = (da + cb) % P
        x3 = x3 * x3 % P
        z3 = (da - cb) % P
        z3 = z3 * z3 % P
        z3 = z3 * x1 % P
        x2 = aa * bb % P
        z2 = e * (aa + _A24 * e) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, P - 2, P) % P
    return out.to_bytes(32, "little")


def x25519(k: bytes, u: bytes) -> bytes:
    """Diffie-Hellman shared secret; raises X25519Error on an all-zero
    result (peer sent a low-order point), matching the native backend's
    `exchange` behavior byte-for-byte."""
    out = scalar_mult(k, u)
    if out == b"\x00" * 32:
        raise X25519Error("x25519: all-zero shared secret (low-order point)")
    return out


def public_from_private(k: bytes) -> bytes:
    return scalar_mult(k, BASE_POINT)


# -- backend selection --------------------------------------------------------

from tendermint_tpu.crypto import _openssl

try:  # pragma: no cover - env dependent
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey as _NativePriv,
        X25519PublicKey as _NativePub,
    )

    _HAVE_NATIVE = True
except ImportError:  # pragma: no cover - env dependent
    _HAVE_NATIVE = False


def have_native() -> bool:
    return _HAVE_NATIVE


def resolve_backend(knob: str = "TENDERMINT_SECRETCONN_BACKEND") -> str:
    """'pure', 'native' (the `cryptography` package) or 'openssl'
    (ctypes straight into libcrypto — crypto/_openssl.py) per the env
    knob, shared with the AEAD module. auto prefers native > openssl >
    pure; a PINNED backend that is unavailable raises — never a silent
    downgrade of an explicit operator choice."""
    choice = env_str(knob, "auto", allowed=("auto", "pure", "native", "openssl"))
    if choice == "native" and not _HAVE_NATIVE:
        raise RuntimeError(
            f"{knob}=native but the `cryptography` package is not importable"
        )
    if choice == "openssl" and not _openssl.available():
        raise RuntimeError(f"{knob}=openssl but no usable libcrypto was found")
    if choice == "auto":
        if _HAVE_NATIVE:
            return "native"
        return "openssl" if _openssl.available() else "pure"
    return choice


# -- key objects (the exact interface secret_connection.py consumes) ----------


class X25519PublicKey:
    __slots__ = ("_raw",)

    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise X25519Error(f"x25519 public key must be 32 bytes, got {len(raw)}")
        self._raw = bytes(raw)

    @classmethod
    def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
        return cls(raw)

    def public_bytes_raw(self) -> bytes:
        return self._raw


class X25519PrivateKey:
    """Ephemeral handshake key. `backend` records which implementation
    serves `exchange` ('pure'|'native') — surfaced by the node log and
    the p2p_secretconn_* telemetry so an operator can see which path a
    box runs."""

    __slots__ = ("_raw", "backend")

    def __init__(self, raw: bytes, backend: str | None = None):
        if len(raw) != 32:
            raise X25519Error(f"x25519 private key must be 32 bytes, got {len(raw)}")
        self._raw = bytes(raw)
        self.backend = backend if backend is not None else resolve_backend()

    @classmethod
    def generate(cls, backend: str | None = None) -> "X25519PrivateKey":
        return cls(os.urandom(32), backend=backend)

    @classmethod
    def from_private_bytes(cls, raw: bytes, backend: str | None = None) -> "X25519PrivateKey":
        return cls(raw, backend=backend)

    def private_bytes_raw(self) -> bytes:
        return self._raw

    def public_key(self) -> X25519PublicKey:
        if self.backend == "native":
            priv = _NativePriv.from_private_bytes(self._raw)
            return X25519PublicKey(priv.public_key().public_bytes_raw())
        if self.backend == "openssl":
            return X25519PublicKey(_openssl.x25519_public(self._raw))
        return X25519PublicKey(public_from_private(self._raw))

    def exchange(self, peer: X25519PublicKey) -> bytes:
        if self.backend == "native":
            try:
                return _NativePriv.from_private_bytes(self._raw).exchange(
                    _NativePub.from_public_bytes(peer.public_bytes_raw())
                )
            except ValueError as exc:
                # OpenSSL raises on the all-zero shared secret; keep ONE
                # exception type across backends so callers triage alike
                raise X25519Error(str(exc)) from exc
        if self.backend == "openssl":
            out = _openssl.x25519_derive(self._raw, peer.public_bytes_raw())
            if out is None:
                raise X25519Error(
                    "x25519: all-zero shared secret (low-order point)"
                )
            return out
        return x25519(self._raw, peer.public_bytes_raw())
