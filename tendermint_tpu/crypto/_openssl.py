"""ctypes bindings to the system libcrypto (OpenSSL >= 1.1.1) for the
SecretConnection primitives — an opportunistic fast path that needs NO
third-party Python package: the runtime image lacks `cryptography`, but
it does ship libcrypto.so, and per-frame AEAD in pure Python costs ~1 ms
while EVP does it in ~10 us. Everything here is optional: `available()`
is False when the library (or any needed symbol) is missing, and the
callers (crypto/x25519.py, crypto/chacha20poly1305.py) fall back to the
RFC-vector-pinned pure-Python implementations, which also serve as the
parity oracle for these bindings (tests/test_secure_transport.py
cross-checks byte-for-byte).

Scope is deliberately tiny — exactly the two primitives the transport
needs: ChaCha20-Poly1305 seal/open via the EVP AEAD interface, and
X25519 keygen/derive via the raw-key EVP_PKEY interface. Every call
allocates its own ctx and frees it in a finally block (OpenSSL >= 1.1 is
thread-safe with per-call contexts)."""

from __future__ import annotations

import ctypes
import ctypes.util

_EVP_CTRL_AEAD_SET_IVLEN = 0x09
_EVP_CTRL_AEAD_GET_TAG = 0x10
_EVP_CTRL_AEAD_SET_TAG = 0x11
_NID_X25519 = 1034
TAG_LEN = 16

_SYMS = (
    "EVP_chacha20_poly1305",
    "EVP_CIPHER_CTX_new",
    "EVP_CIPHER_CTX_free",
    "EVP_CIPHER_CTX_ctrl",
    "EVP_EncryptInit_ex",
    "EVP_EncryptUpdate",
    "EVP_EncryptFinal_ex",
    "EVP_DecryptInit_ex",
    "EVP_DecryptUpdate",
    "EVP_DecryptFinal_ex",
    "EVP_PKEY_new_raw_private_key",
    "EVP_PKEY_new_raw_public_key",
    "EVP_PKEY_get_raw_public_key",
    "EVP_PKEY_free",
    "EVP_PKEY_CTX_new",
    "EVP_PKEY_CTX_free",
    "EVP_PKEY_derive_init",
    "EVP_PKEY_derive_set_peer",
    "EVP_PKEY_derive",
)


def _load():
    name = ctypes.util.find_library("crypto")
    if not name:
        return None
    try:
        lib = ctypes.CDLL(name)
    except OSError:
        return None
    if any(not hasattr(lib, s) for s in _SYMS):
        return None
    p, i, cp = ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p
    ip = ctypes.POINTER(ctypes.c_int)
    sp = ctypes.POINTER(ctypes.c_size_t)
    # declare every signature explicitly: on LP64 a defaulted int return
    # truncates pointers, which is exactly the kind of silent corruption
    # a crypto binding cannot have
    lib.EVP_chacha20_poly1305.restype = p
    lib.EVP_chacha20_poly1305.argtypes = ()
    lib.EVP_CIPHER_CTX_new.restype = p
    lib.EVP_CIPHER_CTX_new.argtypes = ()
    lib.EVP_CIPHER_CTX_free.restype = None
    lib.EVP_CIPHER_CTX_free.argtypes = (p,)
    lib.EVP_CIPHER_CTX_ctrl.restype = i
    lib.EVP_CIPHER_CTX_ctrl.argtypes = (p, i, i, p)
    for fn in (lib.EVP_EncryptInit_ex, lib.EVP_DecryptInit_ex):
        fn.restype = i
        fn.argtypes = (p, p, p, cp, cp)
    for fn in (lib.EVP_EncryptUpdate, lib.EVP_DecryptUpdate):
        fn.restype = i
        fn.argtypes = (p, cp, ip, cp, i)
    for fn in (lib.EVP_EncryptFinal_ex, lib.EVP_DecryptFinal_ex):
        fn.restype = i
        fn.argtypes = (p, cp, ip)
    lib.EVP_PKEY_new_raw_private_key.restype = p
    lib.EVP_PKEY_new_raw_private_key.argtypes = (i, p, cp, ctypes.c_size_t)
    lib.EVP_PKEY_new_raw_public_key.restype = p
    lib.EVP_PKEY_new_raw_public_key.argtypes = (i, p, cp, ctypes.c_size_t)
    lib.EVP_PKEY_get_raw_public_key.restype = i
    lib.EVP_PKEY_get_raw_public_key.argtypes = (p, cp, sp)
    lib.EVP_PKEY_free.restype = None
    lib.EVP_PKEY_free.argtypes = (p,)
    lib.EVP_PKEY_CTX_new.restype = p
    lib.EVP_PKEY_CTX_new.argtypes = (p, p)
    lib.EVP_PKEY_CTX_free.restype = None
    lib.EVP_PKEY_CTX_free.argtypes = (p,)
    lib.EVP_PKEY_derive_init.restype = i
    lib.EVP_PKEY_derive_init.argtypes = (p,)
    lib.EVP_PKEY_derive_set_peer.restype = i
    lib.EVP_PKEY_derive_set_peer.argtypes = (p, p)
    lib.EVP_PKEY_derive.restype = i
    lib.EVP_PKEY_derive.argtypes = (p, cp, sp)
    return lib


_LIB = _load()


def available() -> bool:
    return _LIB is not None


class OpenSSLError(RuntimeError):
    """An EVP call failed where the inputs were valid — misuse or a
    broken library, never a routine condition (tag mismatch returns a
    status, not this)."""


# -- ChaCha20-Poly1305 --------------------------------------------------------


def aead_seal(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes) -> bytes:
    lib = _LIB
    outl = ctypes.c_int(0)
    out = ctypes.create_string_buffer(len(plaintext) + TAG_LEN)
    ctx = lib.EVP_CIPHER_CTX_new()
    if not ctx:
        raise OpenSSLError("EVP_CIPHER_CTX_new failed")
    try:
        ok = lib.EVP_EncryptInit_ex(ctx, lib.EVP_chacha20_poly1305(), None, None, None)
        ok &= lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_IVLEN, len(nonce), None)
        ok &= lib.EVP_EncryptInit_ex(ctx, None, None, key, nonce)
        if ok and aad:
            ok &= lib.EVP_EncryptUpdate(ctx, None, ctypes.byref(outl), aad, len(aad))
        n = 0
        if ok and plaintext:
            ok &= lib.EVP_EncryptUpdate(
                ctx, out, ctypes.byref(outl), plaintext, len(plaintext)
            )
            n = outl.value
        if ok:
            ok &= lib.EVP_EncryptFinal_ex(
                ctx, ctypes.cast(ctypes.byref(out, n), ctypes.c_char_p),
                ctypes.byref(outl),
            )
            n += outl.value
        tag = ctypes.create_string_buffer(TAG_LEN)
        if ok:
            ok &= lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_GET_TAG, TAG_LEN, tag)
        if not ok or n != len(plaintext):
            raise OpenSSLError("chacha20-poly1305 seal failed")
        return out.raw[:n] + tag.raw
    finally:
        lib.EVP_CIPHER_CTX_free(ctx)


def aead_open(key: bytes, nonce: bytes, boxed: bytes, aad: bytes) -> bytes | None:
    """Plaintext, or None on authentication failure (the caller owns the
    exception type so triage is backend-independent)."""
    if len(boxed) < TAG_LEN:
        return None
    ct, tag = boxed[:-TAG_LEN], boxed[-TAG_LEN:]
    lib = _LIB
    outl = ctypes.c_int(0)
    out = ctypes.create_string_buffer(max(1, len(ct)))
    ctx = lib.EVP_CIPHER_CTX_new()
    if not ctx:
        raise OpenSSLError("EVP_CIPHER_CTX_new failed")
    try:
        ok = lib.EVP_DecryptInit_ex(ctx, lib.EVP_chacha20_poly1305(), None, None, None)
        ok &= lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_IVLEN, len(nonce), None)
        ok &= lib.EVP_DecryptInit_ex(ctx, None, None, key, nonce)
        if ok and aad:
            ok &= lib.EVP_DecryptUpdate(ctx, None, ctypes.byref(outl), aad, len(aad))
        n = 0
        if ok and ct:
            ok &= lib.EVP_DecryptUpdate(ctx, out, ctypes.byref(outl), ct, len(ct))
            n = outl.value
        if ok:
            ok &= lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_TAG, TAG_LEN, tag)
        if not ok:
            raise OpenSSLError("chacha20-poly1305 open setup failed")
        # final returns 0 on tag mismatch — the one ROUTINE failure here
        if not lib.EVP_DecryptFinal_ex(
            ctx, ctypes.cast(ctypes.byref(out, n), ctypes.c_char_p),
            ctypes.byref(outl),
        ):
            return None
        return out.raw[: n + outl.value]
    finally:
        lib.EVP_CIPHER_CTX_free(ctx)


# -- X25519 -------------------------------------------------------------------


def x25519_public(priv: bytes) -> bytes:
    lib = _LIB
    pkey = lib.EVP_PKEY_new_raw_private_key(_NID_X25519, None, priv, len(priv))
    if not pkey:
        raise OpenSSLError("X25519 private key rejected")
    try:
        n = ctypes.c_size_t(32)
        buf = ctypes.create_string_buffer(32)
        if not lib.EVP_PKEY_get_raw_public_key(pkey, buf, ctypes.byref(n)):
            raise OpenSSLError("X25519 public key extraction failed")
        return buf.raw[: n.value]
    finally:
        lib.EVP_PKEY_free(pkey)


def x25519_derive(priv: bytes, peer_pub: bytes) -> bytes | None:
    """Shared secret, or None when libcrypto rejects the exchange (it
    refuses low-order peer points with an all-zero output itself)."""
    lib = _LIB
    pkey = lib.EVP_PKEY_new_raw_private_key(_NID_X25519, None, priv, len(priv))
    if not pkey:
        raise OpenSSLError("X25519 private key rejected")
    peer = None
    pctx = None
    try:
        peer = lib.EVP_PKEY_new_raw_public_key(_NID_X25519, None, peer_pub, len(peer_pub))
        if not peer:
            return None
        pctx = lib.EVP_PKEY_CTX_new(pkey, None)
        if not pctx:
            raise OpenSSLError("EVP_PKEY_CTX_new failed")
        if not lib.EVP_PKEY_derive_init(pctx):
            raise OpenSSLError("EVP_PKEY_derive_init failed")
        if not lib.EVP_PKEY_derive_set_peer(pctx, peer):
            return None
        n = ctypes.c_size_t(32)
        buf = ctypes.create_string_buffer(32)
        if not lib.EVP_PKEY_derive(pctx, buf, ctypes.byref(n)):
            return None
        return buf.raw[: n.value]
    finally:
        if pctx:
            lib.EVP_PKEY_CTX_free(pctx)
        if peer:
            lib.EVP_PKEY_free(peer)
        lib.EVP_PKEY_free(pkey)
