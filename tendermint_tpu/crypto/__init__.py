"""Host crypto: Ed25519 keys/signatures, SHA-256/RIPEMD-160 hashing,
addresses — the equivalent of the reference's go-crypto dependency
(SURVEY.md section 2.2). The batched TPU verification path lives in
`tendermint_tpu.ops`; this package is the CPU reference implementation and
the signing side (signing is inherently sequential and stays on host).
"""

from tendermint_tpu.crypto.hashing import ripemd160, sha256
from tendermint_tpu.crypto.keys import (
    PrivKeyEd25519,
    PubKeyEd25519,
    SignatureEd25519,
    gen_priv_key_ed25519,
)

__all__ = [
    "ripemd160",
    "sha256",
    "PrivKeyEd25519",
    "PubKeyEd25519",
    "SignatureEd25519",
    "gen_priv_key_ed25519",
]
