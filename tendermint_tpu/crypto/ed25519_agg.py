"""Ed25519 half-aggregation — the aggregated-signature design point of
"Performance of EdDSA and BLS Signatures in Committee-Based Consensus"
(arXiv 2302.00418) without leaving the chain's existing key type.

n Ed25519 signatures (R_i, s_i) over (A_i, m_i) collapse into
(R_1..R_n, s_agg): the R points must travel (they bind each signer's
nonce), but the n scalars fold into ONE via a Fiat-Shamir random linear
combination — HALF the signature bytes, verified in a single multi-term
equation:

    s_agg = sum_i z_i * s_i  (mod L)
    accept iff  [s_agg]B == sum_i [z_i]R_i + [z_i * h_i]A_i

with h_i = SHA512(R_i || A_i || m_i) mod L (the standard Ed25519
challenge — verifying lanes exactly as RFC 8032 would) and coefficients
z_i = SHA512(DOM || T || LE64(i)) mod L bound to the FULL transcript
T = SHA512(DOM, all R_i, A_i, SHA512(m_i)). Because every z_i depends on
every lane, no subset of signers can cancel another's forged lane: a
single invalid (R_i, s_i) makes the aggregate fail with overwhelming
probability (the standard random-linear-combination soundness argument).

Aggregation itself is untrusted bookkeeping — pure scalar arithmetic, no
secret keys — so any relay can shrink a commit it gossips; verification
is the sole authority.

Prototype caveats (docs/committee.md): pure-python group math off
crypto/ed25519 (verification touches only public data, so variable-time
is acceptable; DO NOT sign here), and no effort to reject mixed-key
lanes beyond shape checks — the caller (types/agg_commit.py) filters to
ed25519 lanes.
"""

from __future__ import annotations

import hashlib

from tendermint_tpu.crypto.ed25519 import (
    B,
    IDENT,
    L,
    P,
    point_add,
    point_decompress,
    point_equal,
    scalar_mult,
)

_DOM = b"tendermint-tpu/ed25519-halfagg/v1"


def _challenge(big_r: bytes, pub: bytes, msg: bytes) -> int:
    """The per-lane RFC 8032 challenge h_i = H(R || A || M) mod L."""
    return int.from_bytes(
        hashlib.sha512(big_r + pub + msg).digest(), "little"
    ) % L


def _coefficients(pubs: list[bytes], msgs: list[bytes],
                  rs: list[bytes]) -> list[int]:
    """Fiat-Shamir lane coefficients over the full transcript. z_i != 0
    by construction (0 would let lane i escape the equation)."""
    t = hashlib.sha512(_DOM)
    for big_r, pub, msg in zip(rs, pubs, msgs):
        t.update(big_r)
        t.update(pub)
        t.update(hashlib.sha512(msg).digest())
    transcript = t.digest()
    out = []
    for i in range(len(rs)):
        z = int.from_bytes(
            hashlib.sha512(
                _DOM + transcript + i.to_bytes(8, "little")
            ).digest(),
            "little",
        ) % L
        out.append(z or 1)
    return out


def aggregate(items: list[tuple[bytes, bytes, bytes]]) -> tuple[list[bytes], bytes]:
    """Collapse [(pub32, msg, sig64)] into (R list, 32-byte s_agg).
    Raises ValueError on malformed lane shapes (aggregation never proves
    anything — a lane carrying an INVALID signature aggregates fine and
    fails at verify_aggregate)."""
    if not items:
        raise ValueError("nothing to aggregate")
    pubs, msgs, rs, ss = [], [], [], []
    for pub, msg, sig in items:
        if len(pub) != 32 or len(sig) != 64:
            raise ValueError("half-aggregation needs 32B ed25519 keys / 64B sigs")
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            raise ValueError("non-canonical signature scalar")
        pubs.append(bytes(pub))
        msgs.append(bytes(msg))
        rs.append(bytes(sig[:32]))
        ss.append(s)
    zs = _coefficients(pubs, msgs, rs)
    s_agg = sum(z * s for z, s in zip(zs, ss)) % L
    return rs, int.to_bytes(s_agg, 32, "little")


def verify_aggregate(pubs: list[bytes], msgs: list[bytes], rs: list[bytes],
                     s_agg: bytes) -> bool:
    """True iff (rs, s_agg) is a valid half-aggregate of one Ed25519
    signature per (pub, msg) lane. Any tampered lane — R, key, message,
    or the folded scalar — fails the whole equation."""
    if not pubs or not (len(pubs) == len(msgs) == len(rs)):
        return False
    if len(s_agg) != 32:
        return False
    s = int.from_bytes(s_agg, "little")
    if s >= L:
        return False
    zs = _coefficients(pubs, msgs, rs)
    acc = IDENT
    for z, big_r, pub, msg in zip(zs, rs, pubs, msgs):
        r_pt = point_decompress(big_r)
        a_pt = point_decompress(pub)
        if r_pt is None or a_pt is None:
            return False
        h = _challenge(big_r, pub, msg)
        acc = point_add(acc, scalar_mult(z, r_pt))
        acc = point_add(acc, scalar_mult(z * h % L, a_pt))
    return point_equal(scalar_mult(s, B), acc)


# -- device-plane decomposition (ops/gateway.Verifier.verify_aggregate) ----
#
# The equation above is n+1 scalar multiplications — the ~4.5 ms/lane
# host cost the gateway batches away. Each lane decomposes into ONE
# dual-scalar-mul term [a]P + [b]Q (ops/ed25519.dsm_batch computes all
# lanes in one device dispatch):
#
#     lane i < n:  [z_i]R_i + [(z_i * h_i) mod L]A_i
#     lane n:      [s_agg]B + [0]IDENT            (the left-hand side)
#
# The host keeps only the cheap parts: SHA-512 transcripts, point
# decompression (cached per validator in ops/ed25519), and the final
# n-term point sum + equality.

_B_AFFINE = (B[0] * pow(B[2], P - 2, P) % P, B[1] * pow(B[2], P - 2, P) % P)
_IDENT_AFFINE = (0, 1)


def aggregate_terms(pubs: list[bytes], msgs: list[bytes], rs: list[bytes],
                    s_agg: bytes):
    """Decompose the half-aggregate check into n+1 dual-scalar-mul terms
    [(a, P_affine, b, Q_affine)] for ops/ed25519.dsm_batch; None when
    the aggregate is structurally invalid (same refusals as
    verify_aggregate's early returns)."""
    if not pubs or not (len(pubs) == len(msgs) == len(rs)):
        return None
    if len(s_agg) != 32:
        return None
    s = int.from_bytes(s_agg, "little")
    if s >= L:
        return None
    zs = _coefficients(pubs, msgs, rs)
    terms = []
    for z, big_r, pub, msg in zip(zs, rs, pubs, msgs):
        r_pt = point_decompress(big_r)
        a_pt = point_decompress(pub)
        if r_pt is None or a_pt is None:
            return None
        r_aff = (r_pt[0] * pow(r_pt[2], P - 2, P) % P,
                 r_pt[1] * pow(r_pt[2], P - 2, P) % P)
        a_aff = (a_pt[0] * pow(a_pt[2], P - 2, P) % P,
                 a_pt[1] * pow(a_pt[2], P - 2, P) % P)
        h = _challenge(big_r, pub, msg)
        terms.append((z, r_aff, z * h % L, a_aff))
    terms.append((s, _B_AFFINE, 0, _IDENT_AFFINE))
    return terms


def finish_from_points(points: list[tuple[int, int]]) -> bool:
    """Complete the aggregate check from dsm_batch's per-lane affine
    results (terms order from aggregate_terms): True iff the sum of
    lanes 0..n-1 equals lane n ([s_agg]B)."""
    if len(points) < 2:
        return False
    acc = IDENT
    for x, y in points[:-1]:
        acc = point_add(acc, (x, y, 1, x * y % P))
    lx, ly = points[-1]
    return point_equal(acc, (lx, ly, 1, lx * ly % P))
