"""SHA-256 / SHA-512 / RIPEMD-160 host hashing.

RIPEMD-160 is the Merkle/leaf/address hash of the reference era
(tmlibs/merkle SimpleHashFromBinary, types/part_set.go:32-41,
types/validator.go:75-86). hashlib provides it only when OpenSSL ships the
legacy provider, so a pure-Python fallback is included and exercised in
tests against hashlib when both are present.
"""

from __future__ import annotations

import hashlib
import struct


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


# ---------------------------------------------------------------------------
# Pure-Python RIPEMD-160 (fallback when OpenSSL lacks the legacy provider)
# ---------------------------------------------------------------------------

_K1 = (0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E)
_K2 = (0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000)

_R1 = (
    [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15],
    [7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8],
    [3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12],
    [1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2],
    [4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13],
)
_R2 = (
    [5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12],
    [6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2],
    [15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13],
    [8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14],
    [12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11],
)
_S1 = (
    [11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8],
    [7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12],
    [11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5],
    [11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12],
    [9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6],
)
_S2 = (
    [8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6],
    [9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11],
    [9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5],
    [15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8],
    [8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11],
)

_M32 = 0xFFFFFFFF


def _rol(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _M32


def _f(j: int, x: int, y: int, z: int) -> int:
    if j == 0:
        return x ^ y ^ z
    if j == 1:
        return (x & y) | (~x & z) & _M32
    if j == 2:
        return (x | ~y & _M32) ^ z
    if j == 3:
        return (x & z) | (y & ~z & _M32)
    return x ^ (y | ~z & _M32)


def _ripemd160_py(data: bytes) -> bytes:
    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    msg = bytearray(data)
    bitlen = len(data) * 8
    msg.append(0x80)
    while len(msg) % 64 != 56:
        msg.append(0)
    msg += struct.pack("<Q", bitlen)

    for off in range(0, len(msg), 64):
        x = struct.unpack("<16I", msg[off : off + 64])
        a1, b1, c1, d1, e1 = h
        a2, b2, c2, d2, e2 = h
        for rnd in range(5):
            for i in range(16):
                t = (a1 + _f(rnd, b1, c1, d1) + x[_R1[rnd][i]] + _K1[rnd]) & _M32
                t = (_rol(t, _S1[rnd][i]) + e1) & _M32
                a1, e1, d1, c1, b1 = e1, d1, _rol(c1, 10), b1, t
                t = (a2 + _f(4 - rnd, b2, c2, d2) + x[_R2[rnd][i]] + _K2[rnd]) & _M32
                t = (_rol(t, _S2[rnd][i]) + e2) & _M32
                a2, e2, d2, c2, b2 = e2, d2, _rol(c2, 10), b2, t
        t = (h[1] + c1 + d2) & _M32
        h[1] = (h[2] + d1 + e2) & _M32
        h[2] = (h[3] + e1 + a2) & _M32
        h[3] = (h[4] + a1 + b2) & _M32
        h[4] = (h[0] + b1 + c2) & _M32
        h[0] = t
    return struct.pack("<5I", *h)


try:
    _RIPEMD_TEMPLATE = hashlib.new("ripemd160", b"")
    _HAVE_OPENSSL_RIPEMD = True
except Exception:  # pragma: no cover - env dependent
    _RIPEMD_TEMPLATE = None
    _HAVE_OPENSSL_RIPEMD = False


def ripemd160(data: bytes) -> bytes:
    if _HAVE_OPENSSL_RIPEMD:
        # .copy() of a prebuilt context skips hashlib.new's per-call
        # name-resolution; on the 64KB part-hash hot path this is ~1-2%.
        h = _RIPEMD_TEMPLATE.copy()
        h.update(data)
        return h.digest()
    return _ripemd160_py(data)
