"""ChaCha20-Poly1305 AEAD (RFC 8439) — in-repo implementation with two
opportunistic fast paths: the `cryptography` package (when importable)
and direct ctypes bindings to the system libcrypto (crypto/_openssl.py,
present on this image even though the Python package is not).

The second half of the SecretConnection crypto hole (see crypto/x25519.py
for the first): every encrypted p2p frame rides this AEAD, so the
pure-Python path must be correct AND fast enough to carry real multi-node
consensus gossip when no native route exists. The ChaCha20 core therefore
runs vectorized in numpy with the state held as a (4, 4, nblocks) grid —
each round's four column (then four diagonal) quarter-rounds execute as
ONE lane-parallel quarter-round over whole rows, and the per-frame
Poly1305 key rides the same keystream call as the payload (block 0 =
one-time key, blocks 1.. = cipher stream), so a full 1024-byte
SecretConnection frame costs one vectorized sweep. Poly1305, inherently
serial, runs Horner-style on Python 130-bit ints.

All three paths are pinned to the RFC 8439 section 2.x test vectors and
cross-checked byte-for-byte (tests/test_secure_transport.py).

Backend selection shares TENDERMINT_SECRETCONN_BACKEND with x25519
(auto|pure|native|openssl; a pinned backend that is unavailable raises
loudly — never a silent downgrade).

Side channels: the pure path is not constant-time (numpy/bigint); the
tag COMPARISON is (hmac.compare_digest). docs/secure-p2p.md carries the
threat-model discussion.
"""

from __future__ import annotations

import hmac
import struct

import numpy as np

from tendermint_tpu.crypto import _openssl
from tendermint_tpu.crypto.x25519 import resolve_backend  # shared knob

KEY_LEN = 32
NONCE_LEN = 12
TAG_LEN = 16

_SIGMA = np.frombuffer(b"expand 32-byte k", dtype="<u4").copy()
_MASK128 = (1 << 128) - 1
_P1305 = (1 << 130) - 5
_RCLAMP = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF


class InvalidTag(ValueError):
    """AEAD authentication failed: tampered/truncated ciphertext, wrong
    key, or reordered/replayed frame (counter-nonce desync)."""


# -- ChaCha20 core (lane-parallel over blocks AND the 4 columns) --------------


def _quarter_round(a, b, c, d) -> None:
    # rows of shape (4, nblocks): one call = four quarter-rounds across
    # every block lane (mutates in place; callers pass views or temps)
    a += b
    d ^= a
    d[:] = (d << np.uint32(16)) | (d >> np.uint32(16))
    c += d
    b ^= c
    b[:] = (b << np.uint32(12)) | (b >> np.uint32(20))
    a += b
    d ^= a
    d[:] = (d << np.uint32(8)) | (d >> np.uint32(24))
    c += d
    b ^= c
    b[:] = (b << np.uint32(7)) | (b >> np.uint32(25))


def _keystream(key: bytes, counter: int, nonce: bytes, nbytes: int) -> bytes:
    if len(key) != KEY_LEN:
        raise ValueError(f"chacha20 key must be {KEY_LEN} bytes, got {len(key)}")
    if len(nonce) != NONCE_LEN:
        raise ValueError(f"chacha20 nonce must be {NONCE_LEN} bytes, got {len(nonce)}")
    nblocks = max(1, (nbytes + 63) // 64)
    x = np.empty((4, 4, nblocks), dtype=np.uint32)
    x[0] = _SIGMA[:, None]
    x[1:3].reshape(8, nblocks)[:] = np.frombuffer(key, dtype="<u4")[:, None]
    # the 32-bit block counter wraps modulo 2^32 (RFC 8439 section 2.3)
    x[3, 0] = ((counter + np.arange(nblocks, dtype=np.uint64)) & 0xFFFFFFFF).astype(
        np.uint32
    )
    x[3, 1:4] = np.frombuffer(nonce, dtype="<u4")[:, None]
    init = x.copy()
    a, b, c, d = x[0], x[1], x[2], x[3]
    for _ in range(10):
        _quarter_round(a, b, c, d)
        # diagonal round: rotate rows 1..3 so diagonals align as columns
        b2 = np.roll(b, -1, axis=0)
        c2 = np.roll(c, -2, axis=0)
        d2 = np.roll(d, -3, axis=0)
        _quarter_round(a, b2, c2, d2)
        b[:] = np.roll(b2, 1, axis=0)
        c[:] = np.roll(c2, 2, axis=0)
        d[:] = np.roll(d2, 3, axis=0)
    x += init
    # serialize block-major: block i = the 16 words [:, :, i], little-endian
    return (
        np.ascontiguousarray(x.reshape(16, nblocks).T).astype("<u4").tobytes()[:nbytes]
    )


def chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    """One 64-byte keystream block (RFC 8439 section 2.3)."""
    return _keystream(key, counter, nonce, 64)


def chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    """Encrypt/decrypt (RFC 8439 section 2.4) — XOR with the keystream
    starting at `counter`."""
    if not data:
        return b""
    ks = _keystream(key, counter, nonce, len(data))
    return (
        np.frombuffer(data, dtype=np.uint8) ^ np.frombuffer(ks, dtype=np.uint8)
    ).tobytes()


# -- Poly1305 -----------------------------------------------------------------


def poly1305_mac(key: bytes, msg: bytes) -> bytes:
    """RFC 8439 section 2.5 one-time authenticator (32-byte key = r||s)."""
    if len(key) != 32:
        raise ValueError(f"poly1305 key must be 32 bytes, got {len(key)}")
    r = int.from_bytes(key[:16], "little") & _RCLAMP
    s = int.from_bytes(key[16:], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        acc = (acc + int.from_bytes(block, "little") + (1 << (8 * len(block)))) * r % _P1305
    return ((acc + s) & _MASK128).to_bytes(16, "little")


def poly1305_key_gen(key: bytes, nonce: bytes) -> bytes:
    """RFC 8439 section 2.6: the one-time key is the first half of
    keystream block 0."""
    return chacha20_block(key, 0, nonce)[:32]


# -- AEAD (RFC 8439 section 2.8) ----------------------------------------------


def _pad16(n: int) -> bytes:
    return b"\x00" * (-n % 16)


def _mac_data(aad: bytes, ct: bytes) -> bytes:
    return (
        aad
        + _pad16(len(aad))
        + ct
        + _pad16(len(ct))
        + struct.pack("<QQ", len(aad), len(ct))
    )


def seal(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    """ciphertext || 16-byte tag."""
    # one keystream sweep: block 0 carries the poly1305 one-time key,
    # blocks 1.. carry the cipher stream (identical bytes to separate
    # counter-0/counter-1 calls, minus a second vectorization setup)
    ks = _keystream(key, 0, nonce, 64 + len(plaintext))
    if plaintext:
        ct = (
            np.frombuffer(plaintext, dtype=np.uint8)
            ^ np.frombuffer(ks[64:], dtype=np.uint8)
        ).tobytes()
    else:
        ct = b""
    return ct + poly1305_mac(ks[:32], _mac_data(aad, ct))


def open_(key: bytes, nonce: bytes, boxed: bytes, aad: bytes = b"") -> bytes:
    """Verify-then-decrypt; raises InvalidTag on any authentication
    failure (incl. a truncated box — a short frame can't carry a tag)."""
    if len(boxed) < TAG_LEN:
        raise InvalidTag("ciphertext shorter than the tag")
    ct, tag = boxed[:-TAG_LEN], boxed[-TAG_LEN:]
    ks = _keystream(key, 0, nonce, 64 + len(ct))
    want = poly1305_mac(ks[:32], _mac_data(aad, ct))
    if not hmac.compare_digest(tag, want):
        raise InvalidTag("poly1305 tag mismatch")
    if not ct:
        return b""
    return (
        np.frombuffer(ct, dtype=np.uint8) ^ np.frombuffer(ks[64:], dtype=np.uint8)
    ).tobytes()


# -- backend-dispatching AEAD object (the `cryptography` surface) -------------

try:  # pragma: no cover - env dependent
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305 as _NativeAEAD,
    )

    _HAVE_NATIVE = True
except ImportError:  # pragma: no cover - env dependent
    _HAVE_NATIVE = False


def have_native() -> bool:
    return _HAVE_NATIVE


class ChaCha20Poly1305:
    """Drop-in for `cryptography`'s AEAD class; `backend` records which
    implementation serves this instance ('pure'|'native'|'openssl')."""

    __slots__ = ("_key", "_native", "backend")

    def __init__(self, key: bytes, backend: str | None = None):
        if len(key) != KEY_LEN:
            raise ValueError(f"key must be {KEY_LEN} bytes, got {len(key)}")
        self._key = bytes(key)
        self.backend = backend if backend is not None else resolve_backend()
        self._native = _NativeAEAD(self._key) if self.backend == "native" else None

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None = None) -> bytes:
        if self._native is not None:
            return self._native.encrypt(nonce, data, aad)
        if self.backend == "openssl":
            return _openssl.aead_seal(self._key, nonce, data, aad or b"")
        return seal(self._key, nonce, data, aad or b"")

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None = None) -> bytes:
        if self._native is not None:
            try:
                return self._native.decrypt(nonce, data, aad)
            except Exception as exc:  # cryptography.exceptions.InvalidTag
                # ONE exception type across backends, so the transport's
                # tamper triage never depends on which path served
                raise InvalidTag(str(exc) or "poly1305 tag mismatch") from exc
        if self.backend == "openssl":
            pt = _openssl.aead_open(self._key, nonce, data, aad or b"")
            if pt is None:
                raise InvalidTag("poly1305 tag mismatch")
            return pt
        return open_(self._key, nonce, data, aad or b"")
