"""tendermint-tpu: a TPU-native BFT state-machine-replication framework.

A from-scratch reimplementation of the capability surface of Tendermint Core
v0.11 (reference: /root/reference), redesigned TPU-first:

- Host plane: the replicated state machine (consensus, mempool, p2p, state,
  RPC) runs on host in Python, mirroring the reference's layering
  (see SURVEY.md section 1).
- TPU data plane: the crypto hot paths -- batched Ed25519 signature
  verification (reference: types/vote_set.go:175, types/validator_set.go:247)
  and vectorized RIPEMD-160/SHA-256 Merkle hashing (types/part_set.go:95,
  types/tx.go:33) -- run as JAX kernels batched across lanes and sharded
  over a device mesh (`tendermint_tpu.ops`).

The two planes meet in `tendermint_tpu.ops.gateway`, a batching gateway that
preserves the CPU implementation's observable accept/reject semantics and
byte-identical hashes.
"""

from tendermint_tpu.version import __version__

__all__ = ["__version__"]
