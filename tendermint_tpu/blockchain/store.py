"""Persistent block store (reference: blockchain/store.go).

Per height: BlockMeta, the block's parts (so gossip can serve individual
parts without reassembly), the block's LastCommit under height-1 ("C:"),
and the SeenCommit — the +2/3 precommits actually observed, which may be
for a different round than the canonical LastCommit ("SC:",
blockchain/store.go:34-38). A height watermark JSON is written LAST so a
crash mid-save leaves the previous height authoritative
(blockchain/store.go:217-240).
"""

from __future__ import annotations

import json
import threading

from tendermint_tpu.libs.db import DB
from tendermint_tpu.types import Block, Commit, Part, PartSet
from tendermint_tpu.types.block_meta import BlockMeta

_STORE_KEY = b"blockStore"


def _meta_key(height: int) -> bytes:
    return b"H:%d" % height


def _part_key(height: int, index: int) -> bytes:
    return b"P:%d:%d" % (height, index)


def _commit_key(height: int) -> bytes:
    return b"C:%d" % height


def _seen_commit_key(height: int) -> bytes:
    return b"SC:%d" % height


class BlockStore:
    def __init__(self, db: DB):
        self.db = db
        self._mtx = threading.Lock()
        self._prune_mtx = threading.Lock()  # serializes prune_to callers
        self._height = 0
        self._base = 0
        # crash-safe prune bookkeeping (round 19): `clean_base` is the
        # lowest height that may still hold data on disk. prune_to
        # advances `base` FIRST (readers disown the range immediately),
        # deletes, then advances clean_base — so clean_base < base marks
        # an interrupted prune whose leftovers this open resumes deleting
        self._clean_base = 0
        # gauges (blockstore_* via the metrics RPC)
        self.pruned_heights = 0
        self.prune_runs = 0
        buf = db.get(_STORE_KEY)
        if buf:
            obj = json.loads(buf)
            self._height = obj["height"]
            # pre-round-10 stores have no base: a non-empty store starts
            # at height 1 (nothing was ever pruned before base existed)
            self._base = obj.get("base", 1 if self._height else 0)
            self._clean_base = obj.get("clean_base", self._base)
            if self._clean_base < self._base:
                self._resume_prune()

    def height(self) -> int:
        with self._mtx:
            return self._height

    def base(self) -> int:
        """Lowest height this store can serve (round 10): >1 after a
        statesync restore or prune_to — heights below it are legitimately
        absent, not missing."""
        with self._mtx:
            return self._base

    def _set_watermark_locked(self) -> None:
        self.db.set_sync(
            _STORE_KEY,
            json.dumps({
                "height": self._height,
                "base": self._base,
                "clean_base": self._clean_base,
            }).encode(),
        )

    # -- loads -------------------------------------------------------------

    def _get_json(self, key: bytes):
        buf = self.db.get(key)
        return json.loads(buf) if buf else None

    def load_block_meta(self, height: int) -> BlockMeta | None:
        obj = self._get_json(_meta_key(height))
        return BlockMeta.from_json(obj) if obj else None

    def load_block_part(self, height: int, index: int) -> Part | None:
        obj = self._get_json(_part_key(height, index))
        return Part.from_json(obj) if obj else None

    def load_block(self, height: int) -> Block | None:
        """Reassemble from parts (blockchain/store.go:60-81)."""
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        chunks = []
        for i in range(meta.block_id.parts_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            chunks.append(part.bytes_)
        return Block.from_bytes(b"".join(chunks))

    def load_block_commit(self, height: int):
        """The canonical commit for `height`, i.e. block height+1's
        LastCommit (blockchain/store.go:102-110). Polymorphic: the key
        C:h holds whatever form block h+1 carried — full below the
        upgrade boundary, AggregateCommit at and above it."""
        from tendermint_tpu.types.agg_commit import commit_from_json

        obj = self._get_json(_commit_key(height))
        return commit_from_json(obj) if obj else None

    def load_seen_commit(self, height: int):
        """SC:h holds whatever form the node OBSERVED the commit in —
        its own VoteSet's full commit when it took part in consensus, or
        an aggregate when the height arrived via fast-sync past the
        upgrade boundary."""
        from tendermint_tpu.types.agg_commit import commit_from_json

        obj = self._get_json(_seen_commit_key(height))
        return commit_from_json(obj) if obj else None

    # -- save --------------------------------------------------------------

    def save_block(self, block: Block, block_parts: PartSet, seen_commit: Commit) -> None:
        """blockchain/store.go:147-172. Height watermark is flushed sync,
        last."""
        height = block.header.height
        if height != self.height() + 1:
            raise ValueError(f"BlockStore can only save contiguous blocks. Wanted {self.height() + 1}, got {height}")
        if not block_parts.is_complete():
            raise ValueError("BlockStore can only save complete block part sets")

        meta = BlockMeta.from_block(block, block_parts)
        self.db.set(_meta_key(height), json.dumps(meta.to_json(), sort_keys=True).encode())
        for i in range(block_parts.total):
            part = block_parts.get_part(i)
            self.db.set(_part_key(height, i), json.dumps(part.to_json(), sort_keys=True).encode())
        self.db.set(
            _commit_key(height - 1),
            json.dumps(block.last_commit.to_json(), sort_keys=True).encode(),
        )
        self.db.set(
            _seen_commit_key(height),
            json.dumps(seen_commit.to_json(), sort_keys=True).encode(),
        )
        with self._mtx:
            self._height = height
            if self._base == 0:
                self._base = height  # first block this store ever held
                self._clean_base = height
            self._set_watermark_locked()

    def seed_snapshot(self, meta: BlockMeta, parts: list[Part], seen_commit: Commit) -> None:
        """Statesync restore: install block H (meta + parts + seen
        commit) as BOTH base and head of an empty store, so the restored
        node serves /block and /commit at its base and save_block's
        contiguity check accepts H+1 from fast sync. The caller verified
        meta/parts/commit against the light-verified header chain."""
        height = meta.header.height
        if self.height() != 0:
            raise ValueError(
                f"seed_snapshot on a non-empty store (height {self.height()})"
            )
        if len(parts) != meta.block_id.parts_header.total:
            raise ValueError("seed_snapshot: part count does not match meta")
        self.db.set(_meta_key(height), json.dumps(meta.to_json(), sort_keys=True).encode())
        for i, part in enumerate(parts):
            self.db.set(_part_key(height, i), json.dumps(part.to_json(), sort_keys=True).encode())
        self.db.set(
            _seen_commit_key(height),
            json.dumps(seen_commit.to_json(), sort_keys=True).encode(),
        )
        with self._mtx:
            self._height = height
            self._base = height
            self._clean_base = height
            self._set_watermark_locked()

    def _delete_heights(self, lo: int, hi: int) -> int:
        """Delete the data of heights [lo, hi) plus the canonical commit
        under lo-1 (block lo's LastCommit, stored under lo-1 at save
        time — below the new base once hi is the base). Pure deletes; no
        watermark writes."""
        deleted = 0
        for h in range(lo, hi):
            meta = self.load_block_meta(h)
            if meta is not None:
                for i in range(meta.block_id.parts_header.total):
                    self.db.delete(_part_key(h, i))
            self.db.delete(_meta_key(h))
            self.db.delete(_commit_key(h))
            self.db.delete(_seen_commit_key(h))
            deleted += 1
        self.db.delete(_commit_key(lo - 1))
        return deleted

    def _resume_prune(self) -> None:
        """Open-time recovery: a crash mid-prune left clean_base < base —
        the heights in between are already disowned (readers treat them
        as pruned) but may still hold partial data. Finish their deletes
        and advance clean_base. Runs from __init__, single-threaded."""
        self._delete_heights(self._clean_base, self._base)
        self._clean_base = self._base
        self._set_watermark_locked()

    def prune_to(self, retain_height: int) -> int:
        """Delete everything below `retain_height`; returns the number of
        heights pruned. The watermark (with the new base) is flushed
        FIRST, so a crash mid-prune leaves heights the store already
        disowned — readers see base and treat them as pruned — never a
        base claiming heights whose data is half-deleted. The old base
        persists as `clean_base` until the deletes finish, so the next
        open resumes an interrupted prune instead of leaking the
        half-deleted range forever (tests/test_retention.py SIGKILLs a
        pruning subprocess mid-delete to hold this). Concurrent callers
        serialize on a dedicated lock — overlapping delete ranges would
        let the faster caller's clean_base claim cover the slower one's
        unfinished deletes."""
        with self._prune_mtx:
            return self._prune_to_serialized(retain_height)

    def _prune_to_serialized(self, retain_height: int) -> int:
        with self._mtx:
            if retain_height <= self._base:
                return 0
            if retain_height > self._height:
                raise ValueError(
                    f"cannot prune to {retain_height} past head {self._height}"
                )
            old_base, self._base = self._base, retain_height
            # clean_base stays at old_base: the watermark now says
            # "[old_base, retain) is disowned but possibly on disk"
            self._set_watermark_locked()
        pruned = self._delete_heights(old_base, retain_height)
        with self._mtx:
            self._clean_base = retain_height
            self._set_watermark_locked()
            self.pruned_heights += pruned
            self.prune_runs += 1
        return pruned
