"""Fast-sync reactor on channel 0x40 (reference: blockchain/reactor.go).

Downloads blocks in parallel via BlockPool, verifies each `first` block
with `second.LastCommit` — the fast-sync batch-verify hot path
(reactor.go:235-236) routed through the TPU gateway — applies it, and
switches over to consensus when caught up (reactor.go:204-217).
"""

from __future__ import annotations

import json
import threading
import time

from tendermint_tpu.blockchain.pool import BlockPool
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn import ChannelDescriptor
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.block_id import BlockID

BLOCKCHAIN_CHANNEL = 0x40
TRY_SYNC_INTERVAL = 0.1  # reactor.go:28-33
STATUS_UPDATE_INTERVAL = 10.0
SWITCH_TO_CONSENSUS_INTERVAL = 1.0


def group_spans(sizes: list[int], target: int) -> list[tuple[int, int]]:
    """Partition consecutive commits into device-call spans [i, j) whose
    signature totals never EXCEED `target` (an overshoot lands in the
    next power-of-two kernel bucket — e.g. 5000 sigs pad to 8192 instead
    of 4096, wasting ~40% of the call); a single commit larger than the
    target still goes alone."""
    spans = []
    i = 0
    while i < len(sizes):
        j, sigs = i, 0
        while j < len(sizes) and (sigs == 0 or sigs + sizes[j] <= target):
            sigs += sizes[j]
            j += 1
        spans.append((i, j))
        i = j
    return spans


def _enc(obj: dict) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


class BlockchainReactor(Reactor, BaseService):
    def __init__(
        self,
        state,
        proxy_app_conn,
        store,
        fast_sync: bool,
        event_cache=None,
        batch_verifier=None,
        async_batch_verifier=None,
        part_hasher=None,
        part_tree_hasher=None,
        status_update_interval: float = STATUS_UPDATE_INTERVAL,
        pipeline_depth: int = 8,
        group_sig_target: int = 4096,
        post_apply_hook=None,
        defer_for_statesync: bool = False,
        evidence_pool=None,
    ):
        BaseService.__init__(self, name="blockchain.reactor")
        self.status_update_interval = status_update_interval
        if state.last_block_height != store.height() and \
           state.last_block_height != store.height() - 1:
            raise ValueError(
                f"state ({state.last_block_height}) and store ({store.height()}) heights diverge"
            )
        # statesync handoff (round 10): when a restore is pending, the
        # pool must not start pulling from the genesis-height state this
        # reactor was constructed with — start_after_statesync() re-seeds
        # it at the restored height and starts the sync loop then
        self.post_apply_hook = post_apply_hook
        # round 12: fast-synced blocks carry evidence too — the pool must
        # learn it or the node re-proposes already-on-chain pieces once
        # it switches to consensus (mark_committed is the only dedup
        # against chain history)
        self.evidence_pool = evidence_pool
        self._deferred = defer_for_statesync
        self.state = state
        self.proxy_app_conn = proxy_app_conn
        self.store = store
        self.fast_sync = fast_sync
        self.event_cache = event_cache
        self.batch_verifier = batch_verifier
        self.async_batch_verifier = async_batch_verifier
        self.part_hasher = part_hasher
        self.part_tree_hasher = part_tree_hasher
        # speculative verify pipeline (see _dispatch_speculative): device
        # batches in flight keyed by block hash -> (valset_hash, finish),
        # plus the part sets hashed ahead for those blocks.
        # group_sig_target amortizes the device round-trip: with large
        # validator sets, grouping several blocks' commits into one
        # dispatch divides the per-call latency (dominant on tunneled
        # chips, harmless on local ones) — 4096 matches the f32p kernel's
        # efficient bucket (grouping never overshoots it; see
        # _dispatch_speculative). A speculated entry is checked against
        # the CURRENT validator set at consume time in _try_sync and
        # falls back to synchronous verify on mismatch, so validator
        # churn degrades to the unpipelined path, never a wrong accept.
        self.pipeline_depth = pipeline_depth
        self.group_sig_target = group_sig_target
        self._inflight: dict[bytes, tuple[bytes, object]] = {}
        self._parts_cache: dict[bytes, object] = {}
        self.pool = BlockPool(
            store.height() + 1,
            request_fn=self._send_block_request,
            timeout_fn=self._on_peer_timeout,
        )
        self.blocks_synced = 0
        self.sync_rate = 0.0  # blocks/s, EWMA for bench/introspection
        # black-box flight recorder (round 17): catchup-path milestones
        # land in the event ring so a fast-sync wedge is diagnosable
        # post-hoc (the PR-16 full-suite flake was chased blind); None
        # in bare harnesses
        self.flightrec = None
        # cumulative per-stage seconds on the consume thread; exposed via
        # /metrics (fastsync_*_s) so the residual bottleneck is measured
        # in production, not guessed (VERDICT r3 weak #6)
        self.stage_s = {
            "dispatch": 0.0, "part_hash": 0.0, "verify_wait": 0.0,
            "store_save": 0.0, "apply": 0.0,
        }
        # horizon-aware catchup (round 19): when every serving peer has
        # PRUNED the next height we need, fast sync can never converge —
        # the node wires this to its statesync arm (node._on_below_horizon)
        # and the pool routine calls it instead of spinning forever on
        # no_block_response. fallback(horizon) -> bool: True = statesync
        # armed, stop fast sync; False = keep trying (and keep logging).
        self.horizon_fallback = None
        self.below_horizon_fallbacks = 0
        self._horizon_strikes = 0

    # -- Reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                id=BLOCKCHAIN_CHANNEL,
                priority=5,
                send_queue_capacity=100,
                recv_message_capacity=22020096,
            )
        ]

    def _status_response(self) -> bytes:
        # round 19: the store BASE rides beside the height so a syncing
        # peer learns not just how far we are but how far BACK we can
        # serve (pruned/restored stores start above 1)
        return _enc({
            "type": "status_response",
            "height": self.store.height(),
            "base": self.store.base(),
        })

    def add_peer(self, peer) -> None:
        peer.try_send(BLOCKCHAIN_CHANNEL, self._status_response())
        # a fast-syncing node must learn this peer's height promptly, not
        # at the next 10s status tick (the pool's 5s catch-up timeout races
        # a peer that connected at genesis height otherwise)
        if self.fast_sync:
            peer.try_send(
                BLOCKCHAIN_CHANNEL,
                _enc({"type": "status_request", "height": self.store.height()}),
            )

    def remove_peer(self, peer, reason) -> None:
        self.pool.remove_peer(peer.id())

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        # EVERYTHING in the message is attacker input: any decode
        # violation (missing key, wrong type, out-of-range scalar) must
        # end as a peer error, never an exception escaping into the p2p
        # recv routine (codec/jsonval contract)
        from tendermint_tpu.codec import jsonval as jv

        try:
            msg = json.loads(msg_bytes.decode())
            mtype = msg["type"]
            if mtype == "block_request":
                self._handle_block_request(
                    peer, jv.int_field(msg, "height", 0, jv.MAX_HEIGHT)
                )
            elif mtype == "block_response":
                block = Block.from_json(jv.dict_field(msg, "block"))
                self.pool.add_block(peer.id(), block, len(msg_bytes))
            elif mtype == "status_request":
                peer.try_send(BLOCKCHAIN_CHANNEL, self._status_response())
            elif mtype == "status_response":
                # base is round-19 optional: a pre-retention peer's
                # status carries none, which reads as base 0 = "serves
                # every height it has"
                base = (
                    jv.int_field(msg, "base", 0, jv.MAX_HEIGHT)
                    if "base" in msg else 0
                )
                self.pool.set_peer_height(
                    peer.id(), jv.int_field(msg, "height", 0, jv.MAX_HEIGHT),
                    base=base,
                )
            elif mtype == "no_block_response":
                # honest "I don't have it" — free the requester for another peer
                height = jv.int_field(msg, "height", 0, jv.MAX_HEIGHT)
                self.logger.debug(
                    "peer %s has no block at %s", peer.id()[:8], height
                )
                self.pool.peer_has_no_block(peer.id(), height)
            else:
                raise ValueError(f"unknown bc msg {mtype!r}")
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as exc:
            self.switch.stop_peer_for_error(peer, exc)

    def _handle_block_request(self, peer, height: int) -> None:
        block = self.store.load_block(height)
        if block is not None:
            peer.try_send(
                BLOCKCHAIN_CHANNEL,
                _enc({"type": "block_response", "block": block.to_json()}),
            )
        else:
            peer.try_send(
                BLOCKCHAIN_CHANNEL,
                _enc({"type": "no_block_response", "height": height}),
            )

    # -- pool callbacks ----------------------------------------------------

    def _send_block_request(self, height: int, peer_id: str) -> None:
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            peer.try_send(
                BLOCKCHAIN_CHANNEL, _enc({"type": "block_request", "height": height})
            )

    def _on_peer_timeout(self, peer_id: str, reason) -> None:
        peer = self.switch.peers.get(peer_id)
        if peer is not None:
            self.switch.stop_peer_for_error(peer, reason)

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self.fast_sync and not self._deferred:
            self._start_sync()

    def _start_sync(self) -> None:
        self.pool.start()
        threading.Thread(
            target=self._pool_routine, daemon=True, name="bc.pool_routine"
        ).start()

    def start_after_statesync(self, state) -> None:
        """Statesync handoff: a restore seeded the block store + state DB
        at the snapshot height; adopt the restored state, re-point the
        pool at the next height, and start syncing the tail. With
        state=None (restore fell back), start from whatever the store
        holds — genesis on a fresh node."""
        if not self._deferred:
            raise RuntimeError("reactor was not deferred for statesync")
        self._deferred = False
        if state is not None:
            self.state = state.copy()
        self.pool = BlockPool(
            self.store.height() + 1,
            request_fn=self._send_block_request,
            timeout_fn=self._on_peer_timeout,
        )
        if self.fast_sync and self.is_running():
            self._start_sync()
            # peers connected during the restore already sent their
            # status; ask again so the pool learns heights promptly
            self.broadcast_status_request()

    def on_stop(self) -> None:
        self.pool.stop()

    # -- the sync loop (reactor.go:174-262) --------------------------------

    def _pool_routine(self) -> None:
        last_status = 0.0
        last_switch_check = 0.0
        last_hundred = time.monotonic()
        while self.is_running() and self.pool.is_running():
            now = time.monotonic()
            if now - last_status >= self.status_update_interval:
                last_status = now
                self.broadcast_status_request()
            if now - last_switch_check >= SWITCH_TO_CONSENSUS_INTERVAL:
                last_switch_check = now
                if self._check_horizon():
                    return
                if self.pool.is_caught_up():
                    self.logger.info("caught up; switching to consensus")
                    if self.flightrec is not None:
                        self.flightrec.record(
                            "fastsync", event="switch_to_consensus",
                            height=self.store.height(),
                            blocks_synced=self.blocks_synced,
                        )
                    self.pool.stop()
                    self.fast_sync = False  # /metrics fastsync_active
                    con_r = self.switch.reactor("CONSENSUS")
                    if con_r is not None and hasattr(con_r, "switch_to_consensus"):
                        con_r.switch_to_consensus(self.state)
                    return
            synced_any = self._try_sync()
            # rate sample on each actual crossing of a 100-block boundary
            if synced_any and self.blocks_synced % 100 == 0:
                if self.flightrec is not None:
                    self.flightrec.record(
                        "fastsync", event="progress",
                        height=self.store.height(),
                        blocks_synced=self.blocks_synced,
                    )
                dt = max(time.monotonic() - last_hundred, 1e-9)
                inst = 100 / dt
                self.sync_rate = (
                    0.9 * self.sync_rate + 0.1 * inst if self.sync_rate else inst
                )
                last_hundred = time.monotonic()
            if not synced_any:
                time.sleep(TRY_SYNC_INTERVAL)

    def _make_parts(self, block):
        """Part set via the TPU hashing gateway (reactor.go:229 rebuilds
        and re-hashes every synced block — the fast-sync hash hot path)."""
        t0 = time.perf_counter()
        try:
            return block.make_part_set(
                self.state.params().block_gossip.block_part_size_bytes,
                hasher=self.part_hasher,
                # one-pass leaf digests + proof tree when the offload
                # path serves (devd hash_stream tree frame) — fast-sync
                # rebuilds a part set per synced block, the heaviest
                # part-set-construction path in the system
                tree_hasher=self.part_tree_hasher,
            )
        finally:
            self.stage_s["part_hash"] += time.perf_counter() - t0

    def _dispatch_speculative(self, window) -> None:
        """Enqueue device verification for every downloaded block in the
        window that isn't in flight yet. Dispatches are SPECULATIVE: they
        use today's validator set, and each in-flight entry records that
        set's hash — if applying an earlier block changes the set, the
        head consume path sees the mismatch and re-verifies synchronously
        (validator sets change rarely, so speculation almost always
        lands). Keeping several batches in flight is what hides the
        device/tunnel round-trip that a 1-deep pipeline pays per block."""
        vhash = self.state.validators.hash()
        entries, hashes = [], []
        for blk, nxt in zip(window[:-1], window[1:]):
            bh = blk.hash()
            if bh in self._inflight:
                continue
            parts = self._parts_cache.get(bh)
            if parts is None:
                parts = self._parts_cache[bh] = self._make_parts(blk)
            entries.append(
                (BlockID(bh, parts.header()), blk.header.height, nxt.last_commit)
            )
            hashes.append(bh)
        # Group commits into shared device calls up to ~group_sig_target
        # signatures: chains with small validator sets (a few sigs per
        # commit) would otherwise verify on CPU or underfill the kernel,
        # while large commits already fill a call each — and keeping
        # calls bounded lets consecutive dispatches overlap instead of
        # serializing one giant transfer.
        for i, j in group_spans(
            [e[2].size() for e in entries], self.group_sig_target
        ):
            # a structurally bad commit gets a finisher that re-raises at
            # consume time (validator_set.verify_commits_async), so it
            # cannot poison the rest of its group's dispatch
            finishes = self.state.validators.verify_commits_async(
                self.state.chain_id, entries[i:j], self.async_batch_verifier
            )
            for bh, finish in zip(hashes[i:j], finishes):
                self._inflight[bh] = (vhash, finish)

    def _try_sync(self) -> bool:
        """Verify+apply one block; True if a block was consumed.

        Pipelined when an async verifier is wired: up to PIPELINE_DEPTH
        blocks' signature batches run on the device concurrently with the
        host hashing part sets and applying the head block."""
        if self.async_batch_verifier is not None:
            window = self.pool.peek_blocks(self.pipeline_depth + 1)
        else:
            window = [b for b in self.pool.peek_two_blocks() if b is not None]
        if len(window) < 2:
            return False
        first, second = window[0], window[1]
        if self.async_batch_verifier is not None:
            t0 = time.perf_counter()
            self._dispatch_speculative(window)
            self.stage_s["dispatch"] += time.perf_counter() - t0
        bh = first.hash()
        # rebuild the part set: the header's PartsHeader committed to it
        first_parts = self._parts_cache.pop(bh, None)
        if first_parts is None:
            first_parts = self._make_parts(first)
        first_id = BlockID(bh, first_parts.header())
        t_verify = time.perf_counter()
        try:
            entry = self._inflight.pop(bh, None)
            if entry is not None and entry[0] == self.state.validators.hash():
                entry[1]()  # raises exactly as verify_commit would
            else:
                # no async verifier, or speculation used a stale validator
                # set: verify synchronously against the current one
                self.state.validators.verify_commit(
                    self.state.chain_id,
                    first_id,
                    first.header.height,
                    second.last_commit,
                    batch_verifier=self.batch_verifier,
                )
            self.stage_s["verify_wait"] += time.perf_counter() - t_verify
        except Exception as exc:  # noqa: BLE001 — bad block/commit
            if self.flightrec is not None:
                self.flightrec.record(
                    "fastsync", event="invalid_block",
                    height=first.header.height,
                    err=f"{type(exc).__name__}: {exc}"[:200],
                )
            self.logger.info("invalid block %d during fast sync: %s", first.header.height, exc)
            # drop all speculation: refetched blocks get fresh hashes, and
            # second's (possibly forged) commit seeded later dispatches
            self._inflight.clear()
            self._parts_cache.clear()
            bad = self.pool.redo_request(first.header.height)
            # second's commit could also be forged; refetch it too
            self.pool.redo_request(second.header.height)
            if bad:
                peer = self.switch.peers.get(bad)
                if peer is not None:
                    self.switch.stop_peer_for_error(peer, "sent invalid block")
            return False
        self.pool.pop_request()
        t0 = time.perf_counter()
        self.store.save_block(first, first_parts, second.last_commit)
        self.stage_s["store_save"] += time.perf_counter() - t0
        from tendermint_tpu.state.execution import apply_block

        t0 = time.perf_counter()
        apply_block(
            self.state,
            self.event_cache,
            self.proxy_app_conn,
            first,
            first_parts.header(),
            _NullMempool(),
            batch_verifier=self.batch_verifier,
        )
        self.stage_s["apply"] += time.perf_counter() - t0
        self.blocks_synced += 1
        if first.evidence.evidence and self.evidence_pool is not None:
            self.evidence_pool.mark_committed(first.evidence.evidence)
        if self.post_apply_hook is not None:
            # snapshot production during catch-up (round 10); best-effort
            # by contract — the hook must never stall or kill the sync loop
            try:
                self.post_apply_hook(self.state, first)
            except Exception:  # noqa: BLE001
                self.logger.exception("post-apply hook failed at %d", first.header.height)
        return True

    def _check_horizon(self) -> bool:
        """Pool-routine tick: when every serving peer has pruned our next
        height, hand the node over to statesync instead of spinning on
        no_block_response forever. Two consecutive strikes (1s apart)
        guard against a single peer's half-reported status. Returns True
        when the routine should exit (statesync armed)."""
        below = getattr(self.pool, "below_horizon", None)  # bare-harness
        # pool fakes predate the round-19 horizon surface
        horizon = below() if below is not None else None
        if horizon is None:
            self._horizon_strikes = 0
            return False
        self._horizon_strikes += 1
        if self._horizon_strikes < 2 or self.horizon_fallback is None:
            return False
        self.logger.warning(
            "fast-sync target %d is below the network's retained horizon "
            "%d (every peer pruned it); attempting statesync fallback",
            self.store.height() + 1, horizon,
        )
        if self.flightrec is not None:
            self.flightrec.record(
                "fastsync", event="below_horizon",
                height=self.store.height(), horizon=horizon,
            )
        # deferred BEFORE the fallback arms statesync: a fast restore
        # completing must find the reactor ready for the re-seed handoff
        # (start_after_statesync asserts _deferred)
        self._deferred = True
        if self.horizon_fallback(horizon):
            self.below_horizon_fallbacks += 1
            self.pool.stop()
            return True
        self._deferred = False
        self._horizon_strikes = 0  # re-arm; conditions may change
        return False

    def broadcast_status_request(self) -> None:
        self.switch.broadcast(
            BLOCKCHAIN_CHANNEL, _enc({"type": "status_request", "height": self.store.height()})
        )


class _NullMempool:
    """Fast sync runs before the mempool matters (types/services.go MockMempool)."""

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def update(self, height: int, txs) -> None:
        pass
