from tendermint_tpu.blockchain.store import BlockStore

__all__ = ["BlockStore"]
