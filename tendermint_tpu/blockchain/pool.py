"""Parallel block fetcher for fast sync (reference: blockchain/pool.go).

A requester per pending height (<=300 outstanding, <=75 per peer) pulls
blocks from peers concurrently; peers below a minimum receive rate get
dropped (pool.go:14-20, 100-118). The sync loop consumes heights strictly
in order via peek_two_blocks/pop_request; verification failures route back
through redo_request, banning the peer that served the bad block.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.libs.flowrate import Monitor
from tendermint_tpu.libs.service import BaseService

MAX_PENDING_REQUESTS = 300  # pool.go:14-20
MAX_PENDING_REQUESTS_PER_PEER = 75
MIN_RECV_RATE = 10240.0  # 10KB/s
PEER_TIMEOUT = 15.0
REQUEST_RETRY_SECONDS = 5.0


class BpPeer:
    def __init__(self, peer_id: str, height: int, base: int = 0):
        self.id = peer_id
        self.height = height
        # round 19: the peer's store BASE (lowest height it can serve —
        # >1 on pruned/snapshot-restored peers). 0 = unknown (a pre-r19
        # peer whose status_response carries no base): treated as "can
        # serve anything", exactly the pre-retention behavior.
        self.base = base
        self.num_pending = 0
        self.recv_monitor = Monitor()
        self.timeout_at: float | None = None
        self.did_timeout = False

    def reset_monitor(self) -> None:
        self.recv_monitor = Monitor()

    def check_rate(self, now: float) -> bool:
        """True if the peer is too slow (pool.go:100-118)."""
        if self.num_pending == 0 or self.timeout_at is None:
            return False
        if now < self.timeout_at:
            return False
        return self.recv_monitor.status().cur_rate < MIN_RECV_RATE


class BpRequester:
    """One height's fetch state (pool.go:468-515, minus the per-requester
    goroutine: retry/redo runs from the pool's single worker loop)."""

    def __init__(self, height: int):
        self.height = height
        self.peer_id: str | None = None
        self.block = None
        self.requested_at = 0.0
        self.redo = False


class BlockPool(BaseService):
    def __init__(self, start_height: int, request_fn, timeout_fn):
        """request_fn(height, peer_id): send a block request to a peer.
        timeout_fn(peer_id, reason): report an errored/slow peer."""
        super().__init__(name="blockchain.pool")
        self._mtx = threading.Lock()
        self.start_height = start_height  # next height to pop
        self.height = start_height
        self.peers: dict[str, BpPeer] = {}
        self.requesters: dict[int, BpRequester] = {}
        self.max_peer_height = 0
        self.request_fn = request_fn
        self.timeout_fn = timeout_fn

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        self._started_at = time.monotonic()
        threading.Thread(
            target=self._make_requesters_routine, daemon=True, name="pool.requesters"
        ).start()

    def _make_requesters_routine(self) -> None:
        while self.is_running():
            self._spawn_and_retry()
            self.quit_event.wait(0.25)

    def _spawn_and_retry(self) -> None:
        now = time.monotonic()
        sends: list[tuple[int, str]] = []
        with self._mtx:
            # slow-peer detection
            for peer in list(self.peers.values()):
                if peer.check_rate(now):
                    self._remove_peer_locked(peer.id)
                    self.timeout_fn(peer.id, "slow peer")
            # spawn new requesters up to the pipeline limit
            while (
                len(self.requesters) < MAX_PENDING_REQUESTS
                and self.height + len(self.requesters) <= self.max_peer_height
            ):
                h = self.height + len(self.requesters)
                if h in self.requesters:
                    break
                self.requesters[h] = BpRequester(h)
            # (re)assign peers to unserved requesters
            for req in self.requesters.values():
                if req.block is not None:
                    continue
                stale = (
                    req.peer_id is not None
                    and now - req.requested_at > REQUEST_RETRY_SECONDS
                )
                if req.peer_id is None or req.redo or stale:
                    if req.peer_id is not None and (req.redo or stale):
                        old = self.peers.get(req.peer_id)
                        if old:
                            old.num_pending = max(0, old.num_pending - 1)
                    peer = self._pick_available_peer_locked(req.height)
                    req.redo = False
                    if peer is None:
                        req.peer_id = None
                        continue
                    req.peer_id = peer.id
                    req.requested_at = now
                    peer.num_pending += 1
                    if peer.num_pending == 1:
                        peer.reset_monitor()
                        peer.timeout_at = now + PEER_TIMEOUT
                    sends.append((req.height, peer.id))
        for height, peer_id in sends:
            self.request_fn(height, peer_id)

    def _pick_available_peer_locked(self, height: int) -> BpPeer | None:
        for peer in self.peers.values():
            if peer.did_timeout:
                continue
            if peer.num_pending >= MAX_PENDING_REQUESTS_PER_PEER:
                continue
            if peer.height < height:
                continue
            if peer.base > height:
                # the peer PRUNED this height (round 19): asking would
                # burn a block_request/no_block_response round trip per
                # retry — ineligible without a wire exchange
                continue
            return peer
        return None

    # -- peer management ---------------------------------------------------

    def set_peer_height(self, peer_id: str, height: int,
                        base: int = 0) -> None:
        with self._mtx:
            peer = self.peers.get(peer_id)
            if peer is None:
                self.peers[peer_id] = BpPeer(peer_id, height, base)
            else:
                peer.height = height
                peer.base = base
            self.max_peer_height = max(self.max_peer_height, height)

    def remove_peer(self, peer_id: str) -> None:
        with self._mtx:
            self._remove_peer_locked(peer_id)

    def _remove_peer_locked(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)
        for req in self.requesters.values():
            if req.peer_id == peer_id and req.block is None:
                req.peer_id = None

    # -- block intake ------------------------------------------------------

    def add_block(self, peer_id: str, block, block_size: int) -> None:
        with self._mtx:
            req = self.requesters.get(block.header.height)
            if req is None or req.peer_id != peer_id or req.block is not None:
                return  # unsolicited or duplicate
            req.block = block
            peer = self.peers.get(peer_id)
            if peer:
                peer.num_pending = max(0, peer.num_pending - 1)
                peer.recv_monitor.update(block_size)
                if peer.num_pending == 0:
                    peer.timeout_at = None
                else:
                    peer.timeout_at = time.monotonic() + PEER_TIMEOUT

    # -- ordered consumption ----------------------------------------------

    def peek_two_blocks(self):
        with self._mtx:
            first = self.requesters.get(self.height)
            second = self.requesters.get(self.height + 1)
            return (
                first.block if first else None,
                second.block if second else None,
            )

    def peek_blocks(self, k: int) -> list:
        """The contiguous run of downloaded blocks from the pool height,
        up to k long (stops at the first gap) — the verify pipeline's
        lookahead window."""
        with self._mtx:
            out = []
            for h in range(self.height, self.height + k):
                req = self.requesters.get(h)
                if req is None or req.block is None:
                    break
                out.append(req.block)
            return out

    def pop_request(self) -> None:
        with self._mtx:
            self.requesters.pop(self.height, None)
            self.height += 1

    def peer_has_no_block(self, peer_id: str, height: int) -> None:
        """Peer answered a request with no_block_response: clear the
        assignment (without banning) so another peer gets picked."""
        with self._mtx:
            req = self.requesters.get(height)
            if req is None or req.peer_id != peer_id or req.block is not None:
                return
            req.peer_id = None
            peer = self.peers.get(peer_id)
            if peer:
                peer.num_pending = max(0, peer.num_pending - 1)

    def redo_request(self, height: int) -> str | None:
        """Bad block at `height`: drop the peer that sent it, refetch
        (pool.go RedoRequest + reactor.go:239)."""
        with self._mtx:
            req = self.requesters.get(height)
            if req is None:
                return None
            bad_peer = req.peer_id
            req.block = None
            req.peer_id = None
            req.redo = True
            if bad_peer:
                self._remove_peer_locked(bad_peer)
            return bad_peer

    def below_horizon(self) -> int | None:
        """The network's retained horizon when fast sync can NEVER make
        progress from here (round 19): every known peer that is ahead of
        us has pruned the next height we need (its base is above our
        pool height). Returns the lowest such base — the height the
        network retains back to — or None while any peer could still
        serve. Peers that never reported a base (pre-r19) read as
        base=0 = "serves everything", so mixed nets never false-trigger."""
        with self._mtx:
            ahead = [
                p for p in self.peers.values() if p.height >= self.height
            ]
            if not ahead:
                return None
            if all(p.base > self.height for p in ahead):
                return min(p.base for p in ahead)
            return None

    # -- status ------------------------------------------------------------

    def is_caught_up(self) -> bool:
        """pool.go:128-142: need at least one peer, and either a synced
        block or 5s elapsed (so a just-connected peer's not-yet-reported
        height can't fake instant catch-up), and be at max peer height."""
        with self._mtx:
            if not self.peers:
                return False
            received_or_timed_out = (
                self.height > self.start_height
                or time.monotonic() - self._started_at > 5.0
            )
            return received_or_timed_out and self.height >= self.max_peer_height

    def status(self) -> tuple[int, int]:
        with self._mtx:
            pending = sum(1 for r in self.requesters.values() if r.block is None)
            return self.height, pending
