"""JSON-RPC server: HTTP POST + GET URI + WebSocket subscriptions
(reference: rpc/lib/server/handlers.go, http_server.go).

One ThreadingHTTPServer serves all three transports:
- POST /            JSON-RPC 2.0 envelope
- GET  /<method>    params from the query string
- GET  /websocket   RFC6455 upgrade; JSON-RPC frames + subscribe/
                    unsubscribe methods that stream node events
                    (handlers.go:351-630)

The listen address may be TCP ("host:port", "tcp://host:port") or a unix
socket ("unix:///path.sock", or a bare filesystem path) — the reference
rpc/lib serves and tests both (rpc/lib/server/http_server.go:20-40,
rpc/lib/rpc_test.go:40-75); all three transports ride either listener.
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.rpc import admission as adm
from tendermint_tpu.rpc.core.handlers import RPCError
from tendermint_tpu.rpc.core.routes import build_routes

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def is_unix_laddr(laddr: str) -> bool:
    """Is this listen address a unix-socket path? Accepts the explicit
    unix:// scheme and bare filesystem paths (what node._parse_laddr
    yields after stripping the scheme)."""
    return laddr.startswith("unix://") or (
        "/" in laddr and ":" not in laddr
    )


class _UnixThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer over AF_UNIX. HTTPServer.server_bind assumes a
    (host, port) address tuple and BaseHTTPRequestHandler.address_string
    indexes client_address — both break on unix sockets, so bind and
    accept are overridden to present tuple-shaped addresses."""

    address_family = socket.AF_UNIX

    def server_bind(self):
        import os as _os
        import stat as _stat

        # reclaim a stale socket from a previous run — but never delete a
        # NON-socket: a mistyped laddr pointing at a real file must fail
        # at bind, not silently destroy the file
        try:
            st = _os.stat(self.server_address)
            if _stat.S_ISSOCK(st.st_mode):
                _os.unlink(self.server_address)
        except (FileNotFoundError, TypeError):
            pass
        self.socket.bind(self.server_address)
        self.server_name = "unix"
        self.server_port = 0

    def get_request(self):
        conn, _ = self.socket.accept()
        return conn, ("unix", 0)


def _json_default(obj):
    to_json = getattr(obj, "to_json", None)
    if to_json is not None:
        return to_json()
    if isinstance(obj, bytes):
        return obj.hex().upper()
    return repr(obj)


def _dumps(obj) -> bytes:
    return json.dumps(obj, default=_json_default).encode()


def _coerce_params(params: dict, known: list[str]) -> dict:
    out = {}
    for k, v in params.items():
        if k not in known:
            raise RPCError(f"unknown parameter {k!r} (expected {known})")
        out[k] = v
    return out


class RPCServer(BaseService):
    def __init__(self, laddr: str, ctx, unsafe: bool = False, routes=None):
        super().__init__(name="rpc.server")
        self.ctx = ctx
        # routes override (round 24): the replica daemon serves the read
        # surface off its verified cache with the same transports/admission
        self.routes = build_routes(unsafe) if routes is None else dict(routes)
        # ingress admission (round 23, rpc/admission.py): the node wires
        # a shared controller (node.rpc_admission) so telemetry and the
        # load-shed ladder see it; bare harnesses get a private default
        node = getattr(ctx, "node", None)
        self.admission = (
            getattr(node, "rpc_admission", None) or adm.AdmissionController()
        )
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through our logger
                server.logger.debug(fmt, *args)

            # -- ingress admission (round 23) ------------------------------

            def handle(self):
                """Connection-cap gate ahead of any HTTP parsing: over
                budget, the flood gets one cheap typed 503 and the thread
                exits — never a parked worker."""
                admit = server.admission.conn_acquire()
                if not admit:
                    # send_response needs these before a request is parsed
                    self.requestline = ""
                    self.request_version = self.protocol_version
                    self.command = ""
                    try:
                        self._shed(admit)
                    except OSError:
                        pass
                    self.close_connection = True
                    return
                try:
                    super().handle()
                finally:
                    server.admission.conn_release()

            def _shed(self, admit: adm.Admit, id_=None) -> None:
                """Typed shed response: HTTP 429/503, Retry-After, and a
                stable `shed:<reason>` JSON-RPC error string."""
                body = _dumps({
                    "jsonrpc": "2.0", "id": id_, "result": None,
                    "error": f"shed:{admit.reason}",
                })
                self.send_response(admit.status)
                self.send_header("Retry-After",
                                 adm.retry_after_header(admit.retry_after))
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            @staticmethod
            def _request_kind(method: str) -> str:
                # writes reach the mempool's lanes even under shed-reads;
                # everything else on the method surface is a read
                return "write" if method.startswith("broadcast_tx") else "read"

            def _respond(self, payload: dict, status: int = 200) -> None:
                body = _dumps(payload)
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _rpc_result(self, id_, result) -> None:
                self._respond({"jsonrpc": "2.0", "id": id_, "result": result, "error": ""})

            def _rpc_error(self, id_, message: str, status: int = 500) -> None:
                self._respond(
                    {"jsonrpc": "2.0", "id": id_, "result": None, "error": message},
                    status=status,
                )

            def _call(self, method: str, params: dict):
                route = server.routes.get(method)
                if route is None:
                    raise RPCError(f"unknown RPC method {method!r}")
                fn, known = route
                return fn(server.ctx, **_coerce_params(params, known))

            # -- POST JSON-RPC (handlers.go:100-160) -----------------------

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                id_ = None
                admitted = False
                try:
                    req = json.loads(raw.decode())
                    id_ = req.get("id")
                    admit = server.admission.admit_request(
                        self.client_address[0],
                        self._request_kind(req.get("method", "")),
                    )
                    if not admit:
                        self._shed(admit, id_)
                        return
                    admitted = True
                    params = req.get("params") or {}
                    if isinstance(params, list):
                        route = server.routes.get(req.get("method", ""))
                        names = route[1] if route else []
                        params = dict(zip(names, params))
                    result = self._call(req["method"], params)
                    self._rpc_result(id_, result)
                except RPCError as exc:
                    self._rpc_error(id_, str(exc), status=400)
                except Exception as exc:  # noqa: BLE001 — surface, don't die
                    server.logger.exception("rpc error")
                    self._rpc_error(id_, f"{type(exc).__name__}: {exc}")
                finally:
                    if admitted:
                        server.admission.request_done()

            # -- GET URI + websocket (handlers.go:229-300, 351+) -----------

            def do_GET(self):
                parsed = urlparse(self.path)
                if parsed.path == "/websocket":
                    admit = server.admission.admit_request(
                        self.client_address[0], "ws")
                    if not admit:
                        self._shed(admit)
                        return
                    # the session must not hold an in-flight REQUEST slot
                    # for its whole lifetime — subscriber count has its
                    # own cap (ws_register, checked before the 101)
                    server.admission.request_done()
                    self._serve_websocket()
                    return
                if parsed.path == "/metrics":
                    # Prometheus text exposition 0.0.4 (round 11): real
                    # scrapers point here. The flat JSON form of the same
                    # gauges stays on the `metrics` JSON-RPC method (POST
                    # / websocket), which this GET path now shadows.
                    self._serve_prometheus()
                    return
                if parsed.path == "/health":
                    # liveness verdict (round 15, node/health.py): 200
                    # for ok/degraded, 503 for failing — probes key off
                    # the status code, the body is machine-readable
                    self._serve_health()
                    return
                if parsed.path.startswith("/debug/"):
                    # live wedge-triage surface (round 17): the flight
                    # ring, all-thread stacks, and queue depths — the
                    # three reads an operator needs against a node
                    # that stopped answering anything clever
                    self._serve_debug(parsed.path[len("/debug/"):])
                    return
                method = parsed.path.strip("/")
                if not method:
                    self._respond({"routes": sorted(server.routes)})
                    return
                admit = server.admission.admit_request(
                    self.client_address[0], self._request_kind(method))
                if not admit:
                    self._shed(admit)
                    return
                params = {}
                for k, v in parse_qsl(parsed.query):
                    try:
                        params[k] = json.loads(v)
                    except ValueError:
                        params[k] = v
                try:
                    self._rpc_result("", self._call(method, params))
                except RPCError as exc:
                    self._rpc_error("", str(exc), status=400)
                except Exception as exc:  # noqa: BLE001
                    server.logger.exception("rpc error")
                    self._rpc_error("", f"{type(exc).__name__}: {exc}")
                finally:
                    server.admission.request_done()

            def _serve_prometheus(self):
                from tendermint_tpu.libs import telemetry

                node = getattr(server.ctx, "node", None)
                reg = getattr(node, "telemetry", None)
                if reg is None:
                    # context without a node (mock harnesses): serve the
                    # process-wide instruments rather than 404ing the
                    # scrape target
                    reg = telemetry.default_registry()
                try:
                    body = reg.render_prometheus().encode()
                except Exception:  # noqa: BLE001 — a scrape must never
                    # take the RPC thread down with it
                    server.logger.exception("prometheus render failed")
                    self.send_error(500, "metrics render failed")
                    return
                self.send_response(200)
                self.send_header("Content-Type", telemetry.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_health(self):
                node = getattr(server.ctx, "node", None)
                if node is None:
                    # context without a node (mock harnesses): answer the
                    # probe rather than 404 the endpoint contract
                    self._respond({"status": "ok", "code": 0, "checks": {},
                                   "note": "no node in RPC context"})
                    return
                # a node-like facade (replica daemon) supplies its own
                # verdict through health_fn; full nodes use health_report
                health_fn = getattr(node, "health_fn", None)
                if health_fn is None:
                    from tendermint_tpu.node.health import health_report

                    health_fn = lambda: health_report(node)  # noqa: E731
                try:
                    report = health_fn()
                except Exception:  # noqa: BLE001 — a broken check is a
                    # wiring bug; surface it as a probe failure, never
                    # take the RPC thread down
                    server.logger.exception("health render failed")
                    self.send_error(500, "health render failed")
                    return
                self._respond(
                    report, status=503 if report["status"] == "failing"
                    else 200,
                )

            # -- debug introspection (round 17) ----------------------------

            def _serve_debug(self, what: str):
                """GET /debug/{flight,stacks,queues}. Every read is
                best-effort against live objects — a subsystem mid-
                teardown costs its section, never the endpoint (this is
                the surface for nodes that are already wedged)."""
                from tendermint_tpu.rpc.core.debug import debug_payload

                node = getattr(server.ctx, "node", None)
                try:
                    payload = debug_payload(what, node)
                except KeyError:
                    self.send_error(
                        404, "unknown debug endpoint (flight|stacks|queues)"
                    )
                    return
                except Exception:  # noqa: BLE001 — triage must not take
                    # the RPC thread down
                    server.logger.exception("debug render failed")
                    self.send_error(500, "debug render failed")
                    return
                self._respond(payload)

            # -- websocket -------------------------------------------------

            def _serve_websocket(self):
                key = self.headers.get("Sec-WebSocket-Key")
                if not key:
                    self.send_error(400, "not a websocket upgrade")
                    return
                conn = WSConnection(server, self.connection)
                if not server.admission.ws_register(conn):
                    # subscriber budget exhausted: typed 503 instead of
                    # the 101 (counted under rpc_shed_total{ws_cap})
                    self._shed(adm.Admit(False, 503, adm.SHED_WS_CAP, 1.0))
                    return
                sndbuf = server.admission.ws_sndbuf()
                if sndbuf:
                    # bounded kernel send buffer: a slow consumer's
                    # backlog lands in the accounted send queue instead
                    # of hiding in multi-MB socket buffers
                    try:
                        self.connection.setsockopt(
                            socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
                    except OSError:
                        pass
                accept = base64.b64encode(
                    hashlib.sha1((key + _WS_MAGIC).encode()).digest()
                ).decode()
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", "websocket")
                self.send_header("Connection", "Upgrade")
                self.send_header("Sec-WebSocket-Accept", accept)
                self.end_headers()
                conn.run()
                self.close_connection = True

        if is_unix_laddr(laddr):
            path = laddr.split("://", 1)[-1]
            self._httpd = _UnixThreadingHTTPServer(path, Handler)
            self.port = 0
            self.unix_path: str | None = path
        else:
            host, _, port = laddr.split("://", 1)[-1].rpartition(":")
            self._httpd = ThreadingHTTPServer((host or "0.0.0.0", int(port)), Handler)
            self.port = self._httpd.server_address[1]
            self.unix_path = None
        self._httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    def on_start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="rpc.httpd"
        )
        self._thread.start()
        if self.unix_path:
            self.logger.info("RPC server listening on unix://%s", self.unix_path)
        else:
            self.logger.info("RPC server listening on port %d", self.port)

    def on_stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self.unix_path:
            import os as _os

            try:
                _os.unlink(self.unix_path)
            except FileNotFoundError:
                pass


class WSConnection:
    """One WebSocket session: JSON-RPC calls + event subscriptions
    (handlers.go:351-630).

    Round 23 fan-out backpressure: outbound JSON rides a BOUNDED
    per-client queue drained by this client's own writer thread, so the
    event bus never blocks on a subscriber socket. Queue overflow drops
    the oldest N messages (counted); a subscriber that keeps
    overflowing is evicted (`ws_evictions_total`). Teardown is
    idempotent and runs on EVERY exit path — reader error, writer error,
    close frame, eviction — so a dead client can never leave a callback
    on the event delivery path."""

    def __init__(self, server: RPCServer, sock: socket.socket):
        self.server = server
        self.sock = sock
        self._wmtx = threading.Lock()
        self._listener_id = f"ws-{id(self):x}"
        self._subscribed: set[str] = set()
        self._closed = False
        self._sendq: deque = deque()
        self._q_cv = threading.Condition(threading.Lock())
        self._overflows = 0
        self._torn = False

    # -- frame IO (RFC 6455, server side: no masking on send) --------------

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return bytes(buf)

    def _read_frame(self) -> tuple[int, bytes]:
        b1, b2 = self._read_exact(2)
        opcode = b1 & 0x0F
        masked = b2 & 0x80
        length = b2 & 0x7F
        if length == 126:
            (length,) = struct.unpack(">H", self._read_exact(2))
        elif length == 127:
            (length,) = struct.unpack(">Q", self._read_exact(8))
        mask = self._read_exact(4) if masked else b""
        payload = self._read_exact(length)
        if mask:
            payload = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
        return opcode, payload

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        head = bytearray([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head.append(n)
        elif n < 1 << 16:
            head.append(126)
            head += struct.pack(">H", n)
        else:
            head.append(127)
            head += struct.pack(">Q", n)
        with self._wmtx:
            self.sock.sendall(bytes(head) + payload)

    def sendq_depth(self) -> int:
        with self._q_cv:
            return len(self._sendq)

    def send_json(self, obj) -> None:
        """Enqueue for this client's writer thread — the event-bus side
        of the session NEVER touches the socket, so one slow consumer
        cannot stall event delivery to anyone else."""
        if self._closed:
            return
        admission = self.server.admission
        qmax = admission.ws_send_queue()
        evict = False
        with self._q_cv:
            if self._torn:
                return
            if qmax and len(self._sendq) >= qmax:
                # drop-oldest N: the subscriber keeps the freshest
                # events; repeated overflow means it can't keep up at
                # all — evict rather than serve a permanently-lagged view
                drop = min(max(1, qmax // 4), len(self._sendq))
                for _ in range(drop):
                    self._sendq.popleft()
                self._overflows += 1
                admission.ws_dropped(drop)
                if self._overflows >= admission.ws_max_overflows():
                    evict = True
            if not evict:
                self._sendq.append(obj)
                self._q_cv.notify()
        if evict:
            admission.ws_evicted()
            self._teardown()

    def _writer_loop(self) -> None:
        try:
            while True:
                with self._q_cv:
                    while not self._sendq and not self._closed:
                        self._q_cv.wait(0.5)
                    if self._closed:
                        return
                    obj = self._sendq.popleft()
                self._send_frame(0x1, _dumps(obj))
        except (ConnectionError, OSError):
            pass
        finally:
            self._teardown()

    def _teardown(self) -> None:
        """Idempotent session teardown: deregister event callbacks,
        leave the subscriber registry, close the socket (which unblocks
        the reader), wake the writer. Safe from any thread."""
        with self._q_cv:
            if self._torn:
                return
            self._torn = True
            self._closed = True
            self._q_cv.notify_all()
        evsw = getattr(self.server.ctx, "event_switch", None)
        if evsw is not None:
            try:
                evsw.remove_listener(self._listener_id)
            except Exception:  # noqa: BLE001 — teardown must finish
                self.server.logger.exception("ws listener removal failed")
        self.server.admission.ws_unregister(self)
        try:
            self.sock.close()
        except OSError:
            pass

    # -- session loop ------------------------------------------------------

    def run(self) -> None:
        writer = threading.Thread(
            target=self._writer_loop, daemon=True, name="rpc.ws.writer"
        )
        writer.start()
        try:
            while not self._closed:
                opcode, payload = self._read_frame()
                if opcode == 0x8:  # close
                    self._send_frame(0x8, b"")
                    return
                if opcode == 0x9:  # ping
                    self._send_frame(0xA, payload)
                    continue
                if opcode not in (0x1, 0x2):
                    continue
                self._handle(payload)
        except (ConnectionError, OSError):
            pass
        finally:
            self._teardown()
            writer.join(timeout=2.0)

    def _handle(self, payload: bytes) -> None:
        id_ = None
        try:
            req = json.loads(payload.decode())
            id_ = req.get("id")
            method = req.get("method", "")
            params = req.get("params") or {}
            if method == "subscribe":
                self._subscribe(params["event"])
                result = {}
            elif method == "unsubscribe":
                self._unsubscribe(params["event"])
                result = {}
            else:
                route = self.server.routes.get(method)
                if route is None:
                    raise RPCError(f"unknown RPC method {method!r}")
                fn, known = route
                if isinstance(params, list):
                    params = dict(zip(known, params))
                result = fn(self.server.ctx, **_coerce_params(params, known))
            self.send_json({"jsonrpc": "2.0", "id": id_, "result": result, "error": ""})
        except Exception as exc:  # noqa: BLE001
            self.send_json(
                {"jsonrpc": "2.0", "id": id_, "result": None, "error": f"{exc}"}
            )

    def _subscribe(self, event: str) -> None:
        admission = self.server.admission
        if (admission.pressure_fn is not None
                and admission.pressure_fn() >= adm.PRESSURE_SHED_READS):
            # ladder rung 1: new subscriptions shed with the reads
            admission.shed(adm.SHED_READS)
            raise RPCError(f"shed:{adm.SHED_READS}")
        evsw = self.server.ctx.event_switch
        if evsw is None:
            raise RPCError("no event switch")
        if event in self._subscribed:
            return
        self._subscribed.add(event)

        def on_event(data, event=event):
            self.send_json(
                {
                    "jsonrpc": "2.0",
                    "id": "",
                    "result": {"event": event, "data": data},
                    "error": "",
                }
            )

        evsw.add_listener_for_event(self._listener_id, event, on_event)

    def _unsubscribe(self, event: str) -> None:
        evsw = self.server.ctx.event_switch
        if evsw is None:
            return
        self._subscribed.discard(event)
        evsw.remove_listener_for_event(event, self._listener_id)
