"""RPC ingress admission (round 23, docs/serving.md).

The serving edge of the overload-control plane: every HTTP/WS request
passes one AdmissionController before it reaches a handler. The
controller enforces

  * a connection cap (bounds the one-thread-per-connection server),
  * an in-flight request cap (bounds concurrently-executing handlers),
  * per-source token-bucket rate limits keyed by client IP — unix-socket
    peers (the node's own operator surface) are exempt,
  * per-request deadline budgets (handlers with waits consult
    `deadline_remaining()` and fail typed instead of holding a thread),
  * the load-shed ladder (node/health.OverloadMonitor): at shed-reads,
    read and subscribe traffic is refused at this edge; writes are never
    refused here — at shed-writes the MEMPOOL still admits the priority
    lane, so refusing writes wholesale at the door would shed exactly
    the traffic the ladder promises to protect.

Sheds are typed (HTTP 429/503 + Retry-After + a stable reason string)
and counted per reason — `rpc_shed_total{reason}` on the scrape surface.
Every knob has a TENDERMINT_RPC_* env twin; env wins over config and is
read per request, so limits are live-tunable under fire.

The WS half: the controller is also the registry of live WSConnections
(per-client bounded send queues live in rpc/server.py) — it caps
subscriber count, aggregates queue depths for the pressure signal, and
owns the eviction/drop counters.
"""

from __future__ import annotations

import math
import threading
import time

from tendermint_tpu.libs.envknob import env_number

# stable shed reasons — the rpc_shed_total{reason} label set
SHED_CONN_CAP = "conn_cap"
SHED_INFLIGHT = "inflight_cap"
SHED_RATE_LIMITED = "rate_limited"
SHED_READS = "shed_reads"
SHED_WS_CAP = "ws_cap"
SHED_DEADLINE = "deadline"
SHED_REASONS = (
    SHED_CONN_CAP,
    SHED_INFLIGHT,
    SHED_RATE_LIMITED,
    SHED_READS,
    SHED_WS_CAP,
    SHED_DEADLINE,
)

# ladder levels, mirrored from node/health.py (no node-package import
# from the rpc layer)
PRESSURE_OK = 0
PRESSURE_SHED_READS = 1
PRESSURE_SHED_WRITES = 2

_UNIX_PEER = "unix"  # client_address[0] of a unix-socket connection

# idle token buckets older than this are pruned (bounds per-IP state)
_BUCKET_IDLE_S = 120.0
_BUCKET_PRUNE_LEN = 4096

_tls = threading.local()


def set_deadline(budget_s: float) -> None:
    _tls.deadline = (time.monotonic() + budget_s) if budget_s > 0 else None


def clear_deadline() -> None:
    _tls.deadline = None


def deadline_remaining() -> float | None:
    """Seconds left in this request's budget; None = no deadline armed.
    Handlers with waits bound them by this (rpc/core/handlers.py)."""
    dl = getattr(_tls, "deadline", None)
    return None if dl is None else dl - time.monotonic()


def request_source() -> str:
    """Client IP of the request running on this thread ("" outside a
    request). Keys the mempool's per-source admission counters so one
    spamming IP hits its own ceiling, not everyone's."""
    return getattr(_tls, "source_ip", "")


class Admit:
    """One admission verdict. Truthy when admitted; a shed carries the
    HTTP status, stable reason, and Retry-After seconds."""

    __slots__ = ("ok", "status", "reason", "retry_after")

    def __init__(self, ok: bool, status: int = 200, reason: str = "",
                 retry_after: float = 0.0):
        self.ok = ok
        self.status = status
        self.reason = reason
        self.retry_after = retry_after

    def __bool__(self) -> bool:
        return self.ok


_ADMITTED = Admit(True)


class AdmissionController:
    """Shared ingress state for one RPC server (rpc/server.py holds one;
    the node wires its own so telemetry and the pressure ladder see it)."""

    def __init__(self, config=None):
        self.config = config
        self._mtx = threading.Lock()
        self.connections = 0
        self.inflight = 0
        # ip -> [tokens, last_refill_monotonic]
        self._buckets: dict[str, list[float]] = {}
        self.sheds = {reason: 0 for reason in SHED_REASONS}
        self.sheds_total = 0
        # wired by the node to OverloadMonitor.level; None = ladder off
        self.pressure_fn = None
        # -- WS subscriber registry ------------------------------------
        self._ws_mtx = threading.Lock()
        self._ws: set = set()
        self.ws_evictions = 0
        self.ws_dropped_events = 0

    # -- knobs (env wins over config, read per call: live-tunable) -------

    def _knob(self, env: str, attr: str, default: float) -> float:
        return env_number(env, getattr(self.config, attr, default))

    def max_connections(self) -> int:
        return int(self._knob("TENDERMINT_RPC_MAX_CONNECTIONS",
                              "max_connections", 512))

    def max_inflight(self) -> int:
        return int(self._knob("TENDERMINT_RPC_MAX_INFLIGHT",
                              "max_inflight", 256))

    def rate_limit(self) -> float:
        return float(self._knob("TENDERMINT_RPC_RATE_LIMIT", "rate_limit", 0.0))

    def rate_burst(self) -> float:
        burst = float(self._knob("TENDERMINT_RPC_RATE_BURST", "rate_burst", 0.0))
        return burst if burst > 0 else 2.0 * self.rate_limit()

    def deadline_s(self) -> float:
        return float(self._knob("TENDERMINT_RPC_DEADLINE_S", "deadline_s", 0.0))

    def ws_send_queue(self) -> int:
        return int(self._knob("TENDERMINT_RPC_WS_QUEUE", "ws_send_queue", 256))

    def ws_max_clients(self) -> int:
        return int(self._knob("TENDERMINT_RPC_WS_MAX_CLIENTS",
                              "ws_max_clients", 200))

    def ws_max_overflows(self) -> int:
        """Queue overflows (each dropping the oldest N events) a slow
        subscriber survives before eviction."""
        return int(env_number("TENDERMINT_RPC_WS_MAX_OVERFLOWS", 4))

    def ws_sndbuf(self) -> int:
        """Server-side SO_SNDBUF for WS sockets, bytes (0 = kernel
        default). The kernel's multi-megabyte send buffer can hide a
        slow consumer from the bounded-queue plane for minutes;
        bounding it moves the backlog into the send queue, where the
        drop/evict accounting lives."""
        return int(env_number("TENDERMINT_RPC_WS_SNDBUF", 0, cast=int))

    # -- counting --------------------------------------------------------

    def shed(self, reason: str) -> None:
        with self._mtx:
            self.sheds[reason] = self.sheds.get(reason, 0) + 1
            self.sheds_total += 1

    # -- connection budget ----------------------------------------------

    def conn_acquire(self) -> Admit:
        cap = self.max_connections()
        with self._mtx:
            if cap and self.connections >= cap:
                pass  # shed below, outside the lock
            else:
                self.connections += 1
                return _ADMITTED
        self.shed(SHED_CONN_CAP)
        return Admit(False, 503, SHED_CONN_CAP, 1.0)

    def conn_release(self) -> None:
        with self._mtx:
            self.connections = max(0, self.connections - 1)

    # -- per-request admission -------------------------------------------

    def admit_request(self, client_ip: str, kind: str) -> Admit:
        """kind: "read" | "write" | "ws" | "ops". Admitted non-ops
        requests hold an in-flight slot and an armed deadline until
        `request_done()`. "ops" (/metrics, /health, /debug) is always
        admitted and never counted — an overloaded node must stay
        observable from scrapes alone (the docs/serving.md runbook)."""
        if kind == "ops":
            return _ADMITTED
        level = self.pressure_fn() if self.pressure_fn is not None else 0
        if level >= PRESSURE_SHED_READS and kind in ("read", "ws"):
            # the ladder's first rung: reads and subscriptions shed at
            # the edge while writes still reach the mempool's lanes
            self.shed(SHED_READS)
            return Admit(False, 503, SHED_READS, 1.0)
        rate = self.rate_limit()
        if rate > 0 and client_ip != _UNIX_PEER:
            wait = self._bucket_take(client_ip, rate, self.rate_burst())
            if wait > 0:
                self.shed(SHED_RATE_LIMITED)
                return Admit(False, 429, SHED_RATE_LIMITED, wait)
        cap = self.max_inflight()
        with self._mtx:
            if cap and self.inflight >= cap:
                over = True
            else:
                over = False
                self.inflight += 1
        if over:
            self.shed(SHED_INFLIGHT)
            return Admit(False, 503, SHED_INFLIGHT, 1.0)
        set_deadline(self.deadline_s())
        _tls.source_ip = client_ip
        return _ADMITTED

    def request_done(self) -> None:
        with self._mtx:
            self.inflight = max(0, self.inflight - 1)
        clear_deadline()
        _tls.source_ip = ""

    def _bucket_take(self, ip: str, rate: float, burst: float) -> float:
        """Take one token from ip's bucket; 0.0 = taken, else seconds
        until a token is available (the Retry-After value)."""
        now = time.monotonic()
        with self._mtx:
            b = self._buckets.get(ip)
            if b is None:
                if len(self._buckets) >= _BUCKET_PRUNE_LEN:
                    self._buckets = {
                        k: v for k, v in self._buckets.items()
                        if now - v[1] < _BUCKET_IDLE_S
                    }
                b = self._buckets[ip] = [burst, now]
            tokens = min(burst, b[0] + (now - b[1]) * rate)
            b[1] = now
            if tokens < 1.0:
                b[0] = tokens
                return (1.0 - tokens) / rate
            b[0] = tokens - 1.0
            return 0.0

    # -- WS subscriber registry ------------------------------------------

    def ws_register(self, conn) -> bool:
        cap = self.ws_max_clients()
        with self._ws_mtx:
            if cap and len(self._ws) >= cap:
                full = True
            else:
                full = False
                self._ws.add(conn)
        if full:
            self.shed(SHED_WS_CAP)
        return not full

    def ws_unregister(self, conn) -> None:
        with self._ws_mtx:
            self._ws.discard(conn)

    def ws_clients(self) -> int:
        with self._ws_mtx:
            return len(self._ws)

    def ws_evicted(self) -> None:
        with self._ws_mtx:
            self.ws_evictions += 1

    def ws_dropped(self, n: int) -> None:
        with self._ws_mtx:
            self.ws_dropped_events += n

    def ws_queue_frac(self) -> float:
        """Max send-queue fill fraction across live subscribers — the WS
        input to the pressure signal (node/health.OverloadMonitor)."""
        qmax = self.ws_send_queue() or 1
        with self._ws_mtx:
            conns = list(self._ws)
        depth = 0
        for c in conns:
            depth = max(depth, c.sendq_depth())
        return min(1.0, depth / qmax)

    # -- telemetry view ---------------------------------------------------

    def snapshot(self) -> dict:
        """Flat instantaneous view (node/telemetry.py "rpc" producer)."""
        with self._mtx:
            out = {
                "inflight": self.inflight,
                "connections": self.connections,
                "sheds": self.sheds_total,
                "deadline_rejects": self.sheds.get(SHED_DEADLINE, 0),
            }
        out["ws_clients"] = self.ws_clients()
        with self._ws_mtx:
            out["ws_evictions"] = self.ws_evictions
            out["ws_dropped_events"] = self.ws_dropped_events
        return out


def retry_after_header(seconds: float) -> str:
    """Retry-After is whole seconds (RFC 7231 §7.1.3); never "0"."""
    return str(max(1, math.ceil(seconds)))
