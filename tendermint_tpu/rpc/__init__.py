"""JSON-RPC API surface (reference: rpc/).

Three transports, same handlers (rpc/lib/server/handlers.go:26-34):
- POST / with a JSON-RPC 2.0 envelope
- GET /<method>?arg=val URI calls
- WebSocket /websocket with JSON-RPC framing + event subscriptions
"""

from tendermint_tpu.rpc.server import RPCServer
from tendermint_tpu.rpc.client import HTTPClient, LocalClient

__all__ = ["RPCServer", "HTTPClient", "LocalClient"]
