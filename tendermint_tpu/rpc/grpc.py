"""The gRPC broadcast API (reference: rpc/grpc/api.go:14 BroadcastAPI —
Ping + BroadcastTx — with client/server helpers in
rpc/grpc/client_server.go:15-48).

Same transport redesign as abci/grpc.py: gRPC unary methods under the
reference's service name, bodies in this framework's canonical JSON.
BroadcastTx runs the full broadcast_tx_commit path (CheckTx, then wait
for the tx to land in a block) exactly like the reference's
core.BroadcastTxCommit hand-off.
"""

from __future__ import annotations

from concurrent import futures as _futures

from tendermint_tpu.libs.grpcutil import bind_insecure, json_deserializer as _de, json_serializer as _ser
from tendermint_tpu.libs.service import BaseService

SERVICE = "tendermint.rpc.grpc.BroadcastAPI"


class GRPCBroadcastServer(BaseService):
    """Serves Ping + BroadcastTx against an RPCContext (the same ctx the
    JSON-RPC server uses, so both ports share one behavior)."""

    def __init__(self, addr: str, ctx):
        super().__init__("rpc.grpc")
        import grpc

        self.ctx = ctx
        self._server = grpc.server(_futures.ThreadPoolExecutor(max_workers=4))

        def ping(request: dict, context) -> dict:
            return {}

        def broadcast_tx(request: dict, context) -> dict:
            from tendermint_tpu.rpc.core import handlers

            try:
                res = handlers.broadcast_tx_commit(self.ctx, request["tx"])
            except Exception as exc:  # noqa: BLE001 — surface as payload
                return {"error": str(exc)}
            return {
                "check_tx": res["check_tx"],
                "deliver_tx": res["deliver_tx"],
                "height": res.get("height", 0),
                "hash": res.get("hash", ""),
            }

        handler = {
            "Ping": grpc.unary_unary_rpc_method_handler(
                ping, request_deserializer=_de, response_serializer=_ser
            ),
            "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                broadcast_tx, request_deserializer=_de, response_serializer=_ser
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handler),)
        )
        self.addr = bind_insecure(self._server, addr)

    def on_start(self) -> None:
        self._server.start()

    def on_stop(self) -> None:
        self._server.stop(grace=0.5)


class GRPCBroadcastClient:
    """Client for the broadcast API (rpc/grpc/client_server.go:15-24)."""

    def __init__(self, addr: str, timeout: float = 10.0):
        import grpc

        self._channel = grpc.insecure_channel(addr)
        grpc.channel_ready_future(self._channel).result(timeout=timeout)
        self._ping = self._channel.unary_unary(
            f"/{SERVICE}/Ping", request_serializer=_ser, response_deserializer=_de
        )
        self._btx = self._channel.unary_unary(
            f"/{SERVICE}/BroadcastTx", request_serializer=_ser, response_deserializer=_de
        )

    def ping(self) -> dict:
        return self._ping({})

    def broadcast_tx(self, tx: bytes, timeout: float = 60.0) -> dict:
        return self._btx({"tx": tx.hex()}, timeout=timeout)

    def close(self) -> None:
        self._channel.close()
