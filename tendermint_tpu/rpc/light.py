"""Light client: verify headers and data through the RPC surface alone.

Working implementation of docs/specification/light-client-protocol.md
(the reference ships only the spec, light-client-protocol.rst; here the
verifier is code, and its batch-verify hook means even a light client's
commit checks can ride the TPU gateway).

Trust model: start from a trusted validator set (genesis or out-of-band);
`verify_header(h)` accepts a header only if that set still holds +2/3 of
the commit; `advance()` walks trust forward height-by-height (sequential
verification — no skipping/bisection, matching the reference line).
"""

from __future__ import annotations

from collections import OrderedDict

from tendermint_tpu.types.agg_commit import commit_from_json, commit_is_aggregate
from tendermint_tpu.types.block import Commit, Header
from tendermint_tpu.types.validator_set import CommitError, ValidatorSet


class LightClientError(Exception):
    pass


class LightClient:
    """`client` is any RPC client exposing .commit/.validators/.tx
    (rpc/client.py HTTPClient, LocalClient, or a test stub)."""

    def __init__(self, client, chain_id: str, trusted_validators: ValidatorSet,
                 trusted_height: int = 0, batch_verifier=None):
        self.client = client
        self.chain_id = chain_id
        self.validators = trusted_validators
        self.height = trusted_height
        self.batch_verifier = batch_verifier
        # last header verified by advance(); persisting it means every
        # validator-set change is chain-linked to a verified predecessor,
        # even across separate advance() calls
        self._trusted_header: Header | None = None
        # small LRU of VERIFIED headers by height (round 24): N proof
        # checks at one height cost one commit verification, and a read
        # replica can verify proofs at any recent height without
        # re-walking trust. Every entry comes out of _verify_with, so
        # everything memoized carried +2/3 of a trusted/adopted set.
        self.header_memo_max = 64
        self._header_memo: OrderedDict[int, Header] = OrderedDict()

    @classmethod
    def from_genesis(cls, client, **kw) -> "LightClient":
        """Bootstrap trust from the node's /genesis (trust-on-first-use;
        for stronger setups pass an out-of-band validator set instead)."""
        from tendermint_tpu.types.genesis import GenesisDoc
        from tendermint_tpu.types.validator import Validator

        doc = GenesisDoc.from_json(client.genesis()["genesis"])
        vs = ValidatorSet(
            [Validator.new(v.pub_key, v.power) for v in doc.validators]
        )
        return cls(client, doc.chain_id, vs, trusted_height=0, **kw)

    def copy(self) -> "LightClient":
        """A speculative clone sharing this client's transport and
        current trust: advancing the clone never mutates this instance
        (advance() only REBINDS validators/height/_trusted_header, it
        never mutates the set in place). The statesync restorer walks a
        clone per candidate snapshot and adopts it only once the
        manifest binds — a forged high-height offer must not advance
        trust past lower, honest snapshots."""
        c = LightClient(
            self.client, self.chain_id, self.validators, self.height,
            batch_verifier=self.batch_verifier,
        )
        c._trusted_header = self._trusted_header
        # the memo is copied, not shared: the clone's walk must never
        # mutate this instance's state
        c.header_memo_max = self.header_memo_max
        c._header_memo = OrderedDict(self._header_memo)
        return c

    def trusted_header(self) -> Header | None:
        """The last header advance() fully verified (None until the first
        advance past an anchor). The statesync restorer reads headers H
        and H+1 off the walk to bind a snapshot manifest to the verified
        chain."""
        return self._trusted_header

    # -- header verification ------------------------------------------------

    def verify_header(self, height: int, _res: dict | None = None) -> Header:
        """Fetch (header, commit) at `height` and verify +2/3 of the
        TRUSTED set signed it. Returns the verified header; raises
        LightClientError otherwise. Does not advance trust. `_res` lets
        advance() share one /commit fetch instead of issuing two."""
        return self._verify_with(self.validators, height, _res)

    def _verify_with(
        self, validators: ValidatorSet, height: int, _res: dict | None = None
    ) -> Header:
        """verify_header against an explicit set — advance() uses this so a
        candidate set is never installed as trusted before it verifies."""
        res = _res if _res is not None else self.client.commit(height=int(height))
        if not res.get("commit") or not res.get("header"):
            raise LightClientError(f"no commit/header for height {height}")
        try:
            header = Header.from_json(res["header"])
            # polymorphic: post-upgrade heights serve AggregateCommit
            # (docs/upgrade.md); verify_commit dispatches on the form
            commit = commit_from_json(res["commit"])
        except ValueError as exc:
            # the serving node's response is untrusted input too
            raise LightClientError(f"malformed commit response: {exc}")
        if header.chain_id != self.chain_id:
            raise LightClientError(
                f"chain id {header.chain_id!r} != trusted {self.chain_id!r}"
            )
        if header.height != height:
            raise LightClientError("header height mismatch")
        # the commit must be over THIS header: BlockID.hash == header hash
        if commit.block_id.hash != header.hash():
            raise LightClientError("commit is not over the fetched header")
        # the signing set must be the verifying one, and the header must
        # commit to the same set
        if header.validators_hash != validators.hash():
            raise LightClientError(
                "validator set changed; advance() trust to this height first"
            )
        try:
            validators.verify_commit(
                self.chain_id, commit.block_id, height, commit,
                batch_verifier=self.batch_verifier,
            )
        except CommitError as exc:
            raise LightClientError(f"commit verification failed: {exc}")
        self._memo_header(height, header)
        return header

    def _memo_header(self, height: int, header: Header) -> None:
        memo = self._header_memo
        memo[height] = header
        memo.move_to_end(height)
        while len(memo) > max(1, self.header_memo_max):
            memo.popitem(last=False)

    def header_at(self, height: int) -> Header:
        """A verified header at `height`, from the memo when possible
        (round 24): repeat proof checks at one height verify its commit
        once, not once per query. Advances trust when `height` is ahead
        of the walk; raises LightClientError when the height fell out of
        the memo behind trust (re-query for a fresher proof)."""
        hdr = self._header_memo.get(height)
        if hdr is not None:
            self._header_memo.move_to_end(height)
            return hdr
        if height > self.height:
            self.advance(height)
        if height == self.height and self._trusted_header is not None:
            return self._trusted_header
        hdr = self._header_memo.get(height)
        if hdr is not None:
            return hdr
        raise LightClientError(
            f"no verified header at {height} (trust is at {self.height}); "
            "re-query for a fresher proof"
        )

    def advance(self, to_height: int) -> None:
        """Walk trust forward to `to_height`, verifying every header with
        the then-trusted set.

        Validator-set changes: this header format carries no
        next_validators_hash, so a claimed new set can't be linked
        cryptographically through the previous header alone — a node
        could serve a forged set vouched for only by itself. The sound
        sequential rule used here: adopt a new set at height h only if
        (a) it matches header h's validators_hash, (b) +2/3 of the NEW
        set signed commit(h), (c) header h chains to the verified header
        h-1 (last_block_id), and (d) the valid precommits in commit(h)
        cast by validators PRESENT IN THE OLD TRUSTED SET carry > 2/3 of
        the old set's power — i.e. the set we already trust still
        controls the chain across the transition. An attacker without
        2/3 of the trusted keys cannot fabricate (d).

        Pruned sources (round 19, bounded retention): a server that
        pruned history below its store base cannot serve the sequential
        walk's early commits. When a commit fetch fails AND the server's
        /status attests `earliest_block_height > h`, the walk JUMPS to
        that horizon. Across the gap, header linkage (c) is unknowable
        and is skipped; trust transfers on the signature rules alone —
        same set: +2/3 of the CURRENTLY trusted set on the horizon
        commit; changed set: rules (a)/(b)/(d), i.e. the old trusted
        set's members must still carry > 2/3 of its power among the
        horizon commit's valid precommits (strictly stronger than
        production Tendermint's 1/3-overlap skipping rule). A set that
        turned over past that bound inside the pruned gap fails loudly:
        the operator must pin statesync.trust_height inside the retained
        window. A lying `earliest_block_height` is denial-of-service
        only — it can widen the jump, never weaken the signature rules."""
        prev_header = self._trusted_header
        if prev_header is None and self.height >= 1:
            # trust was established out-of-band (or this object was rebuilt
            # from a persisted height): verify the trusted height itself so
            # every later set change is chain-linked to a VERIFIED header —
            # only genesis trust (height 0) legitimately has no predecessor
            prev_header = self.verify_header(self.height)
            self._trusted_header = prev_header
        # a verified header at self.height means the walk starts after it;
        # only genesis trust (no header) starts at 1
        h = self.height + 1 if prev_header is not None else 1
        while h <= to_height:
            try:
                res = self.client.commit(height=h)
            except Exception:
                jump = self._horizon_jump_target(h, to_height)
                if jump is None:
                    raise  # a real transport/server failure
                prev_header = None  # linkage across the pruned gap is
                # unknowable; the signature rules below carry the trust
                h = jump
                continue
            try:
                header = Header.from_json(res.get("header"))
            except ValueError as exc:
                raise LightClientError(f"malformed header at {h}: {exc}")
            vals = self.validators
            if header.validators_hash != vals.hash():
                claimed = ValidatorSet.from_json(
                    self.client.validators(height=h)["validators"]
                )
                if claimed.hash() != header.validators_hash:
                    raise LightClientError(
                        f"claimed validator set at {h} does not match header"
                    )
                if prev_header is not None and (
                    header.last_block_id.hash != prev_header.hash()
                ):
                    raise LightClientError(
                        f"header {h} does not chain to verified header {h - 1}"
                    )
                try:
                    commit = commit_from_json(res["commit"])
                except ValueError as exc:
                    raise LightClientError(f"malformed commit at {h}: {exc}")
                self._check_old_set_overlap(h, commit, claimed)
                vals = claimed
            # verify with the candidate set FIRST; only a fully verified
            # height moves trust (set, height, header) forward — a raised
            # LightClientError leaves the previous trusted state intact
            prev_header = self._verify_with(vals, h, _res=res)
            self.validators = vals
            self.height = h
            self._trusted_header = prev_header
            h += 1

    def horizon_floor(self) -> int | None:
        """The server's attested earliest servable height
        (/status earliest_block_height, round 19) — what a caller
        stepping the walk in its own strides (statesync's header-caching
        loop) consults to aim past a pruned gap. None when the probe
        fails or the server predates the field."""
        try:
            st = self.client.status()
        except Exception:  # noqa: BLE001 — dead server: no attestation
            return None
        earliest = st.get("earliest_block_height", 0) or 0
        if isinstance(earliest, int) and earliest > 0:
            return earliest
        return None

    def _horizon_jump_target(self, h: int, to_height: int) -> int | None:
        """Where the walk may legally resume when the server cannot
        serve height `h`: the server's own attested earliest height,
        IFF it proves a pruned gap (earliest above h, at or below the
        target). None re-raises the original fetch failure."""
        earliest = self.horizon_floor()
        if earliest is not None and h < earliest <= to_height:
            return earliest
        return None

    def _check_old_set_overlap(
        self, height: int, commit: Commit, new_set: ValidatorSet
    ) -> None:
        """Condition (d) of advance(): > 2/3 of the OLD trusted set's
        power signed commit(height), counting each precommit under the
        NEW set's index order but crediting the OLD set's power.

        Round 16: with `batch_verifier` wired, the structural filter runs
        first and every candidate signature flushes in ONE gateway batch
        (the turnover check was the last per-sig loop on the light walk);
        per-lane verdicts feed the same tally, so accept/reject is
        byte-identical to the sequential loop."""
        old = self.validators
        if commit_is_aggregate(commit):
            self._check_old_set_overlap_aggregate(height, commit, new_set)
            return
        candidates = []  # (old_val, sign_bytes, signature)
        for idx, pre in enumerate(commit.precommits):
            if pre is None or pre.signature is None:
                continue
            # only precommits FOR this commit's block at this height count:
            # commit_tally tolerates valid precommits for other block ids
            # (they're evidence of the network's round, not endorsement), so
            # without this filter an attacker could stuff replayed genuine
            # old-set precommits from the real chain — same height, different
            # block — into a forged commit and satisfy (d) with zero old-set
            # endorsement of the fork
            if (
                pre.height != height
                or pre.round_ != commit.round_()
                or pre.block_id != commit.block_id
            ):
                continue
            _, val = new_set.get_by_index(idx)
            if val is None:
                continue
            _, old_val = old.get_by_address(val.address)
            if old_val is None:
                continue
            candidates.append(
                (old_val, pre.sign_bytes(self.chain_id), pre.signature)
            )
        if self.batch_verifier is not None:
            oks = self.batch_verifier(
                [(v.pub_key.raw, sb, sig.raw) for v, sb, sig in candidates]
            )
        else:
            oks = [
                v.pub_key.verify_bytes(sb, sig) for v, sb, sig in candidates
            ]
        signed_old_power = sum(
            v.voting_power for (v, _, _), ok in zip(candidates, oks) if ok
        )
        if signed_old_power * 3 <= old.total_voting_power() * 2:
            raise LightClientError(
                f"validator change at {height}: trusted set signed only "
                f"{signed_old_power}/{old.total_voting_power()} power"
            )

    def _check_old_set_overlap_aggregate(
        self, height: int, commit, new_set: ValidatorSet
    ) -> None:
        """Condition (d) for an aggregate-format commit (docs/upgrade.md):
        the half-aggregate is one indivisible equation over the NEW set's
        signer lanes, so it verifies whole — against the new set — and
        then the OLD trusted set's power is credited over the signer
        BITMAP (a signer lane that fails would fail the whole equation,
        so a verified aggregate proves every bitmap member signed).
        The per-lane scalar muls ride the gateway's batched path."""
        if (
            commit.height() != height
            or commit.block_id.is_zero()
        ):
            raise LightClientError(
                f"aggregate commit at {height} has wrong coordinates"
            )
        try:
            commit.verify(self.chain_id, new_set)
        except CommitError as exc:
            raise LightClientError(
                f"validator change at {height}: aggregate commit failed: {exc}"
            )
        old = self.validators
        signed_old_power = 0
        for idx in commit.signers.indices():
            _, val = new_set.get_by_index(idx)
            if val is None:
                continue
            _, old_val = old.get_by_address(val.address)
            if old_val is not None:
                signed_old_power += old_val.voting_power
        if signed_old_power * 3 <= old.total_voting_power() * 2:
            raise LightClientError(
                f"validator change at {height}: trusted set signed only "
                f"{signed_old_power}/{old.total_voting_power()} power"
            )

    # -- data verification --------------------------------------------------

    def verified_query(self, key: bytes, path: str = "", height: int = 0) -> dict:
        """A light-client VERIFIED state read (round 13): `abci_query`
        with prove=True, the returned state-tree proof checked against
        the app hash carried by the light-verified header at
        (proof height + 1) — header H+1 commits to the app state block H
        produced. `height` pins the proven version (0 = the app's
        latest; note a proof at the chain HEAD verifies only once the
        next block commits — pass head-1 for an immediately verifiable
        read). Returns {"key", "value", "height", "absent", "proof"};
        `value` is None (and `absent` True) for a verified absence.
        Raises LightClientError on any failure: a missing proof, a
        proofs-unsupported app, a proof that does not verify, or a
        response value contradicting the proven one."""
        import json as _json

        from tendermint_tpu.merkle.statetree_proof import TreeProof

        res = self.client.abci_query(
            data=key.hex(), path=path, height=int(height), prove=True
        )
        resp = res.get("response") if isinstance(res, dict) else None
        if not isinstance(resp, dict):
            raise LightClientError("malformed abci_query response")
        code = resp.get("code", 0)
        if code != 0:
            raise LightClientError(
                f"query refused (code {code}): {resp.get('log', '')}"
            )
        proof_hex = resp.get("proof") or ""
        if not isinstance(proof_hex, str) or not proof_hex:
            raise LightClientError("node returned no state proof")
        h = resp.get("height")
        if not isinstance(h, int) or isinstance(h, bool) or h < 1:
            raise LightClientError("bad proof height in query response")
        try:
            proof = TreeProof.from_json(_json.loads(bytes.fromhex(proof_hex)))
        except ValueError as exc:
            raise LightClientError(f"malformed state proof: {exc}")
        if proof.key != key:
            raise LightClientError("proof is for a different key")
        # the root that commits height-h app state is header (h+1)'s
        # app_hash; header_at serves repeat queries at one height from
        # the verified-header memo and walks trust forward when needed
        app_hash = self.header_at(h + 1).app_hash
        if not proof.verify(app_hash):
            raise LightClientError(
                f"state proof failed verification against header {h + 1}"
            )
        # the response's bare value must BE the proven one — otherwise a
        # node could prove one value while returning another
        resp_value = bytes.fromhex(resp.get("value") or "")
        if proof.is_membership:
            if resp_value != proof.value:
                raise LightClientError("response value does not match proven value")
        elif resp_value:
            raise LightClientError("response carries a value the proof says is absent")
        return {
            "key": key,
            "value": proof.value,
            "height": h,
            "absent": not proof.is_membership,
            "proof": proof,
        }

    def verify_tx(self, tx_hash: bytes, header: Header) -> dict:
        """Fetch a tx with proof and check inclusion against a VERIFIED
        header's data_hash (types/tx.py TxProof)."""
        from tendermint_tpu.types.tx import TxProof

        from tendermint_tpu.types.tx import tx_hash as _tx_hash

        res = self.client.tx(hash=tx_hash.hex(), prove=True)
        if not res.get("proof"):
            raise LightClientError("node returned no proof")
        proof = TxProof.from_json(res["proof"])
        err = proof.validate(header.data_hash)
        if err is not None:
            raise LightClientError(f"tx inclusion proof failed: {err}")
        # the proof must be for the REQUESTED tx, and the response's tx
        # bytes must be the proven ones — otherwise a node could prove
        # some other committed tx while returning arbitrary payload
        if _tx_hash(proof.data) != tx_hash:
            raise LightClientError("proof is for a different tx")
        if bytes.fromhex(res["tx"]) != bytes(proof.data):
            raise LightClientError("response tx does not match proven tx")
        return res
