"""Mock RPC client for tests (reference: rpc/client/mock/client.go).

Implements the same `call(method, **params)` + attribute-sugar surface as
rpc/client.HTTPClient / LocalClient, with:

- canned responses per method — a value, a callable(**params), or an
  Exception instance (raised);
- a recorded `calls` list (reference mock.Call) so tests assert exactly
  what the unit under test requested;
- an optional passthrough client for methods without a canned response
  (the reference's mock-with-real-ABCI composition).

Replaces the ad-hoc per-test stubs flagged in VERDICT r3 (e.g. the light
client's); those remain where they model richer behavior (a whole chain),
but one-method stubbing should use this.
"""

from __future__ import annotations

from dataclasses import dataclass, field


class MockClientError(Exception):
    pass


@dataclass
class Call:
    method: str
    params: dict
    response: object = None
    error: BaseException | None = None


@dataclass
class MockClient:
    """responses: method name -> canned value | callable(**params) |
    Exception. `client`: optional real client consulted for methods with
    no canned entry (else MockClientError)."""

    responses: dict = field(default_factory=dict)
    client: object = None
    calls: list = field(default_factory=list)

    def expect(self, method: str, response) -> "MockClient":
        """Chainable: mock.expect("status", {...}).expect("tx", boom)."""
        self.responses[method] = response
        return self

    def call(self, method: str, **params):
        rec = Call(method=method, params=dict(params))
        self.calls.append(rec)
        try:
            if method in self.responses:
                r = self.responses[method]
                if isinstance(r, BaseException):
                    raise r
                if callable(r):
                    r = r(**params)
            elif self.client is not None:
                r = self.client.call(method, **params)
            else:
                raise MockClientError(f"no canned response for {method!r}")
        except BaseException as exc:
            rec.error = exc
            raise
        rec.response = r
        return r

    def calls_for(self, method: str) -> list[Call]:
        return [c for c in self.calls if c.method == method]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)
