from tendermint_tpu.rpc.core.pipe import RPCContext
from tendermint_tpu.rpc.core.routes import build_routes

__all__ = ["RPCContext", "build_routes"]
