"""Debug introspection payloads (round 17): GET /debug/{flight,stacks,
queues} on the RPC listener (rpc/server.py dispatches here).

These are the live-triage reads for a node that has stopped answering
anything clever — a wedged consensus thread still leaves the RPC
listener (its own threads) serving these:

- ``flight``  the black-box event ring (node/flightrec.py) — what
              happened in the recent past
- ``stacks``  every thread's current stack via sys._current_frames —
              WHERE a wedge is parked right now
- ``queues``  p2p channel queue depths, the consensus input queues,
              the ApplyExecutor backlog, mempool depth, sig-gate
              backlog, vote-batcher counters — what is backed up

Every section is best-effort: a subsystem mid-teardown (or a bare mock
context without a node) yields a partial payload, never a 500 — this
surface exists precisely for nodes in a bad state.
"""

from __future__ import annotations

import sys
import threading
import traceback


def debug_payload(what: str, node) -> dict:
    if what == "flight":
        return _flight(node)
    if what == "stacks":
        return _stacks()
    if what == "queues":
        return _queues(node)
    raise KeyError(what)


def _flight(node) -> dict:
    rec = getattr(node, "flightrec", None)
    if rec is None:
        return {"enabled": False, "events": [],
                "note": "no flight recorder in RPC context"}
    return {
        "enabled": rec.enabled,
        "recorded_total": rec.recorded,
        "ring_size": rec._ring.maxlen,
        "dumps": rec.dumps,
        "dump_dir": rec.dump_dir,
        "events": rec.events(),
    }


def _stacks() -> dict:
    """All-thread stack dump. Names come from threading.enumerate();
    frames from sys._current_frames() — a thread racing its own exit
    may appear in one and not the other, which is fine for triage."""
    names = {t.ident: t for t in threading.enumerate()}
    threads = []
    for ident, frame in sorted(sys._current_frames().items()):
        t = names.get(ident)
        threads.append({
            "ident": ident,
            "name": t.name if t is not None else "?",
            "daemon": bool(t.daemon) if t is not None else None,
            "stack": [
                f"{fs.filename}:{fs.lineno} {fs.name}: {fs.line or ''}"
                for fs in traceback.extract_stack(frame)
            ],
        })
    return {"count": len(threads), "threads": threads}


def _queues(node) -> dict:
    out: dict = {}
    if node is None:
        return {"note": "no node in RPC context"}

    def section(name, fn):
        try:
            out[name] = fn()
        except Exception as exc:  # noqa: BLE001 — partial > broken
            out[name] = {"error": f"{type(exc).__name__}: {exc}"}

    cs = getattr(node, "consensus_state", None)
    if cs is not None:
        section("consensus", lambda: {
            "inputs": cs._inputs.qsize(),
            "peer_msgs": cs.peer_msg_queue.qsize(),
            "internal_msgs": cs.internal_msg_queue.qsize(),
            "peer_msg_drops": cs.peer_msg_drops,
            "height": cs.rs.height,
            "round": cs.rs.round_,
            "step": int(cs.rs.step),
        })
        section("pipeline", lambda: {
            "executor_backlog": (
                len(cs._apply_executor._queue)
                if cs._apply_executor is not None else 0
            ),
            "pending_apply_height": (
                cs._pending_apply.height
                if cs._pending_apply is not None else None
            ),
            "poisoned": cs.pipeline_poisoned(),
        })
        section("vote_batcher", lambda: {
            "batches": cs.vote_batcher.batches,
            "batched_sigs": cs.vote_batcher.batched_sigs,
            "singletons": cs.vote_batcher.singletons,
            "duplicates": cs.vote_duplicates,
        })
    mp = getattr(node, "mempool", None)
    if mp is not None:
        def mempool_section():
            row = {"size": mp.size()}
            batcher = mp.sig_batcher
            if batcher is not None:
                with batcher._cv:
                    row["sig_gate_backlog"] = len(batcher._buf)
                row["sig_gate_dropped"] = batcher.dropped
            return row

        section("mempool", mempool_section)
    sw = getattr(node, "sw", None)
    if sw is not None:
        def p2p_section():
            peers = {}
            for peer in sw.peers.list():
                try:
                    peers[peer.id()] = {
                        ch_label: depth
                        for ch_label, depth in
                        peer.mconn.status()["channels"].items()
                    }
                except Exception:  # noqa: BLE001 — peer mid-teardown
                    peers[peer.id()] = {"error": "unavailable"}
            return {"peers": peers, "count": sw.peers.size()}

        section("p2p", p2p_section)
    return out
