"""Route table (reference: rpc/core/routes.go:8-46)."""

from __future__ import annotations

from tendermint_tpu.rpc.core.handlers import ROUTES_TABLE, UNSAFE_ROUTES_TABLE


def build_routes(unsafe: bool = False) -> dict:
    """method name -> (handler(ctx, **params), [param names])."""
    routes = dict(ROUTES_TABLE)
    if unsafe:
        routes.update(UNSAFE_ROUTES_TABLE)
    return routes
