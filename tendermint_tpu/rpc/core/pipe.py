"""Dependency container for RPC handlers (reference: rpc/core/pipe.go).

The reference injects node internals into package globals
(pipe.go:36-116); here they travel in one explicit context object.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass
class RPCContext:
    event_switch: Any = None
    block_store: Any = None
    consensus_state: Any = None
    mempool: Any = None
    switch: Any = None
    proxy_app_query: Any = None
    genesis_doc: Any = None
    priv_validator: Any = None
    tx_indexer: Any = None
    state: Any = None  # for historical validator-set lookups
    node: Any = None
