"""RPC method implementations (reference: rpc/core/*.go).

Every handler takes (ctx: RPCContext, **params) and returns a JSON-ready
dict. Byte params arrive hex-encoded; byte results leave hex-encoded
(uppercase, matching the codebase's canonical JSON style).
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.mempool.mempool import (
    MempoolFullError,
    MempoolSourceLimitError,
    TxInCacheError,
)
from tendermint_tpu.rpc import admission as _admission
from tendermint_tpu.types import events as tev
from tendermint_tpu.types.tx import tx_hash


class RPCError(Exception):
    pass


def _mempool_check_tx(ctx, tx, cb=None) -> None:
    """check_tx with typed shed mapping (round 23): mempool intake
    refusals become RPCError with STABLE reason strings (tx_in_cache /
    mempool_full / mempool_source_limit), not generic 500s. The request's
    client IP (rpc/admission thread-local) keys per-source accounting."""
    try:
        ctx.mempool.check_tx(tx, cb, source_id=_admission.request_source())
    except TxInCacheError as exc:
        raise RPCError(f"tx_in_cache: {exc}") from exc
    except (MempoolFullError, MempoolSourceLimitError) as exc:
        # str(exc) already leads with the stable reason string
        raise RPCError(str(exc)) from exc


def _deadline_wait(default_wait: float) -> float:
    """Bound a handler wait by the request's admission deadline budget."""
    left = _admission.deadline_remaining()
    if left is None:
        return default_wait
    return min(default_wait, max(0.0, left))


def _raise_deadline(ctx, what: str) -> None:
    admission_ctl = getattr(getattr(ctx, "node", None), "rpc_admission", None)
    if admission_ctl is not None:
        admission_ctl.shed(_admission.SHED_DEADLINE)
    raise RPCError(f"deadline_exceeded: {what}")


def _wait_or_deadline(ctx, event: threading.Event, default_wait: float,
                      what: str) -> None:
    """Wait bounded by min(handler default, deadline budget); expiry of
    the DEADLINE is a typed deadline_exceeded, of the handler's own
    timeout the pre-existing timed-out error."""
    wait = _deadline_wait(default_wait)
    if not event.wait(wait):
        if wait < default_wait:
            _raise_deadline(ctx, what)
        raise RPCError(f"timed out waiting for {what}")


def _hex(b: bytes) -> str:
    return b.hex().upper()


def _unhex(s) -> bytes:
    if isinstance(s, bytes):
        return s
    return bytes.fromhex(s)


# -- status / net_info (rpc/core/status.go, net_info.go) ----------------------


def status(ctx) -> dict:
    latest_height = ctx.block_store.height()
    latest_meta = ctx.block_store.load_block_meta(latest_height)
    latest_hash, latest_app_hash, latest_time = b"", b"", 0
    if latest_meta is not None:
        latest_hash = latest_meta.block_id.hash
        latest_app_hash = latest_meta.header.app_hash
        latest_time = latest_meta.header.time_ns
    info = ctx.switch.node_info if ctx.switch else None
    return {
        "node_info": info.to_json() if info else None,
        "pub_key": ctx.priv_validator.get_pub_key().to_json()
        if ctx.priv_validator
        else None,
        "latest_block_hash": _hex(latest_hash),
        "latest_app_hash": _hex(latest_app_hash),
        "latest_block_height": latest_height,
        # round 19: the store base — a client planning historical reads
        # learns the retained range without probing for errors
        "earliest_block_height": ctx.block_store.base(),
        "latest_block_time": latest_time,
    }


def net_info(ctx) -> dict:
    peers = []
    for peer in ctx.switch.peers.list():
        peers.append(
            {
                "node_info": peer.node_info.to_json() if peer.node_info else None,
                "is_outbound": peer.outbound,
                "connection_status": peer.status(),
            }
        )
    return {
        "listening": bool(ctx.switch.listeners),
        "listeners": [str(l.internal_address()) for l in ctx.switch.listeners],
        "peers": peers,
    }


def genesis(ctx) -> dict:
    return {"genesis": ctx.genesis_doc.to_json()}


# -- blockchain (rpc/core/blocks.go) ------------------------------------------


def blockchain_info(ctx, min_height: int = 0, max_height: int = 0) -> dict:
    """Block metas for [min_height, max_height], newest first. On a
    pruned/restored node the range CLAMPS to the store base (round 19):
    a request reaching below base returns the retained tail (possibly
    empty) plus the `base` so the client sees exactly what was clamped —
    it never errors mid-range for asking about history that was
    legitimately dropped. min > max in the CALLER's own numbers is still
    an error."""
    store_height = ctx.block_store.height()
    base = ctx.block_store.base()
    floor = max(1, base)
    if min_height and max_height and min_height > max_height:
        raise RPCError(f"min height {min_height} > max height {max_height}")
    max_height = min(store_height, max_height) if max_height else store_height
    min_height = max(floor, min_height) if min_height else max(floor, max_height - 20 + 1)
    metas = []
    for h in range(max_height, min_height - 1, -1):
        meta = ctx.block_store.load_block_meta(h)
        if meta is not None:
            metas.append(meta.to_json())
    return {"last_height": store_height, "base": base, "block_metas": metas}


def _check_pruned(ctx, height: int) -> None:
    """A store restored from a snapshot (or pruned) legitimately starts
    above height 1: queries below its base get a CLEAR error, never a
    None-decoding surprise (round-10 satellite)."""
    base = ctx.block_store.base()
    if height < base:
        raise RPCError(
            f"height {height} is below the store's base {base} "
            "(pruned or restored from a snapshot)"
        )


def block(ctx, height: int) -> dict:
    height = int(height)
    if height <= 0:
        raise RPCError("height must be greater than 0")
    if height > ctx.block_store.height():
        raise RPCError("height must be less than or equal to the head")
    _check_pruned(ctx, height)
    meta = ctx.block_store.load_block_meta(height)
    blk = ctx.block_store.load_block(height)
    return {
        "block_meta": meta.to_json() if meta else None,
        "block": blk.to_json() if blk else None,
    }


def commit(ctx, height: int) -> dict:
    height = int(height)
    store_height = ctx.block_store.height()
    if height <= 0:
        raise RPCError("height must be greater than 0")
    if height > store_height:
        raise RPCError("height must be less than or equal to the head")
    _check_pruned(ctx, height)
    meta = ctx.block_store.load_block_meta(height)
    if meta is None:  # pruned or mid-write height inside the valid range
        raise RPCError(f"no block meta for height {height}")
    header = meta.header
    if height == store_height:
        cmt = ctx.block_store.load_seen_commit(height)
        canonical = False
    else:
        cmt = ctx.block_store.load_block_commit(height)
        canonical = True
    return {
        "header": header.to_json(),
        "commit": cmt.to_json() if cmt else None,
        "canonical_commit": canonical,
    }


def validators(ctx, height: int = 0) -> dict:
    """Current validator set, or — with `height` — the historical set
    that signed at that height (per-height history via the state's
    last-changed pointers; state/state.go:162-194). The historical form
    is what a light client pairs with /commit to verify old headers
    (docs/specification/light-client-protocol.md)."""
    height = int(height)
    if height > 0:
        if ctx.state is None:
            raise RPCError("historical validator sets unavailable")
        try:
            vs = ctx.state.load_validators(height)
        except Exception as exc:
            raise RPCError(f"no validator set for height {height}: {exc}")
        return {"block_height": height, "validators": vs.to_json()}
    rs = ctx.consensus_state.get_round_state()
    return {
        "block_height": rs.height - 1,
        "validators": rs.validators.to_json() if rs.validators else None,
    }


def dump_consensus_state(ctx) -> dict:
    rs = ctx.consensus_state.get_round_state()
    peer_states = {}
    for peer in ctx.switch.peers.list():
        ps = peer.get("ConsensusReactor.peerState")
        if ps is not None:
            prs = ps.get_round_state()
            peer_states[peer.id()] = {
                "height": prs.height,
                "round": prs.round_,
                "step": prs.step,
                "proposal": prs.proposal,
            }
    return {"round_state": rs.to_json(), "peer_round_states": peer_states}


# -- mempool (rpc/core/mempool.go) --------------------------------------------


def broadcast_tx_async(ctx, tx) -> dict:
    tx = _unhex(tx)
    _mempool_check_tx(ctx, tx)
    return {"hash": _hex(tx_hash(tx)), "code": 0, "data": "", "log": ""}


def broadcast_tx_sync(ctx, tx) -> dict:
    """Waits for the CheckTx response (rpc/core/mempool.go:47-77)."""
    tx = _unhex(tx)
    done = threading.Event()
    box = {}

    def cb(res):
        box["res"] = res
        done.set()

    _mempool_check_tx(ctx, tx, cb)
    _wait_or_deadline(ctx, done, 10.0, "CheckTx")
    res = box["res"]
    return {
        "code": res.code,
        "data": _hex(res.data or b""),
        "log": res.log,
        "hash": _hex(tx_hash(tx)),
    }


def broadcast_tx_commit(ctx, tx, timeout: float = 60.0) -> dict:
    """CheckTx, then wait for the tx to be committed in a block
    (rpc/core/mempool.go:149-230; 60s cap)."""
    tx = _unhex(tx)
    committed = threading.Event()
    box = {}

    listener_id = f"rpc-tx-{_hex(tx_hash(tx))[:16]}-{time.monotonic_ns()}"
    event = tev.event_string_tx(tx_hash(tx))

    def on_tx(data):
        box["deliver"] = data
        committed.set()

    ctx.event_switch.add_listener_for_event(listener_id, event, on_tx)
    try:
        check_done = threading.Event()

        def cb(res):
            box["check"] = res
            check_done.set()

        _mempool_check_tx(ctx, tx, cb)
        _wait_or_deadline(ctx, check_done, 10.0, "CheckTx")
        check = box["check"]
        check_json = {
            "code": check.code,
            "data": _hex(check.data or b""),
            "log": check.log,
        }
        if check.code != 0:
            return {
                "check_tx": check_json,
                "deliver_tx": None,
                "hash": _hex(tx_hash(tx)),
                "height": 0,
            }
        _wait_or_deadline(ctx, committed, float(timeout),
                          "tx to be committed")
        d = box["deliver"]
        return {
            "check_tx": check_json,
            "deliver_tx": {"code": d.code, "data": _hex(d.data or b""), "log": d.log},
            "hash": _hex(tx_hash(tx)),
            "height": d.height,
        }
    finally:
        ctx.event_switch.remove_listener(listener_id)


def unconfirmed_txs(ctx) -> dict:
    txs = ctx.mempool.reap(-1) if hasattr(ctx.mempool, "reap") else []
    return {"n_txs": len(txs), "txs": [_hex(t) for t in txs]}


def num_unconfirmed_txs(ctx) -> dict:
    return {"n_txs": ctx.mempool.size(), "txs": None}


# -- tx lookup with proof (rpc/core/tx.go) ------------------------------------


def tx(ctx, hash, prove: bool = False) -> dict:
    h = _unhex(hash)
    res = ctx.tx_indexer.get(h)
    if res is None:
        raise RPCError(f"tx ({_hex(h)}) not found")
    out = {
        "height": res.height,
        "index": res.index,
        "tx_result": {
            "code": res.result.code,
            "data": _hex(res.result.data or b""),
            "log": res.result.log,
        },
        "tx": _hex(bytes(res.tx)),
    }
    if prove:
        from tendermint_tpu.types.tx import txs_proof

        # the proof needs the block itself; on a pruned store the index
        # may outlive the block (round 19) — clear error, not a crash
        _check_pruned(ctx, res.height)
        blk = ctx.block_store.load_block(res.height)
        if blk is None:
            raise RPCError(f"no block at height {res.height} for tx proof")
        proof = txs_proof(blk.data.txs, res.index)
        out["proof"] = proof.to_json()
    return out


# -- abci passthrough (rpc/core/abci.go) --------------------------------------


def abci_query(ctx, data=b"", path: str = "", height: int = 0, prove: bool = False) -> dict:
    res = ctx.proxy_app_query.query_sync(
        data=_unhex(data) if data else b"", path=path, height=int(height),
        prove=bool(prove),
    )
    return {
        "response": {
            "code": res.code,
            "index": getattr(res, "index", 0),
            "key": _hex(getattr(res, "key", b"") or b""),
            "value": _hex(res.value or b""),
            # round 13: the app's state-tree proof (hex of the JSON
            # TreeProof — merkle/statetree_proof.py) and the height it
            # proves at; rpc/light.verified_query checks it against the
            # light-verified header (height+1)'s app_hash
            "proof": _hex(getattr(res, "proof", b"") or b""),
            "log": res.log,
            "height": getattr(res, "height", 0),
        }
    }


def abci_info(ctx) -> dict:
    res = ctx.proxy_app_query.info_sync()
    return {
        "response": {
            "data": res.data,
            "version": getattr(res, "version", ""),
            "last_block_height": res.last_block_height,
            "last_block_app_hash": _hex(res.last_block_app_hash or b""),
        }
    }


# -- unsafe (rpc/core/net.go, dev.go, mempool.go) -----------------------------


def snapshots(ctx) -> dict:
    """State-sync discovery over RPC (round 10): the node's locally held
    snapshots in manifest-lite form, newest first — what an operator (or
    an out-of-band bootstrapper) reads before pointing a fresh node's
    statesync at this one. docs/state-sync.md."""
    node = ctx.node
    store = getattr(node, "snapshot_store", None)
    if store is None:
        return {"snapshots": []}
    out = []
    for h in reversed(store.heights()):
        m = store.load_manifest(h)
        if m is not None:
            out.append(m.lite())
    return {"snapshots": out}


def unsafe_dial_seeds(ctx, seeds) -> dict:
    if isinstance(seeds, str):
        seeds = [s for s in seeds.split(",") if s]
    if not seeds:
        raise RPCError("no seeds provided")
    ctx.switch.dial_seeds(list(seeds))
    return {"log": "dialing seeds in rounds"}


def metrics(ctx) -> dict:
    """Flat numeric snapshot of node health — consensus position, mempool
    depth, peer counts, fast-sync progress, and the TPU gateway counters
    (tpu_sigs moving is how an operator confirms the device path is live).
    Beyond-reference observability: the reference declares a go-metrics
    dep it never wires (SURVEY.md §5); here the node exports one.

    Round 11: the dict is rendered FROM the node's telemetry registry
    (node/telemetry.py holds the canonical <plane>_<name> wiring; the
    same registry serves Prometheus text on GET /metrics). Byte-
    compatible with the pre-registry handler: same flat key set, same
    values. The wiring is DIRECT — a renamed attribute fails loudly here
    instead of silently dropping a gauge (PR-4 convention; the old
    handler's getattr(..., 0.0) defaults and setdefault collision
    handling are gone)."""
    return ctx.node.telemetry.flatten()


def consensus_trace(ctx, last: int = 10) -> dict:
    """The last `last` committed heights' wall-time traces, newest
    first: step-partitioned segments (propose -> prevote-wait ->
    precommit-wait -> commit -> apply -> snapshot-hook), overlapping
    aux attributions (part hashing), and the height's device-vs-CPU
    verify/hash split with breaker state (consensus/trace.py). Operator
    CLI: python -m tendermint_tpu.ops.trace."""
    rec = ctx.consensus_state.trace
    return {"traces": [t.to_json() for t in rec.last(int(last))]}


def tx_trace(ctx, hash="", last: int = 20) -> dict:
    """Sampled tx-lifecycle traces (round 17, libs/txtrace.py): the
    completed ring (newest first) PLUS the in-flight actives — a
    partition-parked tx is visible mid-flight with its stages frozen at
    wherever it stalled. `hash` filters both lists to one tx (the
    cross-node causal id ops/txtrace joins on)."""
    node = getattr(ctx, "node", None)
    rec = getattr(node, "txtrace", None)
    if rec is None:
        return {"traces": [], "active": []}
    traces = rec.last(int(last))
    active = rec.active()
    if hash:
        want = str(hash).upper()
        traces = [t for t in traces if t["hash"] == want]
        active = [t for t in active if t["hash"] == want]
    return {"traces": traces, "active": active}


def unsafe_flush_mempool(ctx) -> dict:
    ctx.mempool.flush()
    return {}


# -- profiler API (rpc/core/routes.go:42-45): the pprof equivalents are
# cProfile for CPU and tracemalloc for heap ----------------------------------

_profiler_state: dict = {"profiler": None}


def unsafe_start_cpu_profiler(ctx, filename) -> dict:
    import cProfile

    if _profiler_state["profiler"] is not None:
        raise RPCError("cpu profiler already running")
    prof = cProfile.Profile()
    prof.enable()
    _profiler_state["profiler"] = (prof, str(filename))
    return {}


def unsafe_stop_cpu_profiler(ctx) -> dict:
    entry = _profiler_state["profiler"]
    if entry is None:
        raise RPCError("cpu profiler not running")
    prof, filename = entry
    prof.disable()
    prof.dump_stats(filename)
    _profiler_state["profiler"] = None
    return {"log": f"profile written to {filename}"}


def unsafe_write_heap_profile(ctx, filename) -> dict:
    import tracemalloc

    started_here = not tracemalloc.is_tracing()
    if started_here:
        # no baseline was running: a point-in-time snapshot still captures
        # allocations made from here on; start tracing for next time
        tracemalloc.start()
    snap = tracemalloc.take_snapshot()
    with open(str(filename), "w") as f:
        for stat in snap.statistics("lineno")[:200]:
            f.write(f"{stat}\n")
    return {"log": f"heap profile written to {filename}"}


def evidence(ctx) -> dict:
    """Recorded duplicate-vote evidence (beyond reference: v0.11 detects
    conflicts and punts, consensus/state.go:1438-1447 — this surfaces
    what the node's pool has validated; types/evidence.py)."""
    pool = getattr(ctx.consensus_state, "evidence_pool", None)
    evs = pool.list() if pool is not None else []
    return {"count": len(evs), "evidence": [e.to_json() for e in evs]}


ROUTES_TABLE = {
    # info API
    "status": (status, []),
    "net_info": (net_info, []),
    "genesis": (genesis, []),
    "blockchain": (blockchain_info, ["min_height", "max_height"]),
    "block": (block, ["height"]),
    "commit": (commit, ["height"]),
    "validators": (validators, ["height"]),
    "dump_consensus_state": (dump_consensus_state, []),
    "evidence": (evidence, []),
    "snapshots": (snapshots, []),
    "metrics": (metrics, []),
    "consensus_trace": (consensus_trace, ["last"]),
    "tx_trace": (tx_trace, ["hash", "last"]),
    "tx": (tx, ["hash", "prove"]),
    "unconfirmed_txs": (unconfirmed_txs, []),
    "num_unconfirmed_txs": (num_unconfirmed_txs, []),
    # tx broadcast
    "broadcast_tx_async": (broadcast_tx_async, ["tx"]),
    "broadcast_tx_sync": (broadcast_tx_sync, ["tx"]),
    "broadcast_tx_commit": (broadcast_tx_commit, ["tx"]),
    # abci
    "abci_query": (abci_query, ["data", "path", "height", "prove"]),
    "abci_info": (abci_info, []),
}

UNSAFE_ROUTES_TABLE = {
    "unsafe_dial_seeds": (unsafe_dial_seeds, ["seeds"]),
    "unsafe_flush_mempool": (unsafe_flush_mempool, []),
    # profiler API (rpc/core/routes.go:42-45)
    "unsafe_start_cpu_profiler": (unsafe_start_cpu_profiler, ["filename"]),
    "unsafe_stop_cpu_profiler": (unsafe_stop_cpu_profiler, []),
    "unsafe_write_heap_profile": (unsafe_write_heap_profile, ["filename"]),
}
