"""RPC clients (reference: rpc/client/httpclient.go, localclient.go,
rpc/lib/client/ws_client.go).

HTTPClient speaks JSON-RPC over HTTP; WSClient adds event subscriptions;
LocalClient calls handlers in-process against an RPCContext (no sockets),
which is what tests and in-node tooling use.
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import queue
import socket
import struct
import threading
from urllib.parse import urlsplit

from tendermint_tpu.rpc.core.routes import build_routes


class RPCClientError(Exception):
    pass


class _UnixHTTPConnection(http.client.HTTPConnection):
    """http.client connection whose transport is an AF_UNIX socket —
    the client half of the reference's unix-socket RPC transport
    (rpc/lib/rpc_test.go:40-75 exercises both)."""

    def __init__(self, path: str, timeout: float):
        super().__init__("unix", timeout=timeout)
        self._path = path

    def connect(self):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._path)
        self.sock = s


class HTTPClient:
    """JSON-RPC over HTTP with per-thread persistent connections (round
    24): a replica's upstream fetch path issues thousands of small POSTs
    and a fresh TCP handshake per request was the dominant cost. Each
    calling thread keeps ONE keep-alive connection (the server side is
    HTTP/1.1 with Content-Length). A connection that turns out dead on
    reuse — server restart, idle EOF — is rebuilt and the request
    retried once; a FRESH connection's failure still raises (the server
    is genuinely down), and a timeout never retries (the request may be
    executing server-side, and a broadcast_tx must not double-submit)."""

    def __init__(self, addr: str, timeout: float = 30.0):
        # addr: "host:port", "http://host:port", or "unix:///path.sock"
        self.timeout = timeout
        self._id = 0
        self._mtx = threading.Lock()
        self._local = threading.local()
        # reused-connection rebuilds that transparently re-sent a request
        self.reconnects = 0
        if addr.startswith("unix://"):
            self.unix_path: str | None = addr[len("unix://"):]
            self.addr = addr
            return
        self.unix_path = None
        if not addr.startswith("http"):
            addr = "http://" + addr
        self.addr = addr.rstrip("/")
        u = urlsplit(self.addr)
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port or 80

    def _connect(self):
        if self.unix_path:
            return _UnixHTTPConnection(self.unix_path, self.timeout)
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self.timeout
        )

    def _drop(self, conn) -> None:
        self._local.conn = None
        try:
            conn.close()
        except OSError:
            pass

    @staticmethod
    def _roundtrip(conn, data: bytes) -> tuple[int, bytes, bool]:
        conn.request(
            "POST", "/", body=data,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, raw, not resp.will_close

    def _post(self, data: bytes) -> tuple[int, bytes]:
        conn = getattr(self._local, "conn", None)
        reused = conn is not None
        if conn is None:
            conn = self._connect()
        try:
            status, raw, keep = self._roundtrip(conn, data)
        except TimeoutError:
            self._drop(conn)
            raise
        except (http.client.HTTPException, ConnectionError, OSError):
            self._drop(conn)
            if not reused:
                raise
            with self._mtx:
                self.reconnects += 1
            conn = self._connect()
            try:
                status, raw, keep = self._roundtrip(conn, data)
            except (http.client.HTTPException, ConnectionError, OSError):
                self._drop(conn)
                raise
        if keep:
            self._local.conn = conn
        else:
            self._drop(conn)
        return status, raw

    def call(self, method: str, **params):
        with self._mtx:
            self._id += 1
            id_ = self._id
        req = {
            "jsonrpc": "2.0",
            "id": id_,
            "method": method,
            "params": params,
        }
        status, raw = self._post(json.dumps(req).encode())
        # JSON-RPC errors ride non-200 statuses with a JSON body
        try:
            body = json.loads(raw.decode())
        except ValueError as exc:
            raise RPCClientError(f"HTTP {status}") from exc
        if body.get("error"):
            raise RPCClientError(body["error"])
        return body["result"]

    def close(self) -> None:
        """Close THIS thread's persistent connection (each thread owns
        its own; idle ones die with their thread or at GC)."""
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._drop(conn)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)


class LocalClient:
    """In-process client: handler table against a live RPCContext
    (reference rpc/client/localclient.go)."""

    def __init__(self, ctx, unsafe: bool = False):
        self.ctx = ctx
        self.routes = build_routes(unsafe)

    def call(self, method: str, **params):
        route = self.routes.get(method)
        if route is None:
            raise RPCClientError(f"unknown method {method!r}")
        fn, _known = route
        return fn(self.ctx, **params)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)


class WSClient:
    """Minimal RFC6455 client for the /websocket endpoint: JSON-RPC calls
    and an event queue for subscriptions."""

    def __init__(self, addr: str, timeout: float = 30.0):
        if addr.startswith("unix://"):
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self.sock.settimeout(timeout)
            self.sock.connect(addr[len("unix://"):])
            host_hdr = "unix"
        else:
            host, _, port = (
                addr.replace("http://", "").replace("ws://", "").rpartition(":")
            )
            self.sock = socket.create_connection((host, int(port)), timeout=timeout)
            host_hdr = f"{host}:{port}"
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            (
                f"GET /websocket HTTP/1.1\r\nHost: {host_hdr}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        # consume the 101 response headers
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise RPCClientError("ws handshake failed")
            buf += chunk
        if b"101" not in buf.split(b"\r\n", 1)[0]:
            raise RPCClientError(f"ws handshake rejected: {buf[:200]!r}")
        self.events: queue.Queue = queue.Queue()
        self.responses: queue.Queue = queue.Queue()
        self._id = 0
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="wsclient.recv"
        )
        self._recv_thread.start()

    # -- frames ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return bytes(buf)

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        mask = os.urandom(4)
        head = bytearray([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head.append(0x80 | n)
        elif n < 1 << 16:
            head.append(0x80 | 126)
            head += struct.pack(">H", n)
        else:
            head.append(0x80 | 127)
            head += struct.pack(">Q", n)
        head += mask
        masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
        self.sock.sendall(bytes(head) + masked)

    def _recv_loop(self) -> None:
        try:
            while True:
                b1, b2 = self._read_exact(2)
                opcode = b1 & 0x0F
                length = b2 & 0x7F
                if length == 126:
                    (length,) = struct.unpack(">H", self._read_exact(2))
                elif length == 127:
                    (length,) = struct.unpack(">Q", self._read_exact(8))
                payload = self._read_exact(length)
                if opcode == 0x9:
                    self._send_frame(0xA, payload)
                    continue
                if opcode == 0x8:
                    return
                if opcode not in (0x1, 0x2):
                    continue
                msg = json.loads(payload.decode())
                result = msg.get("result") or {}
                if isinstance(result, dict) and "event" in result:
                    self.events.put(result)
                else:
                    self.responses.put(msg)
        except (ConnectionError, OSError):
            pass

    # -- API ---------------------------------------------------------------

    def call(self, method: str, timeout: float = 10.0, **params):
        self._id += 1
        self._send_frame(
            0x1,
            json.dumps(
                {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
            ).encode(),
        )
        msg = self.responses.get(timeout=timeout)
        if msg.get("error"):
            raise RPCClientError(msg["error"])
        return msg["result"]

    def subscribe(self, event: str) -> None:
        self.call("subscribe", event=event)

    def unsubscribe(self, event: str) -> None:
        self.call("unsubscribe", event=event)

    def next_event(self, timeout: float = 10.0) -> dict:
        return self.events.get(timeout=timeout)

    def close(self) -> None:
        try:
            self._send_frame(0x8, b"")
            self.sock.close()
        except OSError:
            pass
