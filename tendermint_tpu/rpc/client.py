"""RPC clients (reference: rpc/client/httpclient.go, localclient.go,
rpc/lib/client/ws_client.go).

HTTPClient speaks JSON-RPC over HTTP; WSClient adds event subscriptions;
LocalClient calls handlers in-process against an RPCContext (no sockets),
which is what tests and in-node tooling use.
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import queue
import socket
import struct
import threading
import urllib.request

from tendermint_tpu.rpc.core.routes import build_routes


class RPCClientError(Exception):
    pass


class _UnixHTTPConnection(http.client.HTTPConnection):
    """http.client connection whose transport is an AF_UNIX socket —
    the client half of the reference's unix-socket RPC transport
    (rpc/lib/rpc_test.go:40-75 exercises both)."""

    def __init__(self, path: str, timeout: float):
        super().__init__("unix", timeout=timeout)
        self._path = path

    def connect(self):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self._path)
        self.sock = s


class HTTPClient:
    def __init__(self, addr: str, timeout: float = 30.0):
        # addr: "host:port", "http://host:port", or "unix:///path.sock"
        self.timeout = timeout
        self._id = 0
        if addr.startswith("unix://"):
            self.unix_path: str | None = addr[len("unix://"):]
            self.addr = addr
            return
        self.unix_path = None
        if not addr.startswith("http"):
            addr = "http://" + addr
        self.addr = addr.rstrip("/")

    def _call_unix(self, data: bytes) -> dict:
        conn = _UnixHTTPConnection(self.unix_path, self.timeout)
        try:
            conn.request(
                "POST", "/", body=data,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
        finally:
            conn.close()
        try:
            return json.loads(raw.decode())
        except ValueError as exc:
            raise RPCClientError(f"HTTP {resp.status}") from exc

    def call(self, method: str, **params):
        self._id += 1
        req = {
            "jsonrpc": "2.0",
            "id": self._id,
            "method": method,
            "params": params,
        }
        data = json.dumps(req).encode()
        if self.unix_path:
            body = self._call_unix(data)
            if body.get("error"):
                raise RPCClientError(body["error"])
            return body["result"]
        r = urllib.request.Request(
            self.addr + "/",
            data=data,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(r, timeout=self.timeout) as resp:
                body = json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            # JSON-RPC errors ride non-200 statuses with a JSON body
            try:
                body = json.loads(exc.read().decode())
            except ValueError:
                raise RPCClientError(f"HTTP {exc.code}") from exc
        if body.get("error"):
            raise RPCClientError(body["error"])
        return body["result"]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)


class LocalClient:
    """In-process client: handler table against a live RPCContext
    (reference rpc/client/localclient.go)."""

    def __init__(self, ctx, unsafe: bool = False):
        self.ctx = ctx
        self.routes = build_routes(unsafe)

    def call(self, method: str, **params):
        route = self.routes.get(method)
        if route is None:
            raise RPCClientError(f"unknown method {method!r}")
        fn, _known = route
        return fn(self.ctx, **params)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)


class WSClient:
    """Minimal RFC6455 client for the /websocket endpoint: JSON-RPC calls
    and an event queue for subscriptions."""

    def __init__(self, addr: str, timeout: float = 30.0):
        if addr.startswith("unix://"):
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self.sock.settimeout(timeout)
            self.sock.connect(addr[len("unix://"):])
            host_hdr = "unix"
        else:
            host, _, port = (
                addr.replace("http://", "").replace("ws://", "").rpartition(":")
            )
            self.sock = socket.create_connection((host, int(port)), timeout=timeout)
            host_hdr = f"{host}:{port}"
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            (
                f"GET /websocket HTTP/1.1\r\nHost: {host_hdr}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        # consume the 101 response headers
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise RPCClientError("ws handshake failed")
            buf += chunk
        if b"101" not in buf.split(b"\r\n", 1)[0]:
            raise RPCClientError(f"ws handshake rejected: {buf[:200]!r}")
        self.events: queue.Queue = queue.Queue()
        self.responses: queue.Queue = queue.Queue()
        self._id = 0
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="wsclient.recv"
        )
        self._recv_thread.start()

    # -- frames ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf += chunk
        return bytes(buf)

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        mask = os.urandom(4)
        head = bytearray([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head.append(0x80 | n)
        elif n < 1 << 16:
            head.append(0x80 | 126)
            head += struct.pack(">H", n)
        else:
            head.append(0x80 | 127)
            head += struct.pack(">Q", n)
        head += mask
        masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
        self.sock.sendall(bytes(head) + masked)

    def _recv_loop(self) -> None:
        try:
            while True:
                b1, b2 = self._read_exact(2)
                opcode = b1 & 0x0F
                length = b2 & 0x7F
                if length == 126:
                    (length,) = struct.unpack(">H", self._read_exact(2))
                elif length == 127:
                    (length,) = struct.unpack(">Q", self._read_exact(8))
                payload = self._read_exact(length)
                if opcode == 0x9:
                    self._send_frame(0xA, payload)
                    continue
                if opcode == 0x8:
                    return
                if opcode not in (0x1, 0x2):
                    continue
                msg = json.loads(payload.decode())
                result = msg.get("result") or {}
                if isinstance(result, dict) and "event" in result:
                    self.events.put(result)
                else:
                    self.responses.put(msg)
        except (ConnectionError, OSError):
            pass

    # -- API ---------------------------------------------------------------

    def call(self, method: str, timeout: float = 10.0, **params):
        self._id += 1
        self._send_frame(
            0x1,
            json.dumps(
                {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
            ).encode(),
        )
        msg = self.responses.get(timeout=timeout)
        if msg.get("error"):
            raise RPCClientError(msg["error"])
        return msg["result"]

    def subscribe(self, event: str) -> None:
        self.call("subscribe", event=event)

    def unsubscribe(self, event: str) -> None:
        self.call("unsubscribe", event=event)

    def next_event(self, timeout: float = 10.0) -> dict:
        return self.events.get(timeout=timeout)

    def close(self) -> None:
        try:
            self._send_frame(0x8, b"")
            self.sock.close()
        except OSError:
            pass
