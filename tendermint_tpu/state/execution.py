"""Block execution pipeline (reference: state/execution.go):
validate -> BeginBlock -> DeliverTx (async) -> EndBlock ->
save ABCIResponses -> update validators -> Commit (mempool locked) ->
update mempool -> save state. Fail points at the same crash-critical
boundaries as the reference (state/execution.go:224,232,243).

The LastCommit verification here (validate_block -> verify_commit,
reference state/execution.go:198) is the primary consumer of the TPU batch
verifier: a whole commit's signatures flush to the kernel in one batch.
"""

from __future__ import annotations

import logging
import os

from tendermint_tpu.crypto.keys import PubKeyEd25519, pub_key_from_json
from tendermint_tpu.state.fail import fail_point
from tendermint_tpu.state.state import ABCIResponses, State
from tendermint_tpu.types import Validator, ValidatorSet
from tendermint_tpu.types.events import EventDataTx, fire_event_tx
from tendermint_tpu.types.tx import TxResult

logger = logging.getLogger("state.execution")


class InvalidBlockError(Exception):
    pass


class ProxyAppConnError(Exception):
    pass


def update_validators(validators: ValidatorSet, diffs) -> None:
    """Apply EndBlock diffs: power 0 removes, new address adds, else update
    (state/execution.go:120-159)."""
    for d in diffs:
        pub_key = pub_key_from_json(d.pub_key_json)
        address = pub_key.address()
        power = d.power
        if power < 0:
            raise ValueError(f"negative power {power}")
        _, val = validators.get_by_address(address)
        if val is None:
            if not validators.add(Validator.new(pub_key, power)):
                raise ValueError(f"failed to add validator {address.hex()}")
        elif power == 0:
            _, removed = validators.remove(address)
            if not removed:
                raise ValueError(f"failed to remove validator {address.hex()}")
        else:
            val.voting_power = power
            if not validators.update(val):
                raise ValueError(f"failed to update validator {address.hex()}")


def validate_block(state: State, block, batch_verifier=None) -> None:
    """state/execution.go:180-206. Raises InvalidBlockError."""
    err = block.validate_basic(
        state.chain_id, state.last_block_height, state.last_block_id, state.app_hash,
        commit_format=state.genesis_doc.commit_format_at(block.header.height),
    )
    if err:
        raise InvalidBlockError(err)

    if block.header.height == 1:
        if block.last_commit.is_commit():
            raise InvalidBlockError("first block should have no LastCommit precommits")
    else:
        if block.last_commit.size() != state.last_validators.size():
            raise InvalidBlockError(
                f"invalid commit size: expected {state.last_validators.size()}, "
                f"got {block.last_commit.size()}"
            )
        from tendermint_tpu.types.validator_set import CommitError

        try:
            state.last_validators.verify_commit(
                state.chain_id,
                state.last_block_id,
                block.header.height - 1,
                block.last_commit,
                batch_verifier=batch_verifier,
            )
        except CommitError as e:
            raise InvalidBlockError(str(e)) from e

    # the evidence section is PROPOSER-CONTROLLED input: every piece must
    # be a provable prior-height double-sign by a validator of this chain
    # before any honest node prevotes the block (types/evidence.py);
    # round 16 routes every piece's signatures through the same batch
    # verifier the commit above rode — one gateway call, per-lane
    # attribution
    from tendermint_tpu.types.evidence import EvidenceError

    try:
        block.evidence.validate(
            state.chain_id, block.header.height, state.validators,
            batch_verifier=batch_verifier,
        )
    except EvidenceError as e:
        raise InvalidBlockError(f"invalid evidence: {e}") from e


def exec_block_on_proxy_app(event_cache, proxy_app_conn, block) -> ABCIResponses:
    """BeginBlock -> streamed DeliverTx -> EndBlock
    (state/execution.go:43-118)."""
    from tendermint_tpu.abci.types import Header as ABCIHeader

    responses = ABCIResponses.for_block(block)
    valid_txs = invalid_txs = 0

    proxy_app_conn.begin_block_sync(
        block.hash(),
        ABCIHeader(
            chain_id=block.header.chain_id,
            height=block.header.height,
            time_ns=block.header.time_ns,
            num_txs=block.header.num_txs,
            app_hash=block.header.app_hash,
        ),
    )
    if proxy_app_conn.error():
        raise ProxyAppConnError(str(proxy_app_conn.error()))

    # stream txs asynchronously; responses arrive in order. Round 14:
    # the whole block dispatches in ONE grouped call when the connection
    # offers it — a batch-capable app (kvstore sharded apply) sees the
    # txs together, a local client pays one lock round trip, and the
    # socket client's default keeps the per-tx pipelining.
    # TENDERMINT_DELIVER_BATCH=0 restores the per-tx dispatch (the
    # pre-round-14 execution plane; benches/bench_pipeline.py's serial
    # baseline)
    deliver_many = getattr(proxy_app_conn, "deliver_txs_async", None)
    if os.environ.get("TENDERMINT_DELIVER_BATCH", "") == "0":
        deliver_many = None
    if deliver_many is not None and len(block.data.txs) > 1:
        reqres = deliver_many(list(block.data.txs))
        if proxy_app_conn.error():
            raise ProxyAppConnError(str(proxy_app_conn.error()))
    else:
        reqres = []
        for tx in block.data.txs:
            reqres.append(proxy_app_conn.deliver_tx_async(tx))
            if proxy_app_conn.error():
                raise ProxyAppConnError(str(proxy_app_conn.error()))

    for i, rr in enumerate(reqres):
        res = rr.wait(timeout=60)
        if res is None:
            raise ProxyAppConnError("deliver_tx timed out")
        responses.deliver_tx[i] = res
        if res.is_ok:
            valid_txs += 1
        else:
            invalid_txs += 1
        if event_cache is not None:
            fire_event_tx(
                event_cache,
                EventDataTx(
                    height=block.header.height,
                    tx=block.data.txs[i],
                    data=res.data,
                    log=res.log,
                    code=res.code,
                    error="" if res.is_ok else str(res.code),
                ),
            )

    responses.end_block = proxy_app_conn.end_block_sync(block.header.height)
    logger.info(
        "executed block h=%d valid=%d invalid=%d",
        block.header.height, valid_txs, invalid_txs,
    )
    return responses


def val_exec_block(state: State, event_cache, proxy_app_conn, block, batch_verifier=None) -> ABCIResponses:
    validate_block(state, block, batch_verifier=batch_verifier)
    return exec_block_on_proxy_app(event_cache, proxy_app_conn, block)


def apply_block(
    state: State,
    event_cache,
    proxy_app_conn,
    block,
    parts_header,
    mempool,
    batch_verifier=None,
) -> None:
    """The one entry point that processes and commits an entire block
    (state/execution.go:216-249)."""
    responses = val_exec_block(state, event_cache, proxy_app_conn, block, batch_verifier)

    fail_point()

    index_txs(state, responses)
    state.save_abci_responses(responses)

    fail_point()

    state.set_block_and_validators(block.header, parts_header, responses)

    commit_state_update_mempool(state, proxy_app_conn, block, mempool)

    fail_point()

    state.save()


def commit_state_update_mempool(state: State, proxy_app_conn, block, mempool) -> None:
    """Mempool locked across app-Commit and mempool.Update so no CheckTx
    runs against stale app state (state/execution.go:254-277)."""
    mempool.lock()
    try:
        res = proxy_app_conn.commit_sync()
        if not res.is_ok:
            raise ProxyAppConnError(f"commit failed: {res.log}")
        state.app_hash = res.data
        mempool.update(block.header.height, block.data.txs)
    finally:
        mempool.unlock()


def index_txs(state: State, responses: ABCIResponses) -> None:
    from tendermint_tpu.state.txindex import Batch

    batch = Batch()
    for i, d in enumerate(responses.deliver_tx):
        batch.add(
            TxResult(height=responses.height, index=i, tx=responses.txs[i], result=d)
        )
    state.tx_indexer.add_batch(batch)


def exec_commit_block(proxy_app_conn, block) -> bytes:
    """Execute and commit a block without touching State — used by
    handshake replay (state/execution.go:297-314)."""
    exec_block_on_proxy_app(None, proxy_app_conn, block)
    res = proxy_app_conn.commit_sync()
    if not res.is_ok:
        raise ProxyAppConnError(f"commit failed: {res.log}")
    return res.data
