"""Crash-injection points (reference dep: ebuchman/fail-test; call sites at
state/execution.go:224-243 and consensus/state.go:1284-1345, driven by
FAIL_TEST_INDEX in test/persist/test_failure_indices.sh).

Two families of injection, both armed purely by environment so a node
subprocess under test crashes exactly where the harness asked and a
production process pays one env lookup:

- FAIL_TEST_INDEX=i — the i-th `fail_point()` hit in this process aborts
  hard (os._exit), simulating a power failure at that logical boundary
  (the original crash tier, tests/test_persist.py).

- FAIL_TEST_MODE — the round-9 filesystem tier (the WAL torture harness,
  tests/test_wal_torture.py + docs/crash-recovery.md):
    * torn_write + FAIL_TEST_WAL_BYTES=B: the WAL write that crosses
      cumulative byte offset B is cut at exactly B — the written prefix
      is fsynced so the tear is what a power failure would have left on
      disk — and the process dies.  Sweeping B over every byte offset of
      a record is the ALICE-style "any prefix of the append stream"
      crash model.
    * rotate_crash + FAIL_TEST_ROTATE_INDEX=k + FAIL_TEST_ROTATE_PHASE=
      pre|post: die immediately before / after the k-th chunk rotation's
      os.replace, covering the half-flushed rotation boundary.
    * pipeline + FAIL_TEST_PIPELINE_POINT=name [+ FAIL_TEST_PIPELINE_HITS=k]:
      the round-14 execution-pipeline tier (docs/execution-pipeline.md) —
      die at the k-th (default first) hit of the NAMED stage boundary:
        pre_apply           on the apply-executor thread, after the block
                            save + WAL #ENDHEIGHT landed but before the
                            deferred apply touched the app — the "marker
                            precedes a crashed apply" image;
        mid_parallel_apply  inside the kvstore sharded deliver_tx, after
                            the shard workers folded their ops but before
                            the deterministic merge mutates the app;
        post_apply          after sm.apply_block completed (state saved at
                            H) but before the snapshot hook/events fired.

FAIL_TEST_INDEX keeps its original SERIAL crash model: when it is armed,
consensus runs finalize_commit serially (ConsensusState._pipeline_enabled)
so the i-th fail_point() hit stays a deterministic, single-thread count —
the pipeline's cross-thread boundaries are covered by the named
pipeline_point() tier above instead.

All counters (fail-point index, WAL byte position, rotation count,
per-name pipeline hits) are guarded by one lock; `reset()` clears every
counter under that same lock so it can never race a concurrent
`fail_point()`/`wal_write()` caller.
"""

from __future__ import annotations

import os
import threading

_counter = 0
_wal_bytes = 0
_rotations = 0
_pipeline_hits: dict = {}
_mtx = threading.Lock()

EXIT_CODE = 99  # what the harnesses assert on: "died at the fail point"


def fail_point() -> None:
    target = os.environ.get("FAIL_TEST_INDEX")
    if target is None:
        return
    global _counter
    with _mtx:
        idx = _counter
        _counter += 1
    if idx == int(target):
        os._exit(EXIT_CODE)


def wal_write(f, data: bytes) -> None:
    """Perform a WAL write on behalf of autofile.Group, torn if armed.

    Only consulted when FAIL_TEST_MODE is set (the Group checks the env
    before importing this module, so the hot path never pays the call).
    The byte position advances for every hooked write — headers and
    rotation-surviving bytes included — so a swept offset B lands at one
    deterministic point of the append stream.
    """
    if os.environ.get("FAIL_TEST_MODE") != "torn_write":
        f.write(data)
        return
    target = int(os.environ.get("FAIL_TEST_WAL_BYTES", "-1"))
    global _wal_bytes
    with _mtx:
        start = _wal_bytes
        _wal_bytes += len(data)
    if target < 0 or not (start <= target < start + len(data)):
        f.write(data)
        return
    f.write(data[: target - start])
    # make the torn prefix durable: the crash image must be exactly
    # "every byte before B reached disk, nothing after" — without the
    # fsync the tear would depend on page-cache timing
    f.flush()
    os.fsync(f.fileno())
    os._exit(EXIT_CODE)


def rotate_point(phase: str) -> None:
    """Chunk-rotation crash boundary (phase: 'pre' = before the
    os.replace publishing the chunk, 'post' = after, before the new head
    exists). Armed by FAIL_TEST_MODE=rotate_crash."""
    if os.environ.get("FAIL_TEST_MODE") != "rotate_crash":
        return
    if phase != os.environ.get("FAIL_TEST_ROTATE_PHASE", "post"):
        return
    target = int(os.environ.get("FAIL_TEST_ROTATE_INDEX", "0"))
    global _rotations
    with _mtx:
        idx = _rotations
        _rotations += 1
    if idx == target:
        os._exit(EXIT_CODE)


def pipeline_point(name: str) -> None:
    """Execution-pipeline stage boundary (round 14). Armed by
    FAIL_TEST_MODE=pipeline + FAIL_TEST_PIPELINE_POINT=<name>; the
    optional FAIL_TEST_PIPELINE_HITS=k dies at the k-th hit (0-based,
    default 0) so a mid-chain boundary can be targeted too. Unlike
    fail_point(), hits count PER NAME — the boundaries live on different
    threads and a shared index would be racy by construction."""
    if os.environ.get("FAIL_TEST_MODE") != "pipeline":
        return
    if name != os.environ.get("FAIL_TEST_PIPELINE_POINT"):
        return
    target = int(os.environ.get("FAIL_TEST_PIPELINE_HITS", "0"))
    with _mtx:
        idx = _pipeline_hits.get(name, 0)
        _pipeline_hits[name] = idx + 1
    if idx == target:
        os._exit(EXIT_CODE)


def reset() -> None:
    global _counter, _wal_bytes, _rotations
    with _mtx:
        _counter = 0
        _wal_bytes = 0
        _rotations = 0
        _pipeline_hits.clear()
