"""Crash-injection points (reference dep: ebuchman/fail-test; call sites at
state/execution.go:224-243 and consensus/state.go:1284-1345, driven by
FAIL_TEST_INDEX in test/persist/test_failure_indices.sh).

When FAIL_TEST_INDEX=i is set, the i-th fail point hit in this process
aborts hard (os._exit) — simulating a power failure at exactly that
point for the crash-recovery test tier."""

from __future__ import annotations

import os
import threading

_counter = 0
_mtx = threading.Lock()


def fail_point() -> None:
    target = os.environ.get("FAIL_TEST_INDEX")
    if target is None:
        return
    global _counter
    with _mtx:
        idx = _counter
        _counter += 1
    if idx == int(target):
        os._exit(99)


def reset() -> None:
    global _counter
    with _mtx:
        _counter = 0
