"""State: the last-committed chain state (reference: state/state.go).

Persisted per height with a validator-set history: when the set changes at
height H (via EndBlock diffs) the full set is stored under H, otherwise
only a pointer to the last-changed height (saveValidatorsInfo,
state/state.go:196-210). ABCIResponses are saved BEFORE app Commit so a
crash between app-Commit and state-Save is recoverable by replaying them
(the reference's handshake case at consensus/replay.go:280-295).
"""

from __future__ import annotations

import json
import threading

from tendermint_tpu.libs.db import DB
from tendermint_tpu.types import (
    BlockID,
    GenesisDoc,
    Validator,
    ValidatorSet,
)
from tendermint_tpu.types.block_id import PartSetHeader

_STATE_KEY = b"stateKey"
_ABCI_RESPONSES_KEY = b"abciResponsesKey"


def _validators_key(height: int) -> bytes:
    return b"validatorsKey:%d" % height


class NoValSetForHeightError(Exception):
    pass


class ABCIResponses:
    """Responses of the ABCI calls during block processing
    (state/state.go:215-239)."""

    def __init__(self, height: int, deliver_tx: list, end_block, txs: list[bytes]):
        self.height = height
        self.deliver_tx = deliver_tx
        self.end_block = end_block
        self.txs = txs

    @classmethod
    def for_block(cls, block) -> "ABCIResponses":
        return cls(block.header.height, [None] * len(block.data.txs), None, block.data.txs)

    def to_json(self):
        from tendermint_tpu.abci.types import ResponseEndBlock

        return {
            "height": self.height,
            "deliver_tx": [d.to_json() if d else None for d in self.deliver_tx],
            "end_block": (self.end_block or ResponseEndBlock()).to_json(),
        }

    @classmethod
    def from_json(cls, obj) -> "ABCIResponses":
        from tendermint_tpu.abci.types import ResponseDeliverTx, ResponseEndBlock

        return cls(
            obj["height"],
            [ResponseDeliverTx.from_json(d) if d else None for d in obj["deliver_tx"]],
            ResponseEndBlock.from_json(obj["end_block"]),
            [],
        )

    def bytes_(self) -> bytes:
        return json.dumps(self.to_json(), sort_keys=True).encode()


class State:
    def __init__(self, db: DB, genesis_doc: GenesisDoc, tx_indexer=None):
        from tendermint_tpu.state.txindex import NullTxIndexer

        self.db = db
        self.genesis_doc = genesis_doc
        self.chain_id = genesis_doc.chain_id
        self.last_block_height = 0
        self.last_block_id = BlockID()
        self.last_block_time_ns = genesis_doc.genesis_time_ns
        self.validators: ValidatorSet = ValidatorSet([])
        self.last_validators: ValidatorSet = ValidatorSet([])
        self.app_hash = b""
        self.last_height_validators_changed = 1
        self.tx_indexer = tx_indexer or NullTxIndexer()
        self._mtx = threading.Lock()

    # -- constructors ------------------------------------------------------

    @classmethod
    def make_genesis_state(cls, db: DB, genesis_doc: GenesisDoc) -> "State":
        genesis_doc.validate_and_complete()
        s = cls(db, genesis_doc)
        s.validators = ValidatorSet(
            [Validator.new(v.pub_key, v.power) for v in genesis_doc.validators]
        )
        s.last_validators = ValidatorSet([])
        s.app_hash = genesis_doc.app_hash
        return s

    @classmethod
    def from_json_obj(cls, db: DB, genesis_doc: GenesisDoc, obj: dict) -> "State":
        """Rehydrate a State from its to_json() form — the load_state
        body, also used by the statesync restore path on a snapshot's
        embedded state object."""
        s = cls(db, genesis_doc)
        s.last_block_height = obj["last_block_height"]
        s.last_block_id = BlockID.from_json(obj["last_block_id"])
        s.last_block_time_ns = obj["last_block_time"]
        s.validators = ValidatorSet.from_json(obj["validators"])
        s.last_validators = ValidatorSet.from_json(obj["last_validators"])
        s.app_hash = bytes.fromhex(obj["app_hash"])
        s.last_height_validators_changed = obj["last_height_validators_changed"]
        return s

    @classmethod
    def load_state(cls, db: DB, genesis_doc: GenesisDoc) -> "State | None":
        buf = db.get(_STATE_KEY)
        if not buf:
            return None
        return cls.from_json_obj(db, genesis_doc, json.loads(buf))

    @classmethod
    def get_state(cls, db: DB, genesis_doc: GenesisDoc) -> "State":
        """LoadState-or-genesis (state/state.go:71-84)."""
        s = cls.load_state(db, genesis_doc)
        if s is None:
            s = cls.make_genesis_state(db, genesis_doc)
            s.save()
        return s

    def copy(self) -> "State":
        s = State(self.db, self.genesis_doc, self.tx_indexer)
        s.last_block_height = self.last_block_height
        s.last_block_id = self.last_block_id
        s.last_block_time_ns = self.last_block_time_ns
        s.validators = self.validators.copy()
        s.last_validators = self.last_validators.copy()
        s.app_hash = self.app_hash
        s.last_height_validators_changed = self.last_height_validators_changed
        return s

    # -- persistence -------------------------------------------------------

    def to_json(self):
        return {
            "chain_id": self.chain_id,
            "last_block_height": self.last_block_height,
            "last_block_id": self.last_block_id.to_json(),
            "last_block_time": self.last_block_time_ns,
            "validators": self.validators.to_json(),
            "last_validators": self.last_validators.to_json(),
            "app_hash": self.app_hash.hex().upper(),
            "last_height_validators_changed": self.last_height_validators_changed,
        }

    def bytes_(self) -> bytes:
        return json.dumps(self.to_json(), sort_keys=True).encode()

    def save(self) -> None:
        with self._mtx:
            self._save_validators_info()
            self.db.set_sync(_STATE_KEY, self.bytes_())

    def _save_validators_info(self) -> None:
        """Full set if it changed at next height, else pointer only
        (state/state.go:196-210)."""
        next_height = self.last_block_height + 1
        info = {"last_height_changed": self.last_height_validators_changed}
        if self.last_height_validators_changed == next_height:
            info["validator_set"] = self.validators.to_json()
        self.db.set_sync(_validators_key(next_height), json.dumps(info, sort_keys=True).encode())

    def load_validators(self, height: int) -> ValidatorSet:
        """Validator set that signed at `height`, following last-changed
        pointers (state/state.go:162-194)."""
        info = self._load_validators_info(height)
        if info is None:
            raise NoValSetForHeightError(str(height))
        if "validator_set" not in info:
            info = self._load_validators_info(info["last_height_changed"])
            if info is None or "validator_set" not in info:
                raise NoValSetForHeightError(str(height))
        return ValidatorSet.from_json(info["validator_set"])

    def _load_validators_info(self, height: int):
        buf = self.db.get(_validators_key(height))
        if not buf:
            return None
        return json.loads(buf)

    def save_abci_responses(self, responses: ABCIResponses) -> None:
        self.db.set_sync(_ABCI_RESPONSES_KEY, responses.bytes_())

    def load_abci_responses(self) -> ABCIResponses | None:
        buf = self.db.get(_ABCI_RESPONSES_KEY)
        if not buf:
            return None
        return ABCIResponses.from_json(json.loads(buf))

    # -- updates -----------------------------------------------------------

    def set_block_and_validators(self, header, block_parts_header: PartSetHeader, abci_responses: ABCIResponses) -> None:
        """Apply EndBlock valset diffs, rotate proposer, advance last-block
        pointers (state/state.go:223-260)."""
        from tendermint_tpu.state.execution import update_validators

        prev_val_set = self.validators.copy()
        next_val_set = prev_val_set.copy()

        diffs = abci_responses.end_block.diffs if abci_responses.end_block else []
        if diffs:
            update_validators(next_val_set, diffs)
            self.last_height_validators_changed = header.height + 1

        next_val_set.increment_accum(1)

        self.last_block_height = header.height
        self.last_block_id = BlockID(header.hash(), block_parts_header)
        self.last_block_time_ns = header.time_ns
        self.validators = next_val_set
        self.last_validators = prev_val_set

    def params(self):
        return self.genesis_doc.consensus_params

    def seed_restored(self, validators_info: dict) -> None:
        """Statesync restore: persist this (light-verified) state as THE
        state, plus the validator-history records load_validators needs
        for heights at/after the snapshot (statesync/producer.py
        validators_info_records). The caller verified every record's set
        against the header chain before handing it here."""
        with self._mtx:
            for h_str, info in validators_info.items():
                self.db.set_sync(
                    _validators_key(int(h_str)),
                    json.dumps(info, sort_keys=True).encode(),
                )
            self.db.set_sync(_STATE_KEY, self.bytes_())

    def equals(self, other: "State") -> bool:
        return self.bytes_() == other.bytes_()

    def __repr__(self):
        return (
            f"State{{h:{self.last_block_height} vals:{self.validators.size()} "
            f"app:{self.app_hash.hex()[:12]}}}"
        )
