from tendermint_tpu.state.state import ABCIResponses, State
from tendermint_tpu.state.execution import (
    apply_block,
    exec_commit_block,
    validate_block,
)

__all__ = [
    "State",
    "ABCIResponses",
    "apply_block",
    "exec_commit_block",
    "validate_block",
]
