"""Transaction indexing (reference: state/txindex/ — interface, KV impl
keyed by tx hash, and null impl)."""

from __future__ import annotations

import json

from tendermint_tpu.libs.db import DB
from tendermint_tpu.types.tx import TxResult, tx_hash


class Batch:
    def __init__(self):
        self.ops: list[TxResult] = []

    def add(self, result: TxResult) -> None:
        self.ops.append(result)


class TxIndexer:
    def add_batch(self, batch: Batch) -> None:
        raise NotImplementedError

    def get(self, h: bytes) -> TxResult | None:
        raise NotImplementedError


class NullTxIndexer(TxIndexer):
    """state/txindex/null: stores nothing."""

    def add_batch(self, batch: Batch) -> None:
        pass

    def get(self, h: bytes) -> TxResult | None:
        return None


class KVTxIndexer(TxIndexer):
    """state/txindex/kv: tx-hash -> TxResult in a KV store."""

    def __init__(self, db: DB):
        self.db = db

    def add_batch(self, batch: Batch) -> None:
        for result in batch.ops:
            self.db.set(tx_hash(result.tx), json.dumps(result.to_json()).encode())

    def get(self, h: bytes) -> TxResult | None:
        from tendermint_tpu.abci.types import ResponseDeliverTx

        buf = self.db.get(h)
        if buf is None:
            return None
        obj = json.loads(buf)
        return TxResult(
            height=obj["height"],
            index=obj["index"],
            tx=bytes.fromhex(obj["tx"]),
            result=ResponseDeliverTx.from_json(obj["result"]) if obj["result"] else None,
        )
