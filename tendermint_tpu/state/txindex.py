"""Transaction indexing (reference: state/txindex/ — interface, KV impl
keyed by tx hash, and null impl).

Round 20 adds bounded retention: the kv index was the last per-height
disk term a pruned node kept growing forever. `add_batch` now writes a
height-ordered secondary key per tx so `prune_to(height)` can drop
every indexed tx below the retention coordinator's safe height without
scanning the primary records (node/retention.py drives it on the same
pass that prunes the block store and WAL)."""

from __future__ import annotations

import json

from tendermint_tpu.libs.db import DB
from tendermint_tpu.types.tx import TxResult, tx_hash

# secondary key layout: b"h/" + zero-padded height + b"/" + tx hash.
# Zero-padding keeps lexicographic order == height order; primary tx
# records keep their raw-hash keys (no reindex on upgrade — txs indexed
# before round 20 simply have no height key and outlive pruning, which
# is the safe failure direction for an index).
_HEIGHT_PREFIX = b"h/"
_HEIGHT_DIGITS = 20


def _height_key(height: int, h: bytes) -> bytes:
    return _HEIGHT_PREFIX + b"%0*d/" % (_HEIGHT_DIGITS, height) + h


class Batch:
    def __init__(self):
        self.ops: list[TxResult] = []

    def add(self, result: TxResult) -> None:
        self.ops.append(result)


class TxIndexer:
    def add_batch(self, batch: Batch) -> None:
        raise NotImplementedError

    def get(self, h: bytes) -> TxResult | None:
        raise NotImplementedError

    def prune_to(self, height: int) -> int:
        """Drop indexed txs BELOW `height`. Returns txs removed."""
        return 0


class NullTxIndexer(TxIndexer):
    """state/txindex/null: stores nothing."""

    def add_batch(self, batch: Batch) -> None:
        pass

    def get(self, h: bytes) -> TxResult | None:
        return None


class KVTxIndexer(TxIndexer):
    """state/txindex/kv: tx-hash -> TxResult in a KV store, plus the
    round-20 per-height secondary index that makes pruning O(pruned)."""

    def __init__(self, db: DB):
        self.db = db
        self.pruned_txs = 0

    def add_batch(self, batch: Batch) -> None:
        for result in batch.ops:
            h = tx_hash(result.tx)
            self.db.set(h, json.dumps(result.to_json()).encode())
            self.db.set(_height_key(result.height, h), b"")

    def get(self, h: bytes) -> TxResult | None:
        from tendermint_tpu.abci.types import ResponseDeliverTx

        buf = self.db.get(h)
        if buf is None:
            return None
        obj = json.loads(buf)
        return TxResult(
            height=obj["height"],
            index=obj["index"],
            tx=bytes.fromhex(obj["tx"]),
            result=ResponseDeliverTx.from_json(obj["result"]) if obj["result"] else None,
        )

    def prune_to(self, height: int) -> int:
        """Remove every indexed tx whose height is below `height` (the
        retention coordinator's safe height — heights >= it survive).
        Crash-safe by construction: the primary record is deleted before
        its height key, so an interrupted pass leaves only height keys
        whose primaries are gone — re-deleting those is idempotent."""
        # materialize first: backends may not tolerate deletes under an
        # open prefix iteration (sqlite cursor semantics)
        doomed = []
        for key, _value in self.db.iterate_prefix(_HEIGHT_PREFIX):
            try:
                hgt = int(key[len(_HEIGHT_PREFIX):len(_HEIGHT_PREFIX) + _HEIGHT_DIGITS])
            except ValueError:
                continue  # foreign key shape — never delete what we can't parse
            if hgt < height:
                doomed.append(key)
        pruned = 0
        for key in doomed:
            h = key[len(_HEIGHT_PREFIX) + _HEIGHT_DIGITS + 1:]
            if self.db.get(h) is not None:
                self.db.delete(h)
                pruned += 1
            self.db.delete(key)
        self.pruned_txs += pruned
        return pruned
