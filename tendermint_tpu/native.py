"""ctypes bridge to the C++ host data-plane library (native/).

The native library provides the CPU hot paths the reference implements in
compiled Go (SURVEY.md §2.2: go-crypto verify loops, tmlibs/merkle): batch
Ed25519 verification, batch SHA-256/RIPEMD-160, merkle leaf/tree hashing,
and the TPU-kernel input marshal. Loading is lazy; if the shared library
is missing it is built with `make -C native` (g++ is a baked-in tool);
on any failure callers fall back to the pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("native")

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtendermint_native.so")

_lib = None
_lib_mtx = threading.Lock()
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=300,
        )
        return True
    except Exception as exc:  # noqa: BLE001
        logger.warning("native build failed: %s", exc)
        return False


def _sources_newer_than_lib() -> bool:
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
    except OSError:
        return True
    src_dir = os.path.join(_NATIVE_DIR, "src")
    for f in os.listdir(src_dir):
        if os.path.getmtime(os.path.join(src_dir, f)) > lib_mtime:
            return True
    return False


def get_lib():
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _load_failed
    with _lib_mtx:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if _sources_newer_than_lib() and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as exc:
            logger.warning("native load failed: %s", exc)
            _load_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i64 = ctypes.c_int64
        lib.tm_sha256_batch.argtypes = [u8p, u64p, i64, u8p]
        lib.tm_ripemd160_batch.argtypes = [u8p, u64p, i64, u8p]
        lib.tm_merkle_leaf_hashes.argtypes = [u8p, u64p, i64, u8p]
        lib.tm_merkle_root.argtypes = [u8p, i64, u8p]
        lib.tm_ed25519_verify_batch.argtypes = [u8p, u8p, u8p, u64p, i64, u8p]
        lib.tm_ed25519_verify_batch_rlc.argtypes = [u8p, u8p, u8p, u64p, i64]
        lib.tm_ed25519_verify_batch_rlc.restype = ctypes.c_int
        lib.tm_ed25519_hram_batch.argtypes = [u8p, u8p, u8p, u64p, i64, u8p]
        lib.tm_ed25519_decompress_batch.argtypes = [u8p, i64, u8p, u8p]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def ready() -> bool:
    """available() WITHOUT triggering a build: True only when the library
    is already loaded or the prebuilt .so is current. Hot paths (the
    gateway's CPU verify fallback) call this so the first wide batch can
    never block consensus behind a 300s compiler run; anything that wants
    the build to happen calls available() at startup instead."""
    # lock-free fast path: these reads are GIL-atomic, and a loaded
    # library must never be reported not-ready just because another
    # thread briefly holds the mutex
    if _lib is not None:
        return True
    if _load_failed:
        return False
    # non-blocking probe: the warm thread holds _lib_mtx for the whole
    # build (up to 300s) — while it does, the hot path must see
    # "not ready", never wait
    if not _lib_mtx.acquire(blocking=False):
        return False
    _lib_mtx.release()
    return os.path.exists(_LIB_PATH) and not _sources_newer_than_lib()


def _as_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _concat(msgs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    offsets = np.zeros(len(msgs) + 1, dtype=np.uint64)
    total = 0
    for i, m in enumerate(msgs):
        total += len(m)
        offsets[i + 1] = total
    data = np.frombuffer(b"".join(msgs), dtype=np.uint8) if total else np.zeros(1, np.uint8)
    return np.ascontiguousarray(data), offsets


def sha256_batch(msgs: list[bytes]) -> list[bytes]:
    lib = get_lib()
    data, offsets = _concat(msgs)
    out = np.zeros(len(msgs) * 32, dtype=np.uint8)
    lib.tm_sha256_batch(
        _as_u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(msgs), _as_u8p(out),
    )
    raw = out.tobytes()
    return [raw[32 * i : 32 * i + 32] for i in range(len(msgs))]


def ripemd160_batch(msgs: list[bytes]) -> list[bytes]:
    lib = get_lib()
    data, offsets = _concat(msgs)
    out = np.zeros(len(msgs) * 20, dtype=np.uint8)
    lib.tm_ripemd160_batch(
        _as_u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(msgs), _as_u8p(out),
    )
    raw = out.tobytes()
    return [raw[20 * i : 20 * i + 20] for i in range(len(msgs))]


def merkle_leaf_hashes(items: list[bytes]) -> list[bytes]:
    lib = get_lib()
    data, offsets = _concat(items)
    out = np.zeros(len(items) * 20, dtype=np.uint8)
    lib.tm_merkle_leaf_hashes(
        _as_u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(items), _as_u8p(out),
    )
    raw = out.tobytes()
    return [raw[20 * i : 20 * i + 20] for i in range(len(items))]


def merkle_root_from_leaf_digests(digests: list[bytes]) -> bytes:
    if not digests:
        return b""
    lib = get_lib()
    leaves = np.frombuffer(b"".join(digests), dtype=np.uint8)
    out = np.zeros(20, dtype=np.uint8)
    lib.tm_merkle_root(_as_u8p(np.ascontiguousarray(leaves)), len(digests), _as_u8p(out))
    return out.tobytes()


def merkle_root(items: list[bytes]) -> bytes:
    return merkle_root_from_leaf_digests(merkle_leaf_hashes(items))


RLC_MIN_BATCH = 32  # below this the MSM's fixed costs beat its savings


def ed25519_verify_batch(items: list[tuple[bytes, bytes, bytes]]) -> list[bool]:
    """(pubkey32, msg, sig64) triples -> per-item validity.

    Wide all-well-formed batches first try random-linear-combination
    batch verification (ONE Pippenger multi-scalar multiplication for
    the whole batch — tm_ed25519_verify_batch_rlc, ~4x the per-item
    loop): an accepting combined equation proves every lane valid up to
    the standard 2^-128 soundness bound. A rejection runs the exact
    per-item floor once — the 8-wide IFMA lock-step Straus ladder
    (native verify8_with_neg_a) where the hardware has AVX-512 IFMA,
    the scalar ladder elsewhere — bounding ANY failure density at one
    MSM plus one floor pass (see the in-body note for why this replaced
    bisection). Per-lane verdicts and adversarial-input semantics are
    byte-for-byte those of crypto/ed25519.verify — every accepted lane
    was covered by an accepting combined equation or checked
    individually, every rejected lane individually."""
    lib = get_lib()
    n = len(items)
    # one join + frombuffer, not n numpy slice-writes: the per-slice
    # path cost ~17ms per 4096-lane batch, a quarter of the whole verify
    pub_parts: list[bytes] = []
    sig_parts: list[bytes] = []
    msgs = []
    ok_shape = np.ones(n, dtype=bool)
    for i, (pub, msg, sig) in enumerate(items):
        if len(pub) != 32 or len(sig) != 64:
            ok_shape[i] = False
            pub_parts.append(b"\x00" * 32)
            sig_parts.append(b"\x00" * 64)
            msgs.append(b"")
            continue
        pub_parts.append(bytes(pub))
        sig_parts.append(bytes(sig))
        msgs.append(bytes(msg))
    pubs = np.frombuffer(b"".join(pub_parts), dtype=np.uint8)
    sigs = np.frombuffer(b"".join(sig_parts), dtype=np.uint8)
    data, offsets = _concat(msgs)
    data_p = _as_u8p(data)

    def off_p(i: int):
        # offsets values are absolute into `data`, so a sub-range just
        # passes the pointer at its own start
        return offsets[i:].ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))

    def per_item(i: int, j: int, out: np.ndarray) -> None:
        lib.tm_ed25519_verify_batch(
            _as_u8p(pubs[32 * i:]), _as_u8p(sigs[64 * i:]), data_p,
            off_p(i), j - i, _as_u8p(out[i:]),
        )

    def rlc_ok(i: int, j: int) -> bool:
        return bool(lib.tm_ed25519_verify_batch_rlc(
            _as_u8p(pubs[32 * i:]), _as_u8p(sigs[64 * i:]), data_p,
            off_p(i), j - i,
        ))

    out = np.zeros(n, dtype=np.uint8)
    if n >= RLC_MIN_BATCH and ok_shape.all():
        # Failure policy (round 5): one failed RLC goes STRAIGHT to the
        # exact per-item floor — no bisection. The floor is now the
        # 8-wide IFMA lock-step ladder (native verify8_with_neg_a, ~4x
        # the scalar ladder), which moves the adversarial bound: a
        # failing 4096-batch costs one MSM (~23 ms) + one floor pass
        # (~73 ms), within 1.3x of the floor alone, for EVERY failure
        # density. The earlier log-budget bisection only beat that for
        # exactly-one-bad-lane batches (~83 vs ~96 ms) while losing up
        # to 3x on scattered floods (each tree level re-pays a failing
        # MSM over nearly the whole batch) — and the flood is the case
        # an attacker controls, so the policy optimizes for it.
        if rlc_ok(0, n):
            out[:] = 1
        else:
            per_item(0, n, out)
        return [bool(o) for o in out]
    per_item(0, n, out)
    return [bool(o and s) for o, s in zip(out, ok_shape)]


def ed25519_hram_batch(
    sigs: np.ndarray, pubs: np.ndarray, msgs_data: np.ndarray,
    offsets: np.ndarray, n: int,
) -> np.ndarray:
    """h = SHA512(R || A || M) mod L per row -> (n, 32) uint8 LE.
    sigs: (n*64,) u8 contiguous; pubs: (n*32,) u8; msgs concatenated."""
    lib = get_lib()
    out = np.zeros(n * 32, dtype=np.uint8)
    lib.tm_ed25519_hram_batch(
        _as_u8p(sigs), _as_u8p(pubs), _as_u8p(msgs_data),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n, _as_u8p(out),
    )
    return out.reshape(n, 32)


def ed25519_decompress_batch(pubs: np.ndarray, n: int):
    """(n*32,) u8 compressed keys -> ((n, 64) u8 x||y LE, (n,) bool ok)."""
    lib = get_lib()
    xy = np.zeros(n * 64, dtype=np.uint8)
    ok = np.zeros(n, dtype=np.uint8)
    lib.tm_ed25519_decompress_batch(_as_u8p(pubs), n, _as_u8p(xy), _as_u8p(ok))
    return xy.reshape(n, 64), ok.astype(bool)


