"""Shared JAX persistent-compile-cache setup.

This jax build ignores the JAX_COMPILATION_CACHE_DIR env vars (verified:
env-var-only runs never write the cache; explicit config calls do), so
every entry point — bench.py, __graft_entry__.py, tests/conftest.py —
calls enable() instead. The ed25519 ladder takes ~45s to compile on the
CPU backend; caching it is the difference between a 10-minute and a
10-second test run.
"""

from __future__ import annotations

import os

# repo-root/.jax_cache (this file lives at repo-root/tendermint_tpu/)
_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)


def enable(cache_dir: str | None = None) -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir or _DEFAULT_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
