"""Shared JAX persistent-compile-cache setup.

This jax build ignores the JAX_COMPILATION_CACHE_DIR env vars (verified:
env-var-only runs never write the cache; explicit config calls do), so
every entry point — bench.py, __graft_entry__.py, tests/conftest.py —
calls enable() instead. The ed25519 ladder takes ~45s to compile on the
CPU backend; caching it is the difference between a 10-minute and a
10-second test run.
"""

from __future__ import annotations

import os

# repo-root/.jax_cache (this file lives at repo-root/tendermint_tpu/)
_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
)


def _machine_tag() -> str:
    """Stable per-machine cache key from the CPU feature flags. XLA:CPU
    AOT artifacts bake in the compile machine's features; loading them on
    a different host spews cpu_aot_loader feature-mismatch errors (and
    risks SIGILL) — seen as the stderr noise in MULTICHIP_r04.json when
    the driver machine reloaded this builder's cache. Scoping the cache
    dir by feature-set keeps every machine's artifacts separate."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return hashlib.sha1(line.encode()).hexdigest()[:12]
    except OSError:
        pass
    import platform

    return platform.machine() or "unknown"


def enable(cache_dir: str | None = None) -> None:
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(cache_dir or _DEFAULT_DIR, _machine_tag()),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def probe_device(timeout_s: float = 90.0) -> str | None:
    """Touch the accelerator with a bounded wait; the platform name, or
    None if the device never answered. jax.devices()/the first device op
    can block FOREVER on a wedged axon tunnel (observed after a process
    died mid-device-op), so the dial runs in a daemon thread. NOTE:
    probing initializes this process's jax backend — on exclusive-device
    platforms a parent that probes then holds the device; orchestrators
    spawning per-bench subprocesses must probe in a throwaway subprocess
    (benches/run_all.py does)."""
    import threading

    out: list = []

    def probe():
        try:
            import jax
            import jax.numpy as jnp

            d = jax.devices()[0]
            jnp.zeros((8, 128)).sum().block_until_ready()
            out.append(d.platform)
        except Exception:  # noqa: BLE001 — unreachable counts as absent
            pass

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return out[0] if out else None


def probe_rtt_ms(timeout_s: float = 60.0) -> float | None:
    """Measured device dispatch round trip: min of 3 tiny synchronous
    ops after one warm-up, or None if the device never answered within
    the bound. Same hang discipline as probe_device (daemon-thread
    dial): a wedged tunnel parks the probe thread and returns None
    instead of hanging the caller. Only call from a process that is (or
    may become) the device's owner — ops/gateway.device_rtt_ms guards
    this with the no-daemon-socket check."""
    import threading
    import time

    out: list = []

    def probe():
        try:
            import jax.numpy as jnp

            x = jnp.zeros((8, 128))
            x.sum().block_until_ready()  # compile outside the clock
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                x.sum().block_until_ready()
                dt = (time.perf_counter() - t0) * 1e3
                best = dt if best is None else min(best, dt)
            out.append(best)
        except Exception:  # noqa: BLE001 — unreachable counts as absent
            pass

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return out[0] if out else None


def platform_label() -> str:
    """Backend platform name for bench output, WITHOUT risking a hang or
    contending with a device daemon that holds the chip: an explicit
    TENDERMINT_TPU_DISABLE skips everything, a serving daemon answers
    from its ping, and otherwise the gateway's bounded resolution runs
    (one cached subprocess probe)."""
    if os.environ.get("TENDERMINT_TPU_DISABLE", "") == "1":
        return "cpu (TENDERMINT_TPU_DISABLE)"
    from tendermint_tpu import devd

    rep = devd.available()
    if rep is not None:
        return f"{rep.get('platform')} (via devd)"
    from tendermint_tpu.ops.gateway import resolve_platform

    return resolve_platform() or "unknown (device unreachable)"
