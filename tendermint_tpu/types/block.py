"""Block = Header + Data(txs) + LastCommit (reference: types/block.go).

Hashing layout kept from the reference:
- Header.Hash = Merkle-of-map over the header fields (types/block.go:173-188)
- Commit.Hash = Merkle root over encoded precommits (types/block.go:340-349)
- Data.Hash   = Merkle root of tx hashes (types/tx.go:33-46)
- Block.Hash  = Header.Hash after FillHeader

Binary encoding is this framework's deterministic codec; the block's wire
bytes feed PartSet.from_data for gossip (types/block.go:110-112).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field, replace

from tendermint_tpu.codec.binary import Decoder, Encoder
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.merkle.simple import leaf_hash, simple_hash_from_hashes, simple_hash_from_map
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.part_set import PartSet
from tendermint_tpu.types.tx import Tx, txs_hash
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote


@dataclass
class Header:
    chain_id: str = ""
    height: int = 0
    time_ns: int = 0
    num_txs: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    app_hash: bytes = b""
    evidence_hash: bytes = b""

    def hash(self) -> bytes:
        """Merkle-of-map; nil until validators_hash is set
        (types/block.go:173-188). The Evidence key joins the map only
        when the block actually carries evidence, so evidence-free
        headers hash EXACTLY as they did before the section existed —
        the scenario soaks' byte-identity assertions span the change."""
        if not self.validators_hash:
            return b""
        e = Encoder()
        self.last_block_id.encode(e)
        last_block_id_bytes = e.buf()
        fields = {
            "ChainID": self.chain_id.encode(),
            "Height": Encoder().write_varint(self.height).buf(),
            "Time": Encoder().write_time_ns(self.time_ns).buf(),
            "NumTxs": Encoder().write_varint(self.num_txs).buf(),
            "LastBlockID": last_block_id_bytes,
            "LastCommit": self.last_commit_hash,
            "Data": self.data_hash,
            "Validators": self.validators_hash,
            "App": self.app_hash,
        }
        if self.evidence_hash:
            fields["Evidence"] = self.evidence_hash
        return simple_hash_from_map(fields)

    def encode(self, e: Encoder) -> None:
        e.write_string(self.chain_id)
        e.write_varint(self.height)
        e.write_time_ns(self.time_ns)
        e.write_varint(self.num_txs)
        self.last_block_id.encode(e)
        e.write_bytes(self.last_commit_hash)
        e.write_bytes(self.data_hash)
        e.write_bytes(self.validators_hash)
        e.write_bytes(self.app_hash)
        e.write_bytes(self.evidence_hash)

    @classmethod
    def decode(cls, d: Decoder) -> "Header":
        return cls(
            chain_id=d.read_string(),
            height=d.read_varint(),
            time_ns=d.read_time_ns(),
            num_txs=d.read_varint(),
            last_block_id=BlockID.decode(d),
            last_commit_hash=d.read_bytes(),
            data_hash=d.read_bytes(),
            validators_hash=d.read_bytes(),
            app_hash=d.read_bytes(),
            evidence_hash=d.read_bytes(),
        )

    def to_json(self):
        return {
            "chain_id": self.chain_id,
            "height": self.height,
            "time": self.time_ns,
            "num_txs": self.num_txs,
            "last_block_id": self.last_block_id.to_json(),
            "last_commit_hash": self.last_commit_hash.hex().upper(),
            "data_hash": self.data_hash.hex().upper(),
            "validators_hash": self.validators_hash.hex().upper(),
            "app_hash": self.app_hash.hex().upper(),
            "evidence_hash": self.evidence_hash.hex().upper(),
        }

    @classmethod
    def from_json(cls, obj) -> "Header":
        from tendermint_tpu.codec import jsonval as jv

        obj = jv.require_dict(obj)
        return cls(
            chain_id=jv.str_field(obj, "chain_id"),
            height=jv.int_field(obj, "height", 0, jv.MAX_HEIGHT),
            time_ns=jv.int_field(obj, "time", 0, jv.MAX_TIME_NS),
            num_txs=jv.int_field(obj, "num_txs", 0, jv.MAX_INDEX),
            last_block_id=BlockID.from_json(jv.dict_field(obj, "last_block_id")),
            last_commit_hash=jv.hex_field(obj, "last_commit_hash"),
            data_hash=jv.hex_field(obj, "data_hash"),
            validators_hash=jv.hex_field(obj, "validators_hash"),
            app_hash=jv.hex_field(obj, "app_hash"),
            # defensive input handling for an absent field — NOT a
            # cross-version upgrade path (the binary codec is not
            # backward readable either; docs/specification/
            # block-structure.md round-12 format note)
            evidence_hash=jv.hex_field(obj, "evidence_hash")
            if "evidence_hash" in obj else b"",
        )


class Commit:
    """+2/3 precommits for the previous block, index-aligned with that
    height's validator set (types/block.go:222-349). Precommits may be None
    where a validator skipped."""

    def __init__(self, block_id: BlockID, precommits: list[Vote | None]):
        self.block_id = block_id
        self.precommits = precommits
        self._hash: bytes | None = None
        self._bit_array: BitArray | None = None
        self._first: Vote | None = None

    def first_precommit(self) -> Vote | None:
        if self._first is None:
            self._first = next((p for p in self.precommits if p is not None), None)
        return self._first

    def height(self) -> int:
        fp = self.first_precommit()
        return fp.height if fp else 0

    def round_(self) -> int:
        fp = self.first_precommit()
        return fp.round_ if fp else 0

    def type_(self) -> int:
        return VOTE_TYPE_PRECOMMIT

    def size(self) -> int:
        return len(self.precommits)

    def bit_array(self) -> BitArray:
        if self._bit_array is None:
            self._bit_array = BitArray.from_indices(
                len(self.precommits),
                [i for i, p in enumerate(self.precommits) if p is not None],
            )
        return self._bit_array.copy()

    def get_by_index(self, index: int) -> Vote | None:
        return self.precommits[index]

    def is_commit(self) -> bool:
        return len(self.precommits) != 0

    def validate_basic(self) -> str | None:
        """None if structurally valid; else an error string
        (types/block.go:305-338)."""
        if self.block_id.is_zero():
            return "commit cannot be for nil block"
        if not self.precommits:
            return "no precommits in commit"
        height, round_ = self.height(), self.round_()
        for p in self.precommits:
            if p is None:
                continue
            if p.type_ != VOTE_TYPE_PRECOMMIT:
                return f"invalid commit vote type {p.type_}"
            if p.height != height:
                return f"invalid commit precommit height {p.height} != {height}"
            if p.round_ != round_:
                return f"invalid commit precommit round {p.round_} != {round_}"
        return None

    def hash(self) -> bytes:
        """Merkle root over the encoded precommits; None entries hash as the
        empty encoding (types/block.go:340-349)."""
        if self._hash is None:
            leaves = [
                leaf_hash(p.to_bytes() if p is not None else b"")
                for p in self.precommits
            ]
            self._hash = simple_hash_from_hashes(leaves)
        return self._hash

    def encode(self, e: Encoder) -> None:
        self.block_id.encode(e)
        def write_precommit(enc: Encoder, p: Vote | None):
            if p is None:
                enc.write_u8(0)
            else:
                enc.write_u8(1)
                p.encode(enc)
        e.write_list(self.precommits, write_precommit)

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.buf()

    @classmethod
    def decode(cls, d: Decoder) -> "Commit":
        bid = BlockID.decode(d)
        def read_precommit(dec: Decoder) -> Vote | None:
            tag = dec.read_u8()
            if tag == 0:
                return None
            return Vote.decode(dec)
        return cls(bid, d.read_list(read_precommit))

    def to_json(self):
        return {
            "block_id": self.block_id.to_json(),
            "precommits": [p.to_json() if p else None for p in self.precommits],
        }

    @classmethod
    def from_json(cls, obj) -> "Commit":
        from tendermint_tpu.codec import jsonval as jv

        obj = jv.require_dict(obj)
        return cls(
            BlockID.from_json(jv.dict_field(obj, "block_id")),
            [
                # only JSON null means "validator skipped"; falsy garbage
                # (0, false, "", {}) must reject, not silently drop a vote
                Vote.from_json(p) if p is not None else None
                for p in jv.list_field(obj, "precommits", jv.MAX_INDEX)
            ],
        )

    def __repr__(self):
        n = sum(1 for p in self.precommits if p is not None)
        return f"Commit{{{n}/{len(self.precommits)} for {self.block_id!r}}}"


def empty_commit() -> Commit:
    """The height-1 LastCommit: empty but never nil (types/block.go:216)."""
    return Commit(BlockID(), [])


@dataclass
class Data:
    txs: list[Tx] = field(default_factory=list)
    _hash: bytes | None = None

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = txs_hash(self.txs)
        return self._hash

    def encode(self, e: Encoder) -> None:
        e.write_list(self.txs, lambda enc, tx: enc.write_bytes(tx))

    @classmethod
    def decode(cls, d: Decoder) -> "Data":
        return cls(d.read_list(lambda dec: dec.read_bytes()))

    def to_json(self):
        return {"txs": [tx.hex().upper() for tx in self.txs]}

    @classmethod
    def from_json(cls, obj) -> "Data":
        from tendermint_tpu.codec import jsonval as jv

        obj = jv.require_dict(obj)
        txs = jv.list_field(obj, "txs", jv.MAX_INDEX)
        out = []
        for t in txs:
            if not isinstance(t, str) or len(t) > 2 * jv.MAX_TX_BYTES:
                raise ValueError("bad tx in block data")
            try:
                out.append(bytes.fromhex(t))
            except ValueError as exc:
                raise ValueError("bad tx in block data: not hex") from exc
        return cls(out)


class Block:
    def __init__(self, header: Header, data: Data, last_commit: Commit,
                 evidence=None):
        from tendermint_tpu.types.evidence import EvidenceData

        self.header = header
        self.data = data
        self.last_commit = last_commit
        self.evidence = evidence if evidence is not None else EvidenceData()

    @classmethod
    def make_block(
        cls,
        height: int,
        chain_id: str,
        txs: list[Tx],
        commit: Commit,
        prev_block_id: BlockID,
        val_hash: bytes,
        app_hash: bytes,
        part_size: int,
        time_ns: int | None = None,
        part_hasher=None,
        part_tree_hasher=None,
        part_tree_submitter=None,
        evidence=None,
    ) -> tuple["Block", PartSet]:
        """MakeBlock equivalent (types/block.go:26-44): block + its part set.
        `evidence` is the proposer's drained pool (types/evidence.py
        EvidenceData or a plain list); omitted = an empty section whose
        header bytes hash identically to the pre-evidence format."""
        from tendermint_tpu.types.evidence import EvidenceData

        if evidence is None:
            evidence = EvidenceData()
        elif not isinstance(evidence, EvidenceData):
            evidence = EvidenceData(list(evidence))
        header = Header(
            chain_id=chain_id,
            height=height,
            time_ns=time_ns if time_ns is not None else _time.time_ns(),
            num_txs=len(txs),
            last_block_id=prev_block_id,
            validators_hash=val_hash,
            app_hash=app_hash,
        )
        block = cls(header, Data(txs=list(txs)), commit, evidence=evidence)
        block.fill_header()
        return block, block.make_part_set(
            part_size, hasher=part_hasher, tree_hasher=part_tree_hasher,
            tree_submitter=part_tree_submitter,
        )

    def fill_header(self) -> None:
        if not self.header.last_commit_hash:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.evidence_hash:
            self.header.evidence_hash = self.evidence.hash()

    def hash(self) -> bytes:
        if self.header is None or self.data is None or self.last_commit is None:
            return b""
        self.fill_header()
        return self.header.hash()

    def hashes_to(self, h: bytes) -> bool:
        return len(h) > 0 and self.hash() == h

    def make_part_set(self, part_size: int, hasher=None,
                      tree_hasher=None, tree_submitter=None) -> PartSet:
        return PartSet.from_data(
            self.to_bytes(), part_size, hasher=hasher,
            tree_hasher=tree_hasher, tree_submitter=tree_submitter,
        )

    def commit_format(self) -> str:
        """Wire format this block's last_commit actually carries."""
        from tendermint_tpu.types.agg_commit import AggregateCommit

        return "aggregate" if isinstance(self.last_commit, AggregateCommit) else "full"

    def validate_basic(
        self,
        chain_id: str,
        last_block_height: int,
        last_block_id: BlockID,
        app_hash: bytes,
        commit_format: str | None = None,
    ) -> str | None:
        """Stateless-ish validation (types/block.go:48-85); None when OK.
        `commit_format` (when given) is the format the chain's upgrade
        schedule requires at this height — a block carrying its
        last_commit in the wrong form is refused with a NAMED error, not
        a later hash mismatch (docs/upgrade.md boundary invariant)."""
        h = self.header
        if h.chain_id != chain_id:
            return f"wrong chain_id: {h.chain_id} != {chain_id}"
        if h.height != last_block_height + 1:
            return f"wrong height: {h.height} != {last_block_height + 1}"
        if h.num_txs != len(self.data.txs):
            return f"wrong num_txs: {h.num_txs} != {len(self.data.txs)}"
        if h.last_block_id != last_block_id:
            return f"wrong last_block_id: {h.last_block_id} != {last_block_id}"
        if commit_format is not None and h.height != 1:
            got = self.commit_format()
            if got != commit_format:
                return (
                    f"wrong last_commit format at height {h.height}: "
                    f"got {got}, schedule requires {commit_format}"
                )
        if h.last_commit_hash != self.last_commit.hash():
            return "wrong last_commit_hash"
        if h.height != 1:
            err = self.last_commit.validate_basic()
            if err:
                return err
        if h.data_hash != self.data.hash():
            return "wrong data_hash"
        if h.evidence_hash != self.evidence.hash():
            return "wrong evidence_hash"
        if h.app_hash != app_hash:
            return f"wrong app_hash: {h.app_hash.hex()} != {app_hash.hex()}"
        return None

    # -- binary ------------------------------------------------------------

    def encode(self, e: Encoder) -> None:
        self.header.encode(e)
        self.data.encode(e)
        self.last_commit.encode(e)
        self.evidence.encode(e)

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.buf()

    @classmethod
    def decode(cls, d: Decoder) -> "Block":
        from tendermint_tpu.types.agg_commit import AggregateCommit, AGG_COMMIT_TAG
        from tendermint_tpu.types.evidence import EvidenceData

        header = Header.decode(d)
        data = Data.decode(d)
        # self-describing last-commit: the aggregate form leads with a
        # magic byte no full Commit can start with, so blocks on either
        # side of an upgrade boundary decode without out-of-band state;
        # whether the format is ALLOWED at this height is enforced at
        # validate time (validate_basic's commit_format check) — a
        # schedule violation is a named refusal, never a decode wedge
        if d.peek_u8() == AGG_COMMIT_TAG:
            last_commit = AggregateCommit.decode(d)
        else:
            last_commit = Commit.decode(d)
        return cls(header, data, last_commit, evidence=EvidenceData.decode(d))

    @classmethod
    def from_bytes(cls, b: bytes) -> "Block":
        d = Decoder(b)
        block = cls.decode(d)
        if not d.done():
            raise ValueError("trailing bytes after block")
        return block

    def to_json(self):
        return {
            "header": self.header.to_json(),
            "data": self.data.to_json(),
            "last_commit": self.last_commit.to_json(),
            "evidence": self.evidence.to_json(),
        }

    @classmethod
    def from_json(cls, obj) -> "Block":
        from tendermint_tpu.codec import jsonval as jv
        from tendermint_tpu.types.evidence import EvidenceData

        from tendermint_tpu.types.agg_commit import commit_from_json

        obj = jv.require_dict(obj)
        return cls(
            Header.from_json(jv.dict_field(obj, "header")),
            Data.from_json(jv.dict_field(obj, "data")),
            commit_from_json(jv.dict_field(obj, "last_commit")),
            evidence=(
                EvidenceData.from_json(jv.dict_field(obj, "evidence"))
                if "evidence" in obj else EvidenceData()
            ),
        )

    def block_id(self, part_set: PartSet) -> BlockID:
        return BlockID(self.hash(), part_set.header())

    def __repr__(self):
        return f"Block#{self.hash().hex()[:12]}{{h:{self.header.height} txs:{len(self.data.txs)}}}"
