"""Abstract service interfaces shared across layers, with mocks for tests
(reference: types/services.go)."""

from __future__ import annotations

from typing import Callable


class MempoolI:
    """types/services.go:21-35."""

    def lock(self) -> None:
        raise NotImplementedError

    def unlock(self) -> None:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def check_tx(self, tx: bytes, cb: Callable | None = None):
        raise NotImplementedError

    def reap(self, max_txs: int) -> list[bytes]:
        raise NotImplementedError

    def update(self, height: int, txs: list[bytes]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        raise NotImplementedError

    def enable_txs_available(self, cb: Callable | None = None) -> None:
        """cb() fires (at most once per height) when the pool goes
        non-empty — the no-empty-blocks signal."""
        raise NotImplementedError


class MockMempool(MempoolI):
    """No-op mempool (types/services.go:37-48) — used by replay and tests."""

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def size(self) -> int:
        return 0

    def check_tx(self, tx: bytes, cb: Callable | None = None):
        return None

    def reap(self, max_txs: int) -> list[bytes]:
        return []

    def update(self, height: int, txs: list[bytes]) -> None:
        pass

    def flush(self) -> None:
        pass

    def enable_txs_available(self, cb: Callable | None = None) -> None:
        pass


class BlockStoreRPC:
    """Read surface (types/services.go:55-64)."""

    def height(self) -> int:
        raise NotImplementedError

    def base(self) -> int:
        """Lowest servable height (round 10: >1 after prune/restore)."""
        raise NotImplementedError

    def load_block_meta(self, height: int):
        raise NotImplementedError

    def load_block(self, height: int):
        raise NotImplementedError

    def load_block_part(self, height: int, index: int):
        raise NotImplementedError

    def load_block_commit(self, height: int):
        raise NotImplementedError

    def load_seen_commit(self, height: int):
        raise NotImplementedError


class BlockStoreI(BlockStoreRPC):
    """Full store (types/services.go:66-71)."""

    def save_block(self, block, part_set, seen_commit) -> None:
        raise NotImplementedError
