"""Proposal: signed (height, round, block parts header, POL round/blockID)
(reference: types/proposal.go). POLRound is -1 when there is no
proof-of-lock."""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.codec.binary import Decoder, Encoder
from tendermint_tpu.codec.canonical import canonical_dumps
from tendermint_tpu.crypto.keys import (
    SignatureEd25519,
    SignatureSecp256k1,
    signature_from_json,
)
from tendermint_tpu.types.block_id import BlockID, PartSetHeader


@dataclass(frozen=True)
class Proposal:
    height: int
    round_: int
    block_parts_header: PartSetHeader
    pol_round: int = -1
    pol_block_id: BlockID = BlockID()
    signature: SignatureEd25519 | None = None

    def canonical(self) -> dict:
        """CanonicalJSONProposal (types/canonical_json.go:19-25)."""
        return {
            "block_parts_header": self.block_parts_header.canonical(),
            "height": self.height,
            "pol_block_id": self.pol_block_id.canonical(),
            "pol_round": self.pol_round,
            "round": self.round_,
        }

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_dumps({"chain_id": chain_id, "proposal": self.canonical()})

    def with_signature(self, sig: SignatureEd25519) -> "Proposal":
        return replace(self, signature=sig)

    def encode(self, e: Encoder) -> None:
        e.write_varint(self.height)
        e.write_varint(self.round_)
        self.block_parts_header.encode(e)
        e.write_varint(self.pol_round)
        self.pol_block_id.encode(e)
        if self.signature is None:
            e.write_u8(0)
        elif self.signature.TYPE == SignatureEd25519.TYPE:
            e.write_raw(self.signature.bytes_())  # fixed 64-byte body
        else:
            e.write_u8(self.signature.TYPE)
            e.write_bytes(self.signature.raw)  # variable DER: length-prefixed

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.buf()

    @classmethod
    def decode(cls, d: Decoder) -> "Proposal":
        height = d.read_varint()
        rnd = d.read_varint()
        psh = PartSetHeader.decode(d)
        pol_round = d.read_varint()
        pol_bid = BlockID.decode(d)
        sig_type = d.read_u8()
        sig = None
        if sig_type == SignatureEd25519.TYPE:
            sig = SignatureEd25519(d._take(64))
        elif sig_type == SignatureSecp256k1.TYPE:
            sig = SignatureSecp256k1(d.read_bytes())
        elif sig_type != 0:
            raise ValueError(f"unknown signature type {sig_type}")
        return cls(height, rnd, psh, pol_round, pol_bid, sig)

    @classmethod
    def from_bytes(cls, b: bytes) -> "Proposal":
        return cls.decode(Decoder(b))

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "block_parts_header": self.block_parts_header.to_json(),
            "pol_round": self.pol_round,
            "pol_block_id": self.pol_block_id.to_json(),
            "signature": self.signature.to_json() if self.signature else None,
        }

    @classmethod
    def from_json(cls, obj) -> "Proposal":
        from tendermint_tpu.codec import jsonval as jv

        return cls(
            jv.int_field(obj, "height", 0, jv.MAX_HEIGHT),
            jv.int_field(obj, "round", 0, jv.MAX_ROUND),
            PartSetHeader.from_json(jv.dict_field(obj, "block_parts_header")),
            jv.int_field(obj, "pol_round", -1, jv.MAX_ROUND),
            BlockID.from_json(jv.dict_field(obj, "pol_block_id")),
            signature_from_json(obj["signature"]) if obj.get("signature") else None,
        )

    def __repr__(self):
        return (
            f"Proposal{{{self.height}/{self.round_} {self.block_parts_header!r} "
            f"POL:{self.pol_round}}}"
        )
