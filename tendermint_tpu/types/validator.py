"""Validator: address + pubkey + voting power + round-robin accumulator
(reference: types/validator.go)."""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.codec.binary import Encoder
from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.crypto.keys import PubKeyEd25519, pub_key_from_json


@dataclass
class Validator:
    address: bytes
    pub_key: PubKeyEd25519
    voting_power: int
    accum: int = 0

    @classmethod
    def new(cls, pub_key: PubKeyEd25519, voting_power: int) -> "Validator":
        return cls(pub_key.address(), pub_key, voting_power, 0)

    def copy(self) -> "Validator":
        return Validator(self.address, self.pub_key, self.voting_power, self.accum)

    def compare_accum(self, other: "Validator | None") -> "Validator":
        """Higher accum wins; ties break to the smaller address
        (types/validator.go:43-59)."""
        if other is None:
            return self
        if self.accum != other.accum:
            return self if self.accum > other.accum else other
        if self.address == other.address:
            raise ValueError("cannot compare identical validators")
        return self if self.address < other.address else other

    def hash(self) -> bytes:
        """Identity hash, excluding the round-volatile accum
        (types/validator.go:73-86)."""
        e = Encoder()
        e.write_bytes(self.address)
        e.write_raw(self.pub_key.bytes_())
        e.write_varint(self.voting_power)
        return ripemd160(e.buf())

    def to_json(self):
        return {
            "address": self.address.hex().upper(),
            "pub_key": self.pub_key.to_json(),
            "voting_power": self.voting_power,
            "accum": self.accum,
        }

    @classmethod
    def from_json(cls, obj) -> "Validator":
        return cls(
            bytes.fromhex(obj["address"]),
            pub_key_from_json(obj["pub_key"]),
            obj["voting_power"],
            obj.get("accum", 0),
        )

    def __repr__(self):
        return (
            f"Validator{{{self.address.hex()[:8]} VP:{self.voting_power} A:{self.accum}}}"
        )
