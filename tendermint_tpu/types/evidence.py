"""Duplicate-vote evidence: proof a validator signed two conflicting
votes at the same (height, round, type).

BEYOND the reference: Tendermint v0.11 detects conflicting votes and
punts with a TODO (consensus/state.go:1438-1447, "TODO: catch these
and punish"; VoteSet surfaces them as ErrVoteConflictingVotes,
types/vote_set.go:137-172). Here the detection site hands the pair to an
EvidencePool so byzantine drills (and operators, via the `evidence` RPC)
can assert that double-signing was SEEN — slashing/punishment remains
application policy, exactly as in the reference.

Round 12 extends the path end to end: evidence now COMMITS. Blocks
carry an EvidenceData section (types/block.py) whose Merkle root rides
the header as `evidence_hash`; the proposer drains the pool's pending
set into each proposal, every validator re-validates the section
cryptographically before prevoting (state/execution.validate_block),
and finalize marks the pieces committed — so one node detecting a
double-signer is enough for the whole network to end up with the proof
ON CHAIN, which the real-TCP byzantine scenario asserts byte-identically
across nodes (tests/test_netchaos.py).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from tendermint_tpu.codec.binary import Decoder, Encoder
from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.crypto.keys import pub_key_from_json
from tendermint_tpu.types.vote import Vote

# bound per block: evidence is ~600 B a piece (two signed votes + key);
# 64 keeps the worst-case section far below one 64 KB block part
MAX_EVIDENCE_PER_BLOCK = 64


class EvidenceError(Exception):
    pass


@dataclass(frozen=True)
class DuplicateVoteEvidence:
    """Two votes by one validator for the same (H, R, type) but
    different blocks. vote_a/vote_b are stored in canonical order
    (sorted by block-id key) so the same conflict always hashes the
    same regardless of arrival order."""

    pub_key: object  # PubKeyEd25519 | PubKeySecp256k1 (crypto/keys.py)
    vote_a: Vote
    vote_b: Vote

    @staticmethod
    def new(pub_key, vote_a: Vote, vote_b: Vote) -> "DuplicateVoteEvidence":
        if vote_b.block_id.key() < vote_a.block_id.key():
            vote_a, vote_b = vote_b, vote_a
        return DuplicateVoteEvidence(pub_key, vote_a, vote_b)

    @property
    def height(self) -> int:
        return self.vote_a.height

    @property
    def address(self) -> bytes:
        return self.vote_a.validator_address

    def validate(self, chain_id: str, batch_verifier=None) -> None:
        """Raise EvidenceError unless this really is a double-sign: same
        validator/H/R/type, DIFFERENT blocks, both signatures valid
        under pub_key for this chain. Anyone can forge an unvalidated
        pair; a validated one is cryptographic proof.

        batch_verifier (round 16): callable(items) -> list[bool] — the
        gateway batch plane (ops.gateway.Verifier.commit_batch_verifier);
        None keeps the per-signature pure path."""
        self.validate_structure(chain_id)
        if batch_verifier is not None:
            oks = batch_verifier(self.sig_items(chain_id))
            if not all(oks):
                raise EvidenceError("invalid signature on evidence vote")
            return
        for v in (self.vote_a, self.vote_b):
            if not self.pub_key.verify_bytes(v.sign_bytes(chain_id), v.signature):
                raise EvidenceError("invalid signature on evidence vote")

    def validate_structure(self, chain_id: str) -> None:
        """Everything validate checks BEFORE signatures (round 16 split:
        EvidenceData.validate batches every piece's signatures through
        one gateway call after the structural pass)."""
        a, b = self.vote_a, self.vote_b
        if (
            a.validator_address != b.validator_address
            or a.height != b.height
            or a.round_ != b.round_
            or a.type_ != b.type_
        ):
            raise EvidenceError("votes are not for the same (val, H, R, type)")
        if a.block_id.key() == b.block_id.key():
            raise EvidenceError("votes agree — no conflict")
        if b.block_id.key() < a.block_id.key():
            # canonical order is part of validity: otherwise the same
            # conflict hashes two ways and dedup double-counts it
            raise EvidenceError("evidence votes not in canonical order")
        if self.pub_key.address() != a.validator_address:
            raise EvidenceError("pub_key does not match validator address")
        for v in (a, b):
            if v.signature is None:
                raise EvidenceError("invalid signature on evidence vote")

    def sig_items(self, chain_id: str) -> list:
        """The two gateway verify lanes (pubkey, sign_bytes, signature);
        call after validate_structure (signatures proven present)."""
        return [
            (self.pub_key.raw, v.sign_bytes(chain_id), v.signature.raw)
            for v in (self.vote_a, self.vote_b)
        ]

    def hash(self) -> bytes:
        return ripemd160(
            self.vote_a.sign_bytes("") + b"/" + self.vote_b.sign_bytes("")
        )

    # -- binary (block embedding) ------------------------------------------

    def encode(self, e: Encoder) -> None:
        e.write_bytes(self.pub_key.bytes_())
        self.vote_a.encode(e)
        self.vote_b.encode(e)

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.buf()

    @classmethod
    def decode(cls, d: Decoder) -> "DuplicateVoteEvidence":
        from tendermint_tpu.crypto.keys import pub_key_from_bytes

        pub = pub_key_from_bytes(d.read_bytes())
        return cls(pub, Vote.decode(d), Vote.decode(d))

    def to_json(self):
        return {
            "type": "duplicate_vote",
            "height": self.height,
            "round": self.vote_a.round_,
            "vote_type": self.vote_a.type_,
            "validator_address": self.address.hex().upper(),
            "pub_key": self.pub_key.to_json(),
            "vote_a": self.vote_a.to_json(),
            "vote_b": self.vote_b.to_json(),
        }

    @classmethod
    def from_json(cls, obj) -> "DuplicateVoteEvidence":
        from tendermint_tpu.codec import jsonval as jv

        obj = jv.require_dict(obj)
        if obj.get("type") != "duplicate_vote":
            raise ValueError(f"unknown evidence type {obj.get('type')!r}")
        return cls(
            pub_key_from_json(obj.get("pub_key")),
            Vote.from_json(jv.dict_field(obj, "vote_a")),
            Vote.from_json(jv.dict_field(obj, "vote_b")),
        )


class EvidencePool:
    """Bounded, deduplicated store of validated evidence. Thread-safe:
    the consensus receive routine adds, the RPC thread lists."""

    def __init__(self, max_size: int = 1024):
        self._max = max_size
        self._by_hash: dict[bytes, DuplicateVoteEvidence] = {}
        self._order: list[bytes] = []
        # committed-hash memory is FIFO-bounded like the pool itself (a
        # dict for insertion order): pruning the oldest is safe — its
        # piece is deep in chain history, and a replayed copy would be
        # rejected by block validation long before it mattered
        self._committed: dict[bytes, None] = {}
        self._committed_max = max(4 * max_size, 4096)
        self._mtx = threading.Lock()

    def add(self, ev: DuplicateVoteEvidence, chain_id: str,
            batch_verifier=None) -> bool:
        """Validate + insert; False if duplicate or invalid (invalid
        evidence is dropped, not raised — the vote path must not die on
        a malformed pair). Dedup runs BEFORE validation: a peer
        re-gossiping a known conflict must cost a hash, not two ed25519
        verifies per replay. `batch_verifier` routes the pair's two
        signatures through one gateway batch (round 16)."""
        h = ev.hash()
        with self._mtx:
            if h in self._by_hash:
                return False
        try:
            ev.validate(chain_id, batch_verifier=batch_verifier)
        except EvidenceError:
            return False
        with self._mtx:
            if h in self._by_hash:
                return False
            if len(self._order) >= self._max:
                old = self._order.pop(0)
                self._by_hash.pop(old, None)
            self._by_hash[h] = ev
            self._order.append(h)
            return True

    def list(self) -> list[DuplicateVoteEvidence]:
        with self._mtx:
            return [self._by_hash[h] for h in self._order]

    def size(self) -> int:
        with self._mtx:
            return len(self._order)

    # -- block embedding (round 12) ----------------------------------------

    def pending(self, limit: int = MAX_EVIDENCE_PER_BLOCK,
                before_height: int | None = None) -> list:
        """Validated evidence not yet seen in a committed block — what a
        proposer drains into its next proposal. `before_height` is the
        PROPOSAL height: a block may only carry strictly-older evidence
        (EvidenceData.validate), so same-height detections wait one
        height."""
        with self._mtx:
            out = []
            for h in self._order:
                if h in self._committed:
                    continue
                ev = self._by_hash[h]
                if before_height is not None and ev.height >= before_height:
                    continue
                out.append(ev)
                if len(out) >= limit:
                    break
            return out

    def min_pending_height(self) -> int | None:
        """Lowest height referenced by evidence still awaiting commit —
        the retention coordinator's evidence floor (round 19): blocks at
        and above a pending piece's height stay on disk so operators and
        peers can audit the conflict it proves. None when nothing is
        pending."""
        with self._mtx:
            heights = [
                self._by_hash[h].height
                for h in self._order
                if h not in self._committed
            ]
            return min(heights) if heights else None

    def mark_committed(self, evidence: list) -> None:
        """A block carrying `evidence` was committed: remember each piece
        so it is never re-proposed, and adopt pieces this node had not
        detected itself (they arrived cryptographically validated — the
        block passed validate_block before apply), so every node's
        `evidence` RPC converges on the on-chain set."""
        with self._mtx:
            for ev in evidence:
                h = ev.hash()
                self._committed[h] = None
                while len(self._committed) > self._committed_max:
                    self._committed.pop(next(iter(self._committed)))
                if h not in self._by_hash:
                    if len(self._order) >= self._max:
                        # evict an already-committed entry first: a
                        # pending (detected, not-yet-proposed) piece must
                        # never be forgotten to remember one that is
                        # already on chain
                        victim_i = next(
                            (i for i, old in enumerate(self._order)
                             if old in self._committed),
                            0,
                        )
                        old = self._order.pop(victim_i)
                        self._by_hash.pop(old, None)
                    self._by_hash[h] = ev
                    self._order.append(h)

    def committed_count(self) -> int:
        with self._mtx:
            return len(self._committed)


@dataclass
class EvidenceData:
    """The block's evidence section (mirrors Data for txs): a bounded
    list of DuplicateVoteEvidence, Merkle-rooted into the header as
    `evidence_hash` (empty list = empty hash = a header byte-identical
    to the pre-evidence format)."""

    evidence: list = field(default_factory=list)
    _hash: bytes | None = None

    def hash(self) -> bytes:
        from tendermint_tpu.merkle.simple import leaf_hash, simple_hash_from_hashes

        if self._hash is None:
            if not self.evidence:
                self._hash = b""
            else:
                self._hash = simple_hash_from_hashes(
                    [leaf_hash(ev.to_bytes()) for ev in self.evidence]
                )
        return self._hash

    def validate(self, chain_id: str, block_height: int, validators,
                 batch_verifier=None) -> None:
        """Raise EvidenceError unless every piece is a provable,
        in-committee, prior-height double-sign and the section carries no
        duplicates (the proposer controls this list — it is adversarial
        input to every other validator).

        batch_verifier (round 16): with the gateway batch plane wired,
        every structural check runs first and then ALL pieces' signatures
        (two per piece) flush in ONE batched call — per-lane verdicts
        keep attribution, so a forged lane names exactly its piece."""
        if len(self.evidence) > MAX_EVIDENCE_PER_BLOCK:
            raise EvidenceError(
                f"too much evidence: {len(self.evidence)} > {MAX_EVIDENCE_PER_BLOCK}"
            )
        seen: set[bytes] = set()
        for ev in self.evidence:
            if not isinstance(ev, DuplicateVoteEvidence):
                raise EvidenceError("unknown evidence kind in block")
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)
            if not 1 <= ev.height < block_height:
                raise EvidenceError(
                    f"evidence height {ev.height} outside [1, {block_height})"
                )
            if validators is not None and not validators.has_address(ev.address):
                raise EvidenceError(
                    f"evidence validator {ev.address.hex()[:12]} not in the set"
                )
            if batch_verifier is None:
                ev.validate(chain_id)
            else:
                ev.validate_structure(chain_id)
        if batch_verifier is not None and self.evidence:
            items = []
            for ev in self.evidence:
                items.extend(ev.sig_items(chain_id))
            oks = batch_verifier(items)
            for i, ev in enumerate(self.evidence):
                if not all(oks[2 * i : 2 * i + 2]):
                    raise EvidenceError(
                        "invalid signature on evidence vote (piece "
                        f"{i}, validator {ev.address.hex()[:12]})"
                    )

    def encode(self, e: Encoder) -> None:
        e.write_list(self.evidence, lambda enc, ev: ev.encode(enc))

    @classmethod
    def decode(cls, d: Decoder) -> "EvidenceData":
        return cls(d.read_list(DuplicateVoteEvidence.decode))

    def to_json(self):
        return {"evidence": [ev.to_json() for ev in self.evidence]}

    @classmethod
    def from_json(cls, obj) -> "EvidenceData":
        from tendermint_tpu.codec import jsonval as jv

        obj = jv.require_dict(obj)
        return cls(
            [
                DuplicateVoteEvidence.from_json(o)
                for o in jv.list_field(obj, "evidence", MAX_EVIDENCE_PER_BLOCK)
            ]
        )
