"""Duplicate-vote evidence: proof a validator signed two conflicting
votes at the same (height, round, type).

BEYOND the reference: Tendermint v0.11 detects conflicting votes and
punts with a TODO (consensus/state.go:1438-1447, "TODO: catch these
and punish"; VoteSet surfaces them as ErrVoteConflictingVotes,
types/vote_set.go:137-172). Here the detection site hands the pair to an
EvidencePool so byzantine drills (and operators, via the `evidence` RPC)
can assert that double-signing was SEEN — slashing/punishment remains
application policy, exactly as in the reference.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.types.vote import Vote


class EvidenceError(Exception):
    pass


@dataclass(frozen=True)
class DuplicateVoteEvidence:
    """Two votes by one validator for the same (H, R, type) but
    different blocks. vote_a/vote_b are stored in canonical order
    (sorted by block-id key) so the same conflict always hashes the
    same regardless of arrival order."""

    pub_key: object  # PubKeyEd25519 | PubKeySecp256k1 (crypto/keys.py)
    vote_a: Vote
    vote_b: Vote

    @staticmethod
    def new(pub_key, vote_a: Vote, vote_b: Vote) -> "DuplicateVoteEvidence":
        if vote_b.block_id.key() < vote_a.block_id.key():
            vote_a, vote_b = vote_b, vote_a
        return DuplicateVoteEvidence(pub_key, vote_a, vote_b)

    @property
    def height(self) -> int:
        return self.vote_a.height

    @property
    def address(self) -> bytes:
        return self.vote_a.validator_address

    def validate(self, chain_id: str) -> None:
        """Raise EvidenceError unless this really is a double-sign: same
        validator/H/R/type, DIFFERENT blocks, both signatures valid
        under pub_key for this chain. Anyone can forge an unvalidated
        pair; a validated one is cryptographic proof."""
        a, b = self.vote_a, self.vote_b
        if (
            a.validator_address != b.validator_address
            or a.height != b.height
            or a.round_ != b.round_
            or a.type_ != b.type_
        ):
            raise EvidenceError("votes are not for the same (val, H, R, type)")
        if a.block_id.key() == b.block_id.key():
            raise EvidenceError("votes agree — no conflict")
        if self.pub_key.address() != a.validator_address:
            raise EvidenceError("pub_key does not match validator address")
        for v in (a, b):
            if v.signature is None or not self.pub_key.verify_bytes(
                v.sign_bytes(chain_id), v.signature
            ):
                raise EvidenceError("invalid signature on evidence vote")

    def hash(self) -> bytes:
        return ripemd160(
            self.vote_a.sign_bytes("") + b"/" + self.vote_b.sign_bytes("")
        )

    def to_json(self):
        return {
            "type": "duplicate_vote",
            "height": self.height,
            "round": self.vote_a.round_,
            "vote_type": self.vote_a.type_,
            "validator_address": self.address.hex().upper(),
            "vote_a": self.vote_a.to_json(),
            "vote_b": self.vote_b.to_json(),
        }


class EvidencePool:
    """Bounded, deduplicated store of validated evidence. Thread-safe:
    the consensus receive routine adds, the RPC thread lists."""

    def __init__(self, max_size: int = 1024):
        self._max = max_size
        self._by_hash: dict[bytes, DuplicateVoteEvidence] = {}
        self._order: list[bytes] = []
        self._mtx = threading.Lock()

    def add(self, ev: DuplicateVoteEvidence, chain_id: str) -> bool:
        """Validate + insert; False if duplicate or invalid (invalid
        evidence is dropped, not raised — the vote path must not die on
        a malformed pair). Dedup runs BEFORE validation: a peer
        re-gossiping a known conflict must cost a hash, not two ed25519
        verifies per replay."""
        h = ev.hash()
        with self._mtx:
            if h in self._by_hash:
                return False
        try:
            ev.validate(chain_id)
        except EvidenceError:
            return False
        with self._mtx:
            if h in self._by_hash:
                return False
            if len(self._order) >= self._max:
                old = self._order.pop(0)
                self._by_hash.pop(old, None)
            self._by_hash[h] = ev
            self._order.append(h)
            return True

    def list(self) -> list[DuplicateVoteEvidence]:
        with self._mtx:
            return [self._by_hash[h] for h in self._order]

    def size(self) -> int:
        with self._mtx:
            return len(self._order)
