"""PrivValidator: the validator's signing key with double-sign prevention
(reference: types/priv_validator.go).

Safety invariant kept from the reference (signBytesHRS, lines 225-275):
the last (height, round, step) + signature + sign-bytes are persisted to
disk ATOMICALLY BEFORE any signature is returned, so a crash-and-restart
can never produce two different signatures for the same HRS. Replaying the
same sign-bytes at the same HRS returns the saved signature (WAL replay
idempotence, consensus/replay.go:139-141).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading

from tendermint_tpu.crypto.keys import (
    PrivKeyEd25519,
    PubKeyEd25519,
    SignatureEd25519,
    gen_priv_key_ed25519,
    priv_key_from_json,
    signature_from_json,
)
from tendermint_tpu.types.heartbeat import Heartbeat
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE, Vote

STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3


def vote_to_step(vote: Vote) -> int:
    if vote.type_ == VOTE_TYPE_PREVOTE:
        return STEP_PREVOTE
    if vote.type_ == VOTE_TYPE_PRECOMMIT:
        return STEP_PRECOMMIT
    raise ValueError(f"unknown vote type {vote.type_}")


class DoubleSignError(Exception):
    pass


class PrivValidator:
    """Interface: GetAddress/GetPubKey/SignVote/SignProposal/SignHeartbeat
    (types/priv_validator.go:39-46)."""

    def get_address(self) -> bytes:
        raise NotImplementedError

    def get_pub_key(self) -> PubKeyEd25519:
        raise NotImplementedError

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        raise NotImplementedError

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        raise NotImplementedError

    def sign_heartbeat(self, chain_id: str, heartbeat: Heartbeat) -> Heartbeat:
        raise NotImplementedError


class PrivValidatorFS(PrivValidator):
    def __init__(self, priv_key: PrivKeyEd25519, file_path: str | None):
        self.priv_key = priv_key
        self.pub_key = priv_key.pub_key()
        self.address = self.pub_key.address()
        self.last_height = 0
        self.last_round = 0
        self.last_step = STEP_NONE
        self.last_signature: SignatureEd25519 | None = None
        self.last_sign_bytes: bytes | None = None
        self.file_path = file_path
        self._mtx = threading.Lock()

    # -- construction / persistence ---------------------------------------

    @classmethod
    def generate(cls, file_path: str | None = None) -> "PrivValidatorFS":
        return cls(gen_priv_key_ed25519(), file_path)

    @classmethod
    def load(cls, file_path: str) -> "PrivValidatorFS":
        with open(file_path) as f:
            obj = json.load(f)
        pv = cls(priv_key_from_json(obj["priv_key"]), file_path)
        pv.last_height = obj.get("last_height", 0)
        pv.last_round = obj.get("last_round", 0)
        pv.last_step = obj.get("last_step", STEP_NONE)
        if obj.get("last_signature"):
            pv.last_signature = signature_from_json(obj["last_signature"])
        if obj.get("last_signbytes"):
            pv.last_sign_bytes = bytes.fromhex(obj["last_signbytes"])
        return pv

    @classmethod
    def load_or_generate(cls, file_path: str) -> "PrivValidatorFS":
        if os.path.exists(file_path):
            return cls.load(file_path)
        pv = cls.generate(file_path)
        pv.save()
        return pv

    def to_json(self):
        return {
            "address": self.address.hex().upper(),
            "pub_key": self.pub_key.to_json(),
            "last_height": self.last_height,
            "last_round": self.last_round,
            "last_step": self.last_step,
            "last_signature": self.last_signature.to_json()
            if self.last_signature
            else None,
            "last_signbytes": self.last_sign_bytes.hex().upper()
            if self.last_sign_bytes
            else None,
            "priv_key": self.priv_key.to_json(),
        }

    def save(self) -> None:
        with self._mtx:
            self._save()

    def _save(self) -> None:
        """Atomic write + fsync before returning — the double-sign guard's
        durability requirement (types/priv_validator.go:163-183)."""
        if not self.file_path:
            raise RuntimeError("cannot save PrivValidator: file_path not set")
        data = json.dumps(self.to_json(), indent=2).encode()
        d = os.path.dirname(self.file_path) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".privval-")
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self.file_path)

    def reset(self) -> None:
        """Unsafe: forget last-sign state (types/priv_validator.go:188-196)."""
        self.last_height = 0
        self.last_round = 0
        self.last_step = STEP_NONE
        self.last_signature = None
        self.last_sign_bytes = None
        if self.file_path:
            self.save()

    # -- PrivValidator interface ------------------------------------------

    def get_address(self) -> bytes:
        return self.address

    def get_pub_key(self) -> PubKeyEd25519:
        return self.pub_key

    def sign_vote(self, chain_id: str, vote: Vote) -> Vote:
        with self._mtx:
            sig = self._sign_bytes_hrs(
                vote.height, vote.round_, vote_to_step(vote), vote.sign_bytes(chain_id)
            )
        return vote.with_signature(sig)

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> Proposal:
        with self._mtx:
            sig = self._sign_bytes_hrs(
                proposal.height,
                proposal.round_,
                STEP_PROPOSE,
                proposal.sign_bytes(chain_id),
            )
        return proposal.with_signature(sig)

    def sign_heartbeat(self, chain_id: str, heartbeat: Heartbeat) -> Heartbeat:
        # heartbeats carry no double-sign risk: signed without HRS tracking
        # (types/priv_validator.go SignHeartbeat)
        return heartbeat.with_signature(
            self.priv_key.sign(heartbeat.sign_bytes(chain_id))
        )

    def _sign_bytes_hrs(
        self, height: int, round_: int, step: int, sign_bytes: bytes
    ) -> SignatureEd25519:
        """types/priv_validator.go:225-275, case-for-case."""
        if self.last_height > height:
            raise DoubleSignError("height regression")
        if self.last_height == height:
            if self.last_round > round_:
                raise DoubleSignError("round regression")
            if self.last_round == round_:
                if self.last_step > step:
                    raise DoubleSignError("step regression")
                if self.last_step == step:
                    if self.last_sign_bytes is not None:
                        if self.last_signature is None:
                            raise RuntimeError(
                                "LastSignature nil but LastSignBytes is not"
                            )
                        if self.last_sign_bytes == sign_bytes:
                            # idempotent replay of the same payload
                            return self.last_signature
                    raise DoubleSignError("step regression (conflicting payload)")

        sig = self.priv_key.sign(sign_bytes)
        self.last_height = height
        self.last_round = round_
        self.last_step = step
        self.last_signature = sig
        self.last_sign_bytes = sign_bytes
        if self.file_path:
            self._save()
        return sig
