"""BlockID and PartSetHeader (reference: types/block.go:414-443,
types/part_set.go:60-85). Kept in their own module because nearly every
other type depends on them."""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.codec.binary import Decoder, Encoder


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0

    def canonical(self) -> dict:
        """CanonicalJSONPartSetHeader (types/canonical_json.go:14-17)."""
        return {"hash": self.hash, "total": self.total}

    def encode(self, e: Encoder) -> None:
        e.write_varint(self.total)
        e.write_bytes(self.hash)

    @classmethod
    def decode(cls, d: Decoder) -> "PartSetHeader":
        total = d.read_varint()
        h = d.read_bytes()
        return cls(total, h)

    def to_json(self):
        return {"total": self.total, "hash": self.hash.hex().upper()}

    @classmethod
    def from_json(cls, obj) -> "PartSetHeader":
        from tendermint_tpu.codec import jsonval as jv

        return cls(
            jv.int_field(obj, "total", 0, jv.MAX_INDEX),
            jv.hex_field(obj, "hash"),
        )

    def __repr__(self):
        return f"PartSetHeader({self.total}:{self.hash.hex()[:12]})"


ZERO_PSH = PartSetHeader()


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    parts_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_zero(self) -> bool:
        return len(self.hash) == 0 and self.parts_header.is_zero()

    def key(self) -> bytes:
        """Machine key for votesByBlock maps (types/block.go:433-435)."""
        e = Encoder()
        self.parts_header.encode(e)
        return self.hash + e.buf()

    def canonical(self):
        """CanonicalJSONBlockID; a zero BlockID canonicalizes with hash
        omitted (omitempty semantics, types/canonical_json.go:9-12)."""
        if self.is_zero():
            return {"parts": self.parts_header.canonical()}
        return {"hash": self.hash, "parts": self.parts_header.canonical()}

    def encode(self, e: Encoder) -> None:
        e.write_bytes(self.hash)
        self.parts_header.encode(e)

    @classmethod
    def decode(cls, d: Decoder) -> "BlockID":
        h = d.read_bytes()
        psh = PartSetHeader.decode(d)
        return cls(h, psh)

    def to_json(self):
        return {"hash": self.hash.hex().upper(), "parts": self.parts_header.to_json()}

    @classmethod
    def from_json(cls, obj) -> "BlockID":
        from tendermint_tpu.codec import jsonval as jv

        return cls(
            jv.hex_field(obj, "hash"),
            PartSetHeader.from_json(jv.dict_field(obj, "parts")),
        )

    def __repr__(self):
        return f"BlockID({self.hash.hex()[:12]}:{self.parts_header!r})"


ZERO_BLOCK_ID = BlockID()
