"""ConsensusParams: consensus-critical limits that travel in the genesis
doc (reference: types/params.go)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class BlockSizeParams:
    max_bytes: int = 22020096  # 21MB (types/params.go:45-51)
    max_txs: int = 10000
    max_gas: int = -1


@dataclass
class TxSizeParams:
    max_bytes: int = 10240  # types/params.go:54-60
    max_gas: int = -1


@dataclass
class BlockGossipParams:
    block_part_size_bytes: int = 65536  # types/params.go:62-68


# Upper bound on a legal block part (round 18): the consensus DATA
# channel's reassembly ceiling is sized to carry any part this
# validation admits (a part hex-doubles inside its JSON gossip message,
# plus proof steps — consensus/reactor.get_channels derives from this).
# Without the bound, a genesis declaring a bigger part size would make
# every block-part message a fatal frame violation at the recv ceiling.
MAX_BLOCK_PART_SIZE_BYTES = 1 << 18  # 256 KiB


@dataclass
class ConsensusParams:
    block_size: BlockSizeParams = field(default_factory=BlockSizeParams)
    tx_size: TxSizeParams = field(default_factory=TxSizeParams)
    block_gossip: BlockGossipParams = field(default_factory=BlockGossipParams)

    def validate(self) -> str | None:
        """types/params.go:72-88; None when valid."""
        if self.block_size.max_bytes <= 0:
            return "block_size.max_bytes must be > 0"
        if self.block_gossip.block_part_size_bytes <= 0:
            return "block_gossip.block_part_size_bytes must be > 0"
        if self.block_gossip.block_part_size_bytes > MAX_BLOCK_PART_SIZE_BYTES:
            return (
                "block_gossip.block_part_size_bytes must be <= "
                f"{MAX_BLOCK_PART_SIZE_BYTES} (the consensus data "
                "channel's recv ceiling is sized to this bound)"
            )
        return None

    def to_json(self):
        return {
            "block_size_params": {
                "max_bytes": self.block_size.max_bytes,
                "max_txs": self.block_size.max_txs,
                "max_gas": self.block_size.max_gas,
            },
            "tx_size_params": {
                "max_bytes": self.tx_size.max_bytes,
                "max_gas": self.tx_size.max_gas,
            },
            "block_gossip_params": {
                "block_part_size_bytes": self.block_gossip.block_part_size_bytes,
            },
        }

    @classmethod
    def from_json(cls, obj) -> "ConsensusParams":
        if not obj:
            return cls()
        bs = obj.get("block_size_params", {})
        ts = obj.get("tx_size_params", {})
        bg = obj.get("block_gossip_params", {})
        return cls(
            BlockSizeParams(
                bs.get("max_bytes", 22020096),
                bs.get("max_txs", 10000),
                bs.get("max_gas", -1),
            ),
            TxSizeParams(ts.get("max_bytes", 10240), ts.get("max_gas", -1)),
            BlockGossipParams(bg.get("block_part_size_bytes", 65536)),
        )
