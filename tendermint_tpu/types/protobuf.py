"""TM → ABCI type conversion (reference: types/protobuf.go TM2PB)."""

from __future__ import annotations

from tendermint_tpu.abci.types import ABCIValidator, Header as ABCIHeader


def tm2pb_header(header) -> ABCIHeader:
    """types/protobuf.go:12-22."""
    return ABCIHeader(
        chain_id=header.chain_id,
        height=header.height,
        time_ns=header.time_ns,
        num_txs=header.num_txs,
        app_hash=header.app_hash,
    )


def tm2pb_validator(val) -> ABCIValidator:
    """types/protobuf.go:40-45 (Validator -> abci diff entry)."""
    return ABCIValidator(pub_key_json=val.pub_key.to_json(), power=val.voting_power)


def tm2pb_validators(genesis_validators) -> list[ABCIValidator]:
    """Genesis validator list for InitChain (consensus/replay.go:237-240)."""
    return [
        ABCIValidator(pub_key_json=v.pub_key.to_json(), power=v.power)
        for v in genesis_validators
    ]
