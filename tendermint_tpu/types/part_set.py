"""PartSet: a block split into fixed-size parts, each carrying a Merkle
inclusion proof so peers can forward parts before holding the whole block
(reference: types/part_set.go; spec docs/specification/block-structure.rst
"PartSet").

Hot path note: part hashing (RIPEMD-160 per 64KB part,
types/part_set.go:32-41) and proof building (NewPartSetFromData,
types/part_set.go:95-122) are the Merkle workload the TPU kernel
(ops/merkle.py) vectorizes; this module is the CPU reference whose digests
the kernel must reproduce exactly. A part's leaf hash is the raw
ripemd160 of its bytes (NOT length-prefixed), matching Part.Hash.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dc_field

from tendermint_tpu.crypto.hashing import ripemd160
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.merkle.simple import SimpleProof, simple_proofs_from_hashes
from tendermint_tpu.types.block_id import PartSetHeader


class PartSetError(Exception):
    pass


class UnexpectedIndexError(PartSetError):
    pass


class InvalidProofError(PartSetError):
    pass


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: SimpleProof = dc_field(default_factory=SimpleProof)
    _hash: bytes | None = None

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = ripemd160(self.bytes_)
        return self._hash

    def to_json(self):
        return {
            "index": self.index,
            "bytes": self.bytes_.hex().upper(),
            "proof": self.proof.to_json(),
        }

    @classmethod
    def from_json(cls, obj) -> "Part":
        from tendermint_tpu.codec import jsonval as jv

        return cls(
            jv.int_field(obj, "index", 0, jv.MAX_INDEX),
            # parts are 64KB on the wire; 1MB here is protocol slack, the
            # real cap is the channel's recv capacity
            jv.hex_field(obj, "bytes", max_bytes=1 << 20),
            SimpleProof.from_json(jv.dict_field(obj, "proof")),
        )


class PartSet:
    """Thread-safe; mirrors the reference's two constructors: from full data
    (immutable, complete) or from a header (empty, fill via add_part)."""

    def __init__(self, total: int, hash_: bytes):
        self._total = total
        self._hash = hash_
        self._mtx = threading.Lock()
        self._parts: list[Part | None] = [None] * total
        self._bit_array = BitArray(total)
        self._count = 0

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_data(
        cls, data: bytes, part_size: int, hasher=None, tree_hasher=None,
        tree_submitter=None,
    ) -> "PartSet":
        """Split + build Merkle proofs (NewPartSetFromData,
        types/part_set.go:95-122). `hasher` optionally supplies batched leaf
        hashes (the TPU path); it must equal [ripemd160(p) for p in chunks].
        `tree_hasher` (ops/gateway.Hasher.part_set_tree) optionally
        supplies (leaf hashes, merkle.simple.FlatTree) in one offload
        pass — the devd hash_stream tree frame — making the proofs free
        here; returning None falls through to the host path. Either way
        proofs are shared-aunt views over one flat node buffer,
        byte-identical to the recursive reference.

        `tree_submitter` (round 14, ops/gateway.Hasher.submit_part_set_tree)
        is the FUTURE form of tree_hasher: the chunk batch is on the hash
        plane while this thread allocates the Part shells, and the future
        joins only when the proofs are actually needed — the pipelined
        proposal build's part-hash overlap. A failed submission falls
        through to the inline ladder; digests are identical either way."""
        total = max((len(data) + part_size - 1) // part_size, 1)
        chunks = [data[i * part_size : (i + 1) * part_size] for i in range(total)]
        leaf_hashes = tree = None
        fut = None
        if tree_submitter is not None:
            try:
                fut = tree_submitter(chunks)
            except Exception:
                fut = None  # submission is an accelerator, never a gate
        if fut is not None:
            # overlapped host work: the set shell + part list allocate
            # while the hash plane rounds the chunk batch
            shell_parts = [
                Part(index=i, bytes_=c) for i, c in enumerate(chunks)
            ]
            try:
                built = fut.result(timeout=120)
            except Exception:
                built = None
            if built is not None:
                leaf_hashes, tree = built
                root, proofs = tree.root(), tree.proofs()
                ps = cls(total, root)
                for i, part in enumerate(shell_parts):
                    part.proof = proofs[i]
                    part._hash = leaf_hashes[i]
                    ps._parts[i] = part
                    ps._bit_array.set_index(i, True)
                ps._count = total
                return ps
        if leaf_hashes is None and tree_hasher is not None:
            built = tree_hasher(chunks)
            if built is not None:
                leaf_hashes, tree = built
        if leaf_hashes is None:
            if hasher is not None:
                leaf_hashes = hasher(chunks)
            else:
                leaf_hashes = [ripemd160(c) for c in chunks]
        if tree is not None:
            root, proofs = tree.root(), tree.proofs()
        else:
            root, proofs = simple_proofs_from_hashes(list(leaf_hashes))
        ps = cls(total, root)
        for i, chunk in enumerate(chunks):
            part = Part(index=i, bytes_=chunk, proof=proofs[i], _hash=leaf_hashes[i])
            ps._parts[i] = part
            ps._bit_array.set_index(i, True)
        ps._count = total
        return ps

    @classmethod
    def from_header(cls, header: PartSetHeader) -> "PartSet":
        return cls(header.total, header.hash)

    # -- accessors ---------------------------------------------------------

    def header(self) -> PartSetHeader:
        return PartSetHeader(self._total, self._hash)

    def has_header(self, header: PartSetHeader) -> bool:
        return self.header() == header

    @property
    def total(self) -> int:
        return self._total

    def count(self) -> int:
        with self._mtx:
            return self._count

    def hash(self) -> bytes:
        return self._hash

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self._bit_array.copy()

    def is_complete(self) -> bool:
        with self._mtx:
            return self._count == self._total

    def get_part(self, index: int) -> Part | None:
        with self._mtx:
            if 0 <= index < self._total:
                return self._parts[index]
            return None

    # -- filling -----------------------------------------------------------

    def add_part(self, part: Part) -> bool:
        """True if added, False if duplicate; raises on bad index/proof
        (types/part_set.go:188-214). Proof verification per part is a
        reference hot path (the gossip receive path)."""
        with self._mtx:
            if part.index >= self._total:
                raise UnexpectedIndexError(f"index {part.index} >= total {self._total}")
            if self._parts[part.index] is not None:
                return False
            if not part.proof.verify(part.index, self._total, part.hash(), self._hash):
                raise InvalidProofError(f"invalid proof for part {part.index}")
            self._parts[part.index] = part
            self._bit_array.set_index(part.index, True)
            self._count += 1
            return True

    def get_data(self) -> bytes:
        """Reassembled payload; only valid when complete (the reference's
        PartSetReader, types/part_set.go:233-276)."""
        with self._mtx:
            if self._count != self._total:
                raise PartSetError("part set incomplete")
            return b"".join(p.bytes_ for p in self._parts)  # type: ignore[union-attr]

    def __repr__(self):
        return f"PartSet{{{self.count()}/{self._total} {self._hash.hex()[:12]}}}"
