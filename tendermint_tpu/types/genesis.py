"""GenesisDoc: chain bootstrap document (reference: types/genesis.go)."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from tendermint_tpu.crypto.keys import PubKeyEd25519, pub_key_from_json
from tendermint_tpu.types.params import ConsensusParams


@dataclass
class GenesisValidator:
    pub_key: PubKeyEd25519
    power: int
    name: str = ""

    def to_json(self):
        return {"pub_key": self.pub_key.to_json(), "power": self.power, "name": self.name}

    @classmethod
    def from_json(cls, obj) -> "GenesisValidator":
        return cls(pub_key_from_json(obj["pub_key"]), obj["power"], obj.get("name", ""))


# commit wire formats (round 16, docs/committee.md): "full" = the
# reference Commit (one signed vote per validator); "aggregate" = the
# half-aggregated prototype (types/agg_commit.py). A format flag in
# GENESIS, not config: every node of a chain must agree or refuse —
# mixed-format nets cannot silently form (decode_commit's refusal).
COMMIT_FORMATS = ("full", "aggregate")


@dataclass
class GenesisDoc:
    genesis_time_ns: int
    chain_id: str
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    consensus_params: ConsensusParams = field(default_factory=ConsensusParams)
    commit_format: str = "full"
    # Scheduled consensus-rule flip: blocks at heights >= upgrade_height
    # carry their last_commit in upgrade_format; heights below stay on
    # commit_format forever. 0 = no flip scheduled. The schedule is part
    # of the chain identity — nodes disagreeing on it refuse at the
    # handshake (p2p/node_info.py), never wedge on a later decode.
    upgrade_height: int = 0
    upgrade_format: str = ""

    def validate_and_complete(self) -> None:
        """types/genesis.go:55-84: ensure chain id, >=1 validator with
        positive power, valid consensus params."""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        err = self.consensus_params.validate()
        if err:
            raise ValueError(err)
        if self.commit_format not in COMMIT_FORMATS:
            raise ValueError(
                f"unknown commit_format {self.commit_format!r}; "
                f"expected one of {COMMIT_FORMATS}"
            )
        if self.upgrade_height < 0:
            raise ValueError("upgrade_height must be >= 0")
        if self.upgrade_height:
            if self.upgrade_format not in COMMIT_FORMATS:
                raise ValueError(
                    f"unknown upgrade_format {self.upgrade_format!r}; "
                    f"expected one of {COMMIT_FORMATS}"
                )
            if self.upgrade_format == self.commit_format:
                raise ValueError(
                    "upgrade_format equals commit_format; drop the schedule"
                )
            if self.upgrade_height < 2:
                # height 1 carries no last_commit, so the earliest height
                # whose format can differ is 2
                raise ValueError("upgrade_height must be >= 2")
        elif self.upgrade_format:
            raise ValueError("upgrade_format set without upgrade_height")
        if not self.validators:
            raise ValueError("genesis doc must include at least one validator")
        for v in self.validators:
            if v.power <= 0:
                raise ValueError(f"validator {v.name!r} has non-positive power")

    def commit_format_at(self, height: int) -> str:
        """Wire format of the last_commit carried by the block at
        `height` (which attests height-1). Heights below the scheduled
        flip are commit_format forever; at and above, upgrade_format."""
        if self.upgrade_height and height >= self.upgrade_height:
            return self.upgrade_format
        return self.commit_format

    def aggregate_commits_at(self, height: int) -> bool:
        return self.commit_format_at(height) == "aggregate"

    def schedule_string(self) -> str:
        """Canonical one-token schedule descriptor, carried in the p2p
        handshake: `full`, or `full>aggregate@100` when a flip is set."""
        if self.upgrade_height:
            return f"{self.commit_format}>{self.upgrade_format}@{self.upgrade_height}"
        return self.commit_format

    def aggregate_commits(self) -> bool:
        """True when ANY height uses the aggregate format (genesis flag
        or scheduled flip) — the agg_commit.decode_commit gate."""
        return self.commit_format == "aggregate" or self.upgrade_format == "aggregate"

    def validator_hash(self) -> bytes:
        from tendermint_tpu.types.validator import Validator
        from tendermint_tpu.types.validator_set import ValidatorSet

        vs = ValidatorSet([Validator.new(v.pub_key, v.power) for v in self.validators])
        return vs.hash()

    def to_json(self):
        out = {
            "genesis_time": self.genesis_time_ns,
            "chain_id": self.chain_id,
            "validators": [v.to_json() for v in self.validators],
            "app_hash": self.app_hash.hex().upper(),
            "consensus_params": self.consensus_params.to_json(),
        }
        if self.commit_format != "full":
            # key present only off the default so every existing genesis
            # doc serializes byte-identically to the pre-flag format
            out["commit_format"] = self.commit_format
        if self.upgrade_height:
            out["upgrade_height"] = self.upgrade_height
            out["upgrade_format"] = self.upgrade_format
        return out

    def save_as(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2)

    @classmethod
    def from_json(cls, obj) -> "GenesisDoc":
        doc = cls(
            genesis_time_ns=obj.get("genesis_time", 0),
            chain_id=obj["chain_id"],
            validators=[GenesisValidator.from_json(v) for v in obj.get("validators", [])],
            app_hash=bytes.fromhex(obj.get("app_hash", "")),
            consensus_params=ConsensusParams.from_json(obj.get("consensus_params")),
            commit_format=obj.get("commit_format", "full"),
            upgrade_height=obj.get("upgrade_height", 0),
            upgrade_format=obj.get("upgrade_format", ""),
        )
        doc.validate_and_complete()
        return doc

    @classmethod
    def from_file(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(json.load(f))
