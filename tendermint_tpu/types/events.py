"""Event taxonomy + payloads + fire helpers (reference: types/events.go).

Event strings are the pub/sub keys on the EventSwitch; the consensus
reactor and RPC WebSocket manager subscribe by these names.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from tendermint_tpu.libs.events import Fireable

# -- event names (types/events.go:14-46) ------------------------------------

EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_TIMEOUT_PROPOSE = "TimeoutPropose"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_POLKA = "Polka"
EVENT_UNLOCK = "Unlock"
EVENT_LOCK = "Lock"
EVENT_RELOCK = "Relock"
EVENT_TIMEOUT_WAIT = "TimeoutWait"
EVENT_VOTE = "Vote"
EVENT_PROPOSAL_HEARTBEAT = "ProposalHeartbeat"
# beyond reference: fired when the proposal part-set gains a part
# (build or gossip) — the consensus reactor broadcasts a HasBlockPart
# announcement off it so peers stop re-sending parts we already hold
# (the round-20 part-gossip dedup screen)
EVENT_PROPOSAL_BLOCK_PART = "ProposalBlockPart"
# beyond reference: fired when duplicate-vote evidence is validated and
# pooled (types/evidence.py; the reference detects conflicts and punts,
# consensus/state.go:1438-1447)
EVENT_EVIDENCE = "Evidence"


def event_string_tx(tx_hash: bytes) -> str:
    """Per-tx event key (types/events.go EventStringTx): lets
    broadcast_tx_commit wait for exactly its own tx."""
    return f"Tx:{tx_hash.hex().upper()}"


# -- payloads (types/events.go:105-145) --------------------------------------


@dataclass
class EventDataNewBlock:
    block: Any

    def to_json(self):
        return {"block": self.block.to_json()}


@dataclass
class EventDataNewBlockHeader:
    header: Any

    def to_json(self):
        return {"header": self.header.to_json()}


@dataclass
class EventDataTx:
    height: int
    tx: bytes
    data: bytes
    log: str
    code: int
    error: str = ""

    def to_json(self):
        return {
            "height": self.height,
            "tx": self.tx.hex().upper(),
            "data": (self.data or b"").hex().upper(),
            "log": self.log,
            "code": self.code,
            "error": self.error,
        }


@dataclass
class EventDataRoundState:
    height: int
    round_: int
    step: str
    round_state: Any = None  # full RoundState for internal subscribers

    def to_json(self):
        return {"height": self.height, "round": self.round_, "step": self.step}


@dataclass
class EventDataVote:
    vote: Any

    def to_json(self):
        return {"vote": self.vote.to_json()}


@dataclass
class EventDataBlockPart:
    height: int
    round_: int
    index: int

    def to_json(self):
        return {"height": self.height, "round": self.round_, "index": self.index}


@dataclass
class EventDataProposalHeartbeat:
    heartbeat: Any

    def to_json(self):
        return {"heartbeat": self.heartbeat.to_json()}


# -- fire helpers (types/events.go:190-251) ----------------------------------


def fire_event_new_block(evsw: Fireable, block) -> None:
    evsw.fire_event(EVENT_NEW_BLOCK, EventDataNewBlock(block))


def fire_event_new_block_header(evsw: Fireable, header) -> None:
    evsw.fire_event(EVENT_NEW_BLOCK_HEADER, EventDataNewBlockHeader(header))


def fire_event_vote(evsw: Fireable, vote) -> None:
    evsw.fire_event(EVENT_VOTE, EventDataVote(vote))


def fire_event_tx(evsw: Fireable, data: EventDataTx) -> None:
    evsw.fire_event(event_string_tx_from_data(data), data)


def event_string_tx_from_data(data: EventDataTx) -> str:
    from tendermint_tpu.types.tx import tx_hash

    return event_string_tx(tx_hash(data.tx))
