"""ValidatorSet: address-sorted validator set with weighted-round-robin
proposer selection and commit verification (reference:
types/validator_set.go).

verify_commit is the HOTTEST path in the reference (sequential Ed25519
verifies, types/validator_set.go:220-264; called from block validation at
state/execution.go:198 and per fast-sync block at blockchain/reactor.go:235).
Here it accepts a pluggable batch verifier so the whole commit's signatures
flush to the TPU kernel in one batch while preserving the exact CPU
accept/reject semantics.
"""

from __future__ import annotations

import bisect

from tendermint_tpu.merkle.simple import simple_hash_from_hashes
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT


class CommitError(Exception):
    pass


class ValidatorSet:
    def __init__(self, validators: list[Validator] | None):
        vals = sorted((v.copy() for v in (validators or [])), key=lambda v: v.address)
        self.validators: list[Validator] = vals
        self.proposer: Validator | None = None
        self._total_voting_power = 0
        self._hash: bytes | None = None
        if validators:
            self.increment_accum(1)

    # -- lookups -----------------------------------------------------------

    def _addresses(self) -> list[bytes]:
        return [v.address for v in self.validators]

    def has_address(self, address: bytes) -> bool:
        i = bisect.bisect_left(self._addresses(), address)
        return i < len(self.validators) and self.validators[i].address == address

    def get_by_address(self, address: bytes) -> tuple[int, Validator | None]:
        i = bisect.bisect_left(self._addresses(), address)
        if i < len(self.validators) and self.validators[i].address == address:
            return i, self.validators[i].copy()
        return 0, None

    def get_by_index(self, index: int) -> tuple[bytes, Validator | None]:
        if index < 0 or index >= len(self.validators):
            return b"", None
        v = self.validators[index]
        return v.address, v.copy()

    def size(self) -> int:
        return len(self.validators)

    def total_voting_power(self) -> int:
        if self._total_voting_power == 0:
            self._total_voting_power = sum(v.voting_power for v in self.validators)
        return self._total_voting_power

    # -- proposer rotation -------------------------------------------------

    def increment_accum(self, times: int) -> None:
        """Each validator gains VotingPower*times accum; `times` times, the
        richest validator is decremented by the total power; the last
        decremented one becomes proposer (types/validator_set.go:52-69)."""
        for v in self.validators:
            v.accum += v.voting_power * times
        for i in range(times):
            mostest = None
            for v in self.validators:
                mostest = v.compare_accum(mostest)
            assert mostest is not None
            if i == times - 1:
                self.proposer = mostest
            mostest.accum -= self.total_voting_power()

    def get_proposer(self) -> Validator | None:
        if not self.validators:
            return None
        if self.proposer is None:
            p = None
            for v in self.validators:
                p = v.compare_accum(p)
            self.proposer = p
        return self.proposer.copy()

    # -- membership changes (applied from ABCI EndBlock diffs,
    #    state/execution.go:120-159) --------------------------------------

    def _invalidate(self) -> None:
        self.proposer = None
        self._total_voting_power = 0
        self._hash = None

    def add(self, val: Validator) -> bool:
        val = val.copy()
        i = bisect.bisect_left(self._addresses(), val.address)
        if i < len(self.validators) and self.validators[i].address == val.address:
            return False
        self.validators.insert(i, val)
        self._invalidate()
        return True

    def update(self, val: Validator) -> bool:
        i, existing = self.get_by_address(val.address)
        if existing is None:
            return False
        self.validators[i] = val.copy()
        self._invalidate()
        return True

    def remove(self, address: bytes) -> tuple[Validator | None, bool]:
        i = bisect.bisect_left(self._addresses(), address)
        if i >= len(self.validators) or self.validators[i].address != address:
            return None, False
        removed = self.validators.pop(i)
        self._invalidate()
        return removed, True

    def copy(self) -> "ValidatorSet":
        vs = ValidatorSet(None)
        vs.validators = [v.copy() for v in self.validators]
        vs.proposer = self.proposer.copy() if self.proposer else None
        vs._total_voting_power = self._total_voting_power
        return vs

    def hash(self) -> bytes:
        """Merkle root of validator identity hashes
        (types/validator_set.go:140-148). Memoized: the fast-sync
        speculation check reads it per block, and the O(N) tree over a
        large set would otherwise rival the verify work it guards."""
        if not self.validators:
            return b""
        if self._hash is None:
            self._hash = simple_hash_from_hashes([v.hash() for v in self.validators])
        return self._hash

    # -- commit verification (TPU-batched hot path) ------------------------

    def verify_commit(
        self,
        chain_id: str,
        block_id: BlockID,
        height: int,
        commit,
        batch_verifier=None,
    ) -> None:
        """Raise CommitError unless +2/3 of this set signed the commit
        (types/validator_set.go:220-264 semantics, preserved exactly).

        batch_verifier: callable(list[(pubkey32, msg, sig64)]) -> list[bool].
        When given, all structural checks run first, then every signature in
        the commit is verified in ONE batch (the TPU kernel); per-signature
        results feed the same accept/reject logic the sequential loop has.

        Polymorphic over the commit format: an AggregateCommit takes the
        aggregate branch (one multi-term check, batched through the
        device gateway), so every caller — block validation, fast-sync,
        statesync restore, the light client — spans the upgrade boundary
        without knowing it.
        """
        if self._try_verify_aggregate(chain_id, block_id, height, commit):
            return
        items = self._commit_structural_check(chain_id, height, commit)
        if batch_verifier is not None:
            oks = batch_verifier(
                [(val.pub_key.raw, sb, sig.raw) for _, _, val, sb, sig in items]
            )
        else:
            oks = [
                val.pub_key.verify_bytes(sb, sig) for _, _, val, sb, sig in items
            ]
        self._commit_tally(block_id, items, oks)

    def verify_commit_async(
        self, chain_id: str, block_id: BlockID, height: int, commit,
        async_batch_verifier,
    ):
        """Pipelined verify_commit: structural checks run now (raising
        CommitError immediately), the signature batch is dispatched to the
        device, and the returned zero-arg resolver finishes the tally —
        raising CommitError exactly as verify_commit would. Lets a caller
        overlap host work (e.g. the NEXT block's part-set hashing in fast
        sync) with device execution.

        async_batch_verifier: callable(items) -> resolver() -> list[bool]
        (ops/gateway.Verifier.verify_batch_async)."""
        if self._aggregate_precheck(chain_id, block_id, height, commit):
            def finish_agg() -> None:
                self._try_verify_aggregate(chain_id, block_id, height, commit)

            return finish_agg
        items = self._commit_structural_check(chain_id, height, commit)
        resolve = async_batch_verifier(
            [(val.pub_key.raw, sb, sig.raw) for _, _, val, sb, sig in items]
        )

        def finish() -> None:
            self._commit_tally(block_id, items, resolve())

        return finish

    def verify_commits_async(self, chain_id: str, entries, async_batch_verifier):
        """Grouped form of verify_commit_async: several commits' signature
        batches concatenated into ONE device dispatch (a 1000-validator
        commit underfills the kernel; four of them hit the efficient
        bucket). entries = [(block_id, height, commit)]; returns one
        zero-arg finisher per entry, each raising CommitError exactly as
        verify_commit would for its block. Fast sync's speculative
        pipeline is the caller (blockchain/reactor._dispatch_speculative).
        On the devd backend the concatenated batch rides the streamed
        transport (chunked frames, double-buffered daemon-side), so the
        group dispatch overlaps IPC with device compute for free."""
        spans, all_items = [], []
        for block_id, height, commit in entries:
            try:
                if self._aggregate_precheck(chain_id, block_id, height, commit):
                    # aggregate entries carry no per-vote lanes for the
                    # group batch; their multi-term check runs at consume
                    # time and rides the gateway's own aggregate batching
                    spans.append(((block_id, height, commit), None, 0, 0))
                    continue
                items = self._commit_structural_check(chain_id, height, commit)
            except CommitError as exc:
                # a structurally bad commit must not poison its group: its
                # finisher re-raises at consume time, where the caller's
                # normal bad-block path adjudicates it
                spans.append((block_id, exc, 0, 0))
                continue
            spans.append((block_id, items, len(all_items), len(all_items) + len(items)))
            all_items.extend(
                (val.pub_key.raw, sb, sig.raw) for _, _, val, sb, sig in items
            )
        resolve = async_batch_verifier(all_items)
        memo: dict = {}

        def resolved():
            if "oks" not in memo:
                memo["oks"] = resolve()
            return memo["oks"]

        def make_finish(block_id, items, lo, hi):
            def finish() -> None:
                if isinstance(items, CommitError):
                    raise items
                if items is None:
                    bid, h, agg = block_id
                    self._try_verify_aggregate(chain_id, bid, h, agg)
                    return
                self._commit_tally(block_id, items, resolved()[lo:hi])

            return finish

        return [make_finish(*span) for span in spans]

    # -- aggregate-commit branch (docs/upgrade.md cutover) -----------------

    def _aggregate_precheck(self, chain_id: str, block_id: BlockID,
                            height: int, commit) -> bool:
        """True iff `commit` is an AggregateCommit; raises CommitError on
        the cheap structural mismatches so async callers fail fast."""
        from tendermint_tpu.types.agg_commit import AggregateCommit

        if not isinstance(commit, AggregateCommit):
            return False
        if height != commit.height():
            raise CommitError(f"wrong height: {height} vs {commit.height()}")
        if block_id != commit.block_id:
            raise CommitError(
                f"aggregate commit is for a different block: "
                f"{commit.block_id!r} vs {block_id!r}"
            )
        err = commit.validate_basic()
        if err:
            raise CommitError(err)
        return True

    def _try_verify_aggregate(self, chain_id: str, block_id: BlockID,
                              height: int, commit,
                              agg_verifier=None) -> bool:
        """Full aggregate verify (structural + quorum + multi-term
        crypto); returns False when `commit` is a plain Commit."""
        if not self._aggregate_precheck(chain_id, block_id, height, commit):
            return False
        commit.verify(chain_id, self, agg_verifier=agg_verifier)
        return True

    def _commit_structural_check(self, chain_id: str, height: int, commit):
        """Everything verify_commit checks before signatures; returns the
        signature work items (idx, precommit, validator, sign_bytes, sig)."""
        if self.size() != len(commit.precommits):
            raise CommitError(
                f"wrong set size: {self.size()} vs {len(commit.precommits)}"
            )
        if height != commit.height():
            raise CommitError(f"wrong height: {height} vs {commit.height()}")

        round_ = commit.round_()
        items = []
        # sign bytes exclude the validator identity (canonical_json), so
        # every precommit for the same (H,R,type,block) shares ONE byte
        # string — memoizing turns N canonical serializations per commit
        # into one, which dominated the fast-sync host time at N=1000
        sb_cache: dict = {}
        for idx, precommit in enumerate(commit.precommits):
            if precommit is None:
                continue  # validator skipped: fine
            if precommit.height != height:
                raise CommitError(f"wrong precommit height at {idx}")
            if precommit.round_ != round_:
                raise CommitError(f"wrong precommit round at {idx}")
            if precommit.type_ != VOTE_TYPE_PRECOMMIT:
                raise CommitError(f"not a precommit at index {idx}")
            _, val = self.get_by_index(idx)
            assert val is not None
            if precommit.signature is None:
                raise CommitError(f"missing signature at index {idx}")
            # keyed on the frozen BlockID itself: injective (unlike
            # .key()'s unprefixed concatenation) and cheaper to build
            sb_key = (precommit.height, precommit.round_, precommit.block_id)
            sb = sb_cache.get(sb_key)
            if sb is None:
                sb = sb_cache[sb_key] = precommit.sign_bytes(chain_id)
            items.append((idx, precommit, val, sb, precommit.signature))
        return items

    def _commit_tally(self, block_id: BlockID, items, oks) -> None:
        tallied = 0
        for (idx, precommit, val, _, _), ok in zip(items, oks):
            if not ok:
                raise CommitError(f"invalid signature: {precommit!r}")
            if block_id != precommit.block_id:
                continue  # not an error, but doesn't count toward quorum
            tallied += val.voting_power

        if tallied <= self.total_voting_power() * 2 // 3:
            raise CommitError(
                f"insufficient voting power: got {tallied}, "
                f"needed {self.total_voting_power() * 2 // 3 + 1}"
            )

    def to_json(self):
        return {
            "validators": [v.to_json() for v in self.validators],
            "proposer": self.proposer.to_json() if self.proposer else None,
        }

    @classmethod
    def from_json(cls, obj) -> "ValidatorSet":
        vs = cls(None)
        vs.validators = [Validator.from_json(v) for v in obj["validators"]]
        if obj.get("proposer"):
            p = Validator.from_json(obj["proposer"])
            # alias the in-set object when present (the reference's heap holds
            # pointers into the validator list)
            vs.proposer = next(
                (v for v in vs.validators if v.address == p.address), p
            )
        return vs

    def __repr__(self):
        prop = self.get_proposer()
        return f"ValidatorSet{{n:{self.size()} proposer:{prop!r}}}"
