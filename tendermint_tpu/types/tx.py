"""Transactions: raw bytes with Merkle hashing and inclusion proofs
(reference: types/tx.go). Tx is a plain `bytes` alias; helpers operate on
lists of them. The left-heavy (n+1)//2 split matches types/tx.go:33-46;
since round 7 the tree builds flat (merkle.simple.FlatTree — same shape,
same bytes, no recursion), and the injected batch hook
(ops/gateway.Hasher.tx_merkle_root) memoizes roots per tx set so
reproposals and gossip re-validation of an unchanged set never rehash."""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

from tendermint_tpu.merkle.simple import (
    SimpleProof,
    leaf_hash,
    simple_hash_from_hashes,
    simple_proofs_from_hashes,
)

Tx = bytes


def tx_hash(tx: Tx) -> bytes:
    """Tx.Hash: hash of the length-prefixed tx bytes (types/tx.go:20-22)."""
    return leaf_hash(tx)


# Batched tx-tree hook: node assembly injects the TPU hashing gateway
# (ops/gateway.Hasher.tx_merkle_root) so Data.hash / block validation ride
# the batched kernel; None means pure-CPU. The gateway preserves the exact
# tree shape, so hashes are identical either way (enforced by tests).
_batch_tx_root = None


def set_batch_tx_root(fn) -> None:
    global _batch_tx_root
    _batch_tx_root = fn


def txs_hash(txs: list[Tx]) -> bytes:
    """Merkle root of tx hashes (types/tx.go:33-46). Empty list -> b""."""
    if _batch_tx_root is not None:
        return _batch_tx_root(list(txs))
    return simple_hash_from_hashes([tx_hash(tx) for tx in txs])


def txs_index(txs: list[Tx], tx: Tx) -> int:
    for i, t in enumerate(txs):
        if t == tx:
            return i
    return -1


def txs_index_by_hash(txs: list[Tx], h: bytes) -> int:
    for i, t in enumerate(txs):
        if tx_hash(t) == h:
            return i
    return -1


@dataclass
class TxProof:
    """Merkle inclusion proof for one tx (types/tx.go:92-113)."""

    index: int
    total: int
    root_hash: bytes
    data: Tx
    proof: SimpleProof = dc_field(default_factory=SimpleProof)

    def leaf_hash(self) -> bytes:
        return tx_hash(self.data)

    def validate(self, data_hash: bytes) -> str | None:
        """None if valid against data_hash; else an error string."""
        if data_hash != self.root_hash:
            return "proof matches different data hash"
        if not self.proof.verify(self.index, self.total, self.leaf_hash(), self.root_hash):
            return "proof is not internally consistent"
        return None

    def to_json(self):
        return {
            "index": self.index,
            "total": self.total,
            "root_hash": self.root_hash.hex().upper(),
            "data": self.data.hex().upper(),
            "proof": self.proof.to_json(),
        }

    @classmethod
    def from_json(cls, obj) -> "TxProof":
        return cls(
            obj["index"],
            obj["total"],
            bytes.fromhex(obj["root_hash"]),
            bytes.fromhex(obj["data"]),
            SimpleProof.from_json(obj["proof"]),
        )


def txs_proof(txs: list[Tx], i: int) -> TxProof:
    if i < 0 or i >= len(txs):
        raise IndexError("tx index out of range")
    root, proofs = simple_proofs_from_hashes([tx_hash(tx) for tx in txs])
    return TxProof(index=i, total=len(txs), root_hash=root, data=txs[i], proof=proofs[i])


@dataclass
class TxResult:
    """Execution result of one tx, as stored by the tx indexer
    (types/tx.go:118-123)."""

    height: int
    index: int
    tx: Tx
    result: Any  # abci.ResponseDeliverTx

    def to_json(self):
        return {
            "height": self.height,
            "index": self.index,
            "tx": self.tx.hex().upper(),
            "result": self.result.to_json() if self.result is not None else None,
        }
