"""Aggregate commit prototype (round 16, docs/committee.md): +2/3
precommits as ONE object instead of N full signed votes.

A full ``Commit`` carries every precommit wholesale — address, index,
height, round, type, block id, and a 64-byte signature per validator:
~150+ bytes each, ~60 KB of every block and every commit-gossip message
at N=400. The precommits that actually form the quorum all sign the SAME
canonical payload (vote sign-bytes exclude the validator identity), so
the whole section compresses to: the block id, (height, round), a signer
bit array over the validator set, one 32-byte nonce point R per signer,
and a single folded scalar — Ed25519 half-aggregation
(crypto/ed25519_agg.py). That is ~32 bytes per signer instead of ~150:
the gossip-bytes shrink that makes million-user-scale committees
plausible (arXiv 2302.00418's aggregated design point).

What the format gives up: precommits for OTHER blocks (tolerated in a
full Commit as round evidence, never counted toward quorum) cannot join
the aggregate and are dropped at conversion — the aggregate carries
exactly the quorum.

Format flag + mixed-net story: the wire form leads with a magic tag byte
(0xAC) no full Commit can start with (a Commit's first byte is its block
hash's varint length — 0x00 or 0x14), and ``decode_commit`` only accepts
it when the chain's genesis says ``commit_format: "aggregate"``
(types/genesis.py). A full-format node fed an aggregate commit refuses
LOUDLY at decode, and the genesis docs themselves differ byte-for-byte —
a mixed net cannot silently form.

Round 22 turned the prototype into the consensus rule: blocks, the block
store, gossip (commit catchup included), fast-sync, statesync manifests,
and the light client all carry and verify AggregateCommit wherever the
chain's schedule (types/genesis.py commit_format_at) says the format is
active, and the multi-term verify rides the device-plane gateway
(ops/gateway.Verifier.verify_aggregate) instead of the pure-python
reference path. The class mirrors Commit's accessor surface
(height()/round_()/size()/bit_array()/hash()/validate_basic()) so every
consumer stays polymorphic over the two forms. docs/upgrade.md covers
the upgrade-at-height orchestration that flips a live net between them.
"""

from __future__ import annotations

from tendermint_tpu.codec.binary import Decoder, Encoder
from tendermint_tpu.crypto import ed25519_agg
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.merkle.simple import leaf_hash
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.validator_set import CommitError, ValidatorSet
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote

# leading wire byte; a full Commit starts with its block-id hash's varint
# length byte (0x00 empty / 0x14 twenty) — never this
AGG_COMMIT_TAG = 0xAC

MAX_AGG_SIGNERS = 1 << 16


class AggregateCommit:
    """(block_id, height, round, signer bits, R per signer, s_agg)."""

    def __init__(self, block_id: BlockID, height: int, round_: int,
                 signers: BitArray, rs: list[bytes], s_agg: bytes):
        self.block_id = block_id
        self._height = height
        self._round = round_
        self.signers = signers
        self.rs = rs
        self.s_agg = s_agg
        self._hash: bytes | None = None

    # -- Commit-mirroring accessors (keep consumers polymorphic) -----------

    def height(self) -> int:
        return self._height

    def round_(self) -> int:
        return self._round

    def type_(self) -> int:
        return VOTE_TYPE_PRECOMMIT

    def size(self) -> int:
        """Validator-set size the signer bits span (Commit.size parity:
        the set size, not the signer count)."""
        return self.signers.size

    def num_signers(self) -> int:
        return len(self.rs)

    def bit_array(self) -> BitArray:
        return self.signers.copy()

    def is_commit(self) -> bool:
        return len(self.rs) != 0

    def validate_basic(self) -> str | None:
        """None if structurally valid; else an error string (the
        aggregate counterpart of Commit.validate_basic)."""
        if self.block_id.is_zero():
            return "aggregate commit cannot be for nil block"
        if not self.rs:
            return "no signers in aggregate commit"
        if not 0 < self.signers.size <= MAX_AGG_SIGNERS:
            return f"bad signer-set size {self.signers.size}"
        if len(self.rs) != self.signers.num_true_bits():
            return "signer bits do not match nonce points"
        if any(len(r) != 32 for r in self.rs):
            return "nonce point not 32 bytes"
        if len(self.s_agg) != 32:
            return "aggregate scalar not 32 bytes"
        return None

    def hash(self) -> bytes:
        """What the NEXT header's last_commit_hash commits to when the
        aggregate format is active: the leaf hash of the canonical wire
        form (one leaf — the object IS the whole commit section)."""
        if self._hash is None:
            self._hash = leaf_hash(self.to_bytes())
        return self._hash

    # -- construction ------------------------------------------------------

    @classmethod
    def from_commit(cls, commit: Commit, chain_id: str,
                    val_set: ValidatorSet) -> "AggregateCommit":
        """Aggregate a full Commit's quorum precommits. Only ed25519
        precommits FOR the commit's block join (off-block precommits and
        other key types cannot — see module docstring); raises
        CommitError if what remains cannot carry +2/3 of `val_set`."""
        height, round_ = commit.height(), commit.round_()
        items, idxs, power = [], [], 0
        for idx, pre in enumerate(commit.precommits):
            if pre is None or pre.signature is None:
                continue
            if (
                pre.block_id != commit.block_id
                or pre.height != height
                or pre.round_ != round_
                or len(pre.signature.raw) != 64
            ):
                continue
            _, val = val_set.get_by_index(idx)
            if val is None or len(val.pub_key.raw) != 32:
                continue
            items.append(
                (val.pub_key.raw, pre.sign_bytes(chain_id), pre.signature.raw)
            )
            idxs.append(idx)
            power += val.voting_power
        if power * 3 <= val_set.total_voting_power() * 2:
            raise CommitError(
                f"aggregable precommits carry only {power}/"
                f"{val_set.total_voting_power()} power"
            )
        rs, s_agg = ed25519_agg.aggregate(items)
        return cls(
            commit.block_id, height, round_,
            BitArray.from_indices(val_set.size(), idxs), rs, s_agg,
        )

    # -- verification ------------------------------------------------------

    def sign_message(self, chain_id: str) -> bytes:
        """The ONE canonical payload every aggregated lane signed (vote
        sign-bytes exclude the validator identity)."""
        return Vote(
            validator_address=b"", validator_index=0, height=self._height,
            round_=self._round, type_=VOTE_TYPE_PRECOMMIT,
            block_id=self.block_id,
        ).sign_bytes(chain_id)

    def verify(self, chain_id: str, val_set: ValidatorSet,
               agg_verifier=None) -> None:
        """Raise CommitError unless the aggregate carries +2/3 of
        `val_set` AND the half-aggregate equation holds for every signer
        lane — the whole commit's crypto in one multi-term check.

        `agg_verifier` is a callable (pubs, msgs, rs, s_agg) -> bool; by
        default the device-plane gateway's batched dual-scalar-mul path
        (ops/gateway.Verifier.verify_aggregate — devd/sharded/direct
        kernel with the pure-python reference as CPU floor), so the hot
        paths never pay ~4.5 ms/lane of host scalar muls."""
        idxs = self.signers.indices()
        if self.signers.size != val_set.size():
            raise CommitError(
                f"wrong set size: {self.signers.size} vs {val_set.size()}"
            )
        if len(idxs) != len(self.rs):
            raise CommitError(
                f"signer bits ({len(idxs)}) != nonce points ({len(self.rs)})"
            )
        pubs, power = [], 0
        for idx in idxs:
            _, val = val_set.get_by_index(idx)
            if val is None:
                raise CommitError(f"signer index {idx} not in the set")
            if len(val.pub_key.raw) != 32:
                raise CommitError(f"signer {idx} is not an ed25519 key")
            pubs.append(val.pub_key.raw)
            power += val.voting_power
        if power * 3 <= val_set.total_voting_power() * 2:
            raise CommitError(
                f"insufficient voting power: got {power}, "
                f"needed {val_set.total_voting_power() * 2 // 3 + 1}"
            )
        msg = self.sign_message(chain_id)
        if agg_verifier is None:
            agg_verifier = _default_agg_verifier()
        if not agg_verifier(pubs, [msg] * len(pubs), self.rs, self.s_agg):
            raise CommitError("aggregate signature failed verification")

    # -- wire --------------------------------------------------------------

    def encode(self, e: Encoder) -> None:
        e.write_u8(AGG_COMMIT_TAG)
        self.block_id.encode(e)
        e.write_varint(self._height)
        e.write_varint(self._round)
        e.write_varint(self.signers.size)
        e.write_list(self.signers.indices(), lambda enc, i: enc.write_varint(i))
        e.write_raw(b"".join(self.rs))
        e.write_raw(self.s_agg)

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.buf()

    @classmethod
    def decode(cls, d: Decoder) -> "AggregateCommit":
        if d.read_u8() != AGG_COMMIT_TAG:
            raise ValueError("not an aggregate commit")
        block_id = BlockID.decode(d)
        height = d.read_varint()
        round_ = d.read_varint()
        size = d.read_varint()
        if not 0 < size <= MAX_AGG_SIGNERS:
            raise ValueError(f"bad signer-set size {size}")
        idxs = d.read_list(lambda dec: dec.read_varint())
        if len(idxs) > size or any(not 0 <= i < size for i in idxs):
            raise ValueError("signer index out of range")
        # strictly ascending is the canonical (and only) wire order:
        # verify() pairs rs with signers.indices() (sorted), so any
        # other order would mispair lanes and reject a valid aggregate
        if any(a >= b for a, b in zip(idxs, idxs[1:])):
            raise ValueError("signer indices not strictly ascending")
        rs = [d.read_raw(32) for _ in range(len(idxs))]
        s_agg = d.read_raw(32)
        return cls(block_id, height, round_,
                   BitArray.from_indices(size, idxs), rs, s_agg)

    @classmethod
    def from_bytes(cls, b: bytes) -> "AggregateCommit":
        d = Decoder(b)
        out = cls.decode(d)
        if not d.done():
            raise ValueError("trailing bytes after aggregate commit")
        return out

    # -- json --------------------------------------------------------------

    def to_json(self):
        return {
            "block_id": self.block_id.to_json(),
            "height": self._height,
            "round": self._round,
            "signers": self.signers.to_json(),
            "rs": [r.hex().upper() for r in self.rs],
            "s_agg": self.s_agg.hex().upper(),
        }

    @classmethod
    def from_json(cls, obj) -> "AggregateCommit":
        from tendermint_tpu.codec import jsonval as jv

        obj = jv.require_dict(obj)
        signers_obj = jv.require_dict(obj.get("signers"))
        bits = jv.int_field(signers_obj, "bits", 1, MAX_AGG_SIGNERS)
        elems = signers_obj.get("elems")
        if not isinstance(elems, str) or len(elems) > (bits // 4) + 2:
            raise ValueError("bad signer bit array")
        try:
            signers = BitArray.from_int(bits, int(elems or "0", 16))
        except ValueError as exc:
            raise ValueError("bad signer bit array") from exc
        rs_hex = jv.list_field(obj, "rs", MAX_AGG_SIGNERS)
        rs = []
        for r in rs_hex:
            if not isinstance(r, str) or len(r) != 64:
                raise ValueError("bad nonce point hex")
            rs.append(bytes.fromhex(r))
        if len(rs) != signers.num_true_bits():
            raise ValueError("signer bits do not match nonce points")
        return cls(
            BlockID.from_json(jv.dict_field(obj, "block_id")),
            jv.int_field(obj, "height", 0, jv.MAX_HEIGHT),
            jv.int_field(obj, "round", 0, jv.MAX_ROUND),
            signers,
            rs,
            jv.hex_field(obj, "s_agg"),
        )

    def __repr__(self):
        return (
            f"AggregateCommit{{{len(self.rs)}/{self.signers.size} "
            f"for {self.block_id!r}}}"
        )


class AggregateLastCommit:
    """rs.last_commit stand-in when only a VERIFIED AggregateCommit is
    available for the previous height — commit-proof catchup and restart
    from an aggregate seen-commit (consensus/state.py). It carries no
    individual votes: vote-gossip picks nothing from it (bit_array() is
    empty; the reactor's aggregate catchup branch ships the whole commit
    instead), late precommits cannot be absorbed (begin_add refuses as a
    duplicate), but proposing at the next height works — make_commit()
    IS the aggregate, exactly what the schedule requires the next
    block's last_commit section to be."""

    def __init__(self, agg: "AggregateCommit", val_set: ValidatorSet):
        self.agg = agg
        self.val_set = val_set  # the set that signed (VoteSet parity)
        self.height = agg.height()
        self.round_ = agg.round_()
        self.type_ = VOTE_TYPE_PRECOMMIT

    def size(self) -> int:
        return self.agg.size()

    def has_two_thirds_majority(self) -> bool:
        return True  # verified against val_set before construction

    def two_thirds_majority(self):
        return self.agg.block_id

    def has_all(self) -> bool:
        return self.agg.num_signers() == self.agg.size()

    def make_commit(self):
        return self.agg

    def bit_array(self) -> BitArray:
        # EMPTY by design: pick_vote_to_send must never find a per-vote
        # lane here (there are none to send)
        return BitArray(self.agg.size())

    def get_by_index(self, index: int):
        # truthy for "this lane is covered" screens (vote_batcher), but
        # unreachable from vote gossip (bit_array above is empty)
        return self.agg if self.agg.signers.get_index(index) else None

    def begin_add(self, vote):
        return None  # cannot absorb votes; reads as an exact duplicate

    def add_vote(self, vote, verifier=None) -> bool:
        return False

    def __repr__(self):
        return f"AggregateLastCommit{{{self.agg!r}}}"


def _default_agg_verifier():
    """The gateway-batched aggregate verifier, resolved lazily (types/
    must not import ops/ at module load). Falls back to the pure-python
    reference if the gateway is unavailable for any reason."""
    try:
        from tendermint_tpu.ops.gateway import default_verifier

        return default_verifier().verify_aggregate
    except Exception:
        return ed25519_agg.verify_aggregate


def decode_commit(d: Decoder, aggregate_commits: bool = False):
    """Format-flag-aware commit decode: dispatches on the aggregate
    magic tag. `aggregate_commits` is whether the chain's schedule
    allows the aggregate format AT THIS HEIGHT (genesis
    ``commit_format_at``) — a node fed an aggregate commit for a
    full-format height refuses HERE, loudly (the mixed-net refusal
    test, tests/test_vote_batch.py)."""
    if d.peek_u8() == AGG_COMMIT_TAG:
        if not aggregate_commits:
            raise ValueError(
                "aggregate commit refused: this chain runs "
                "commit_format=full at this height (mixed-net refusal, "
                "docs/committee.md; upgrade schedule, docs/upgrade.md)"
            )
        return AggregateCommit.decode(d)
    return Commit.decode(d)


def commit_from_json(obj):
    """Polymorphic commit parse: aggregate JSON carries ``s_agg``, full
    carries ``precommits`` — the RPC /commit, statesync manifests, and
    the light client all accept either form and verify by the schedule."""
    if isinstance(obj, dict) and "s_agg" in obj:
        return AggregateCommit.from_json(obj)
    return Commit.from_json(obj)


def commit_is_aggregate(commit) -> bool:
    return isinstance(commit, AggregateCommit)
