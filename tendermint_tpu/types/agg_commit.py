"""Aggregate commit prototype (round 16, docs/committee.md): +2/3
precommits as ONE object instead of N full signed votes.

A full ``Commit`` carries every precommit wholesale — address, index,
height, round, type, block id, and a 64-byte signature per validator:
~150+ bytes each, ~60 KB of every block and every commit-gossip message
at N=400. The precommits that actually form the quorum all sign the SAME
canonical payload (vote sign-bytes exclude the validator identity), so
the whole section compresses to: the block id, (height, round), a signer
bit array over the validator set, one 32-byte nonce point R per signer,
and a single folded scalar — Ed25519 half-aggregation
(crypto/ed25519_agg.py). That is ~32 bytes per signer instead of ~150:
the gossip-bytes shrink that makes million-user-scale committees
plausible (arXiv 2302.00418's aggregated design point).

What the format gives up: precommits for OTHER blocks (tolerated in a
full Commit as round evidence, never counted toward quorum) cannot join
the aggregate and are dropped at conversion — the aggregate carries
exactly the quorum.

Format flag + mixed-net story: the wire form leads with a magic tag byte
(0xAC) no full Commit can start with (a Commit's first byte is its block
hash's varint length — 0x00 or 0x14), and ``decode_commit`` only accepts
it when the chain's genesis says ``commit_format: "aggregate"``
(types/genesis.py). A full-format node fed an aggregate commit refuses
LOUDLY at decode, and the genesis docs themselves differ byte-for-byte —
a mixed net cannot silently form. This is a PROTOTYPE: blocks and the
block store still carry full commits; the object, wire form, verifier,
flag, and refusal path are real, the consensus-rule cutover (headers
committing to aggregate last-commit hashes) is queued in ROADMAP.
"""

from __future__ import annotations

from tendermint_tpu.codec.binary import Decoder, Encoder
from tendermint_tpu.crypto import ed25519_agg
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.validator_set import CommitError, ValidatorSet
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote

# leading wire byte; a full Commit starts with its block-id hash's varint
# length byte (0x00 empty / 0x14 twenty) — never this
AGG_COMMIT_TAG = 0xAC

MAX_AGG_SIGNERS = 1 << 16


class AggregateCommit:
    """(block_id, height, round, signer bits, R per signer, s_agg)."""

    def __init__(self, block_id: BlockID, height: int, round_: int,
                 signers: BitArray, rs: list[bytes], s_agg: bytes):
        self.block_id = block_id
        self.height = height
        self.round_ = round_
        self.signers = signers
        self.rs = rs
        self.s_agg = s_agg

    # -- construction ------------------------------------------------------

    @classmethod
    def from_commit(cls, commit: Commit, chain_id: str,
                    val_set: ValidatorSet) -> "AggregateCommit":
        """Aggregate a full Commit's quorum precommits. Only ed25519
        precommits FOR the commit's block join (off-block precommits and
        other key types cannot — see module docstring); raises
        CommitError if what remains cannot carry +2/3 of `val_set`."""
        height, round_ = commit.height(), commit.round_()
        items, idxs, power = [], [], 0
        for idx, pre in enumerate(commit.precommits):
            if pre is None or pre.signature is None:
                continue
            if (
                pre.block_id != commit.block_id
                or pre.height != height
                or pre.round_ != round_
                or len(pre.signature.raw) != 64
            ):
                continue
            _, val = val_set.get_by_index(idx)
            if val is None or len(val.pub_key.raw) != 32:
                continue
            items.append(
                (val.pub_key.raw, pre.sign_bytes(chain_id), pre.signature.raw)
            )
            idxs.append(idx)
            power += val.voting_power
        if power * 3 <= val_set.total_voting_power() * 2:
            raise CommitError(
                f"aggregable precommits carry only {power}/"
                f"{val_set.total_voting_power()} power"
            )
        rs, s_agg = ed25519_agg.aggregate(items)
        return cls(
            commit.block_id, height, round_,
            BitArray.from_indices(val_set.size(), idxs), rs, s_agg,
        )

    # -- verification ------------------------------------------------------

    def sign_message(self, chain_id: str) -> bytes:
        """The ONE canonical payload every aggregated lane signed (vote
        sign-bytes exclude the validator identity)."""
        return Vote(
            validator_address=b"", validator_index=0, height=self.height,
            round_=self.round_, type_=VOTE_TYPE_PRECOMMIT,
            block_id=self.block_id,
        ).sign_bytes(chain_id)

    def verify(self, chain_id: str, val_set: ValidatorSet) -> None:
        """Raise CommitError unless the aggregate carries +2/3 of
        `val_set` AND the half-aggregate equation holds for every signer
        lane — the whole commit's crypto in one multi-term check."""
        idxs = self.signers.indices()
        if self.signers.size != val_set.size():
            raise CommitError(
                f"wrong set size: {self.signers.size} vs {val_set.size()}"
            )
        if len(idxs) != len(self.rs):
            raise CommitError(
                f"signer bits ({len(idxs)}) != nonce points ({len(self.rs)})"
            )
        pubs, power = [], 0
        for idx in idxs:
            _, val = val_set.get_by_index(idx)
            if val is None:
                raise CommitError(f"signer index {idx} not in the set")
            if len(val.pub_key.raw) != 32:
                raise CommitError(f"signer {idx} is not an ed25519 key")
            pubs.append(val.pub_key.raw)
            power += val.voting_power
        if power * 3 <= val_set.total_voting_power() * 2:
            raise CommitError(
                f"insufficient voting power: got {power}, "
                f"needed {val_set.total_voting_power() * 2 // 3 + 1}"
            )
        msg = self.sign_message(chain_id)
        if not ed25519_agg.verify_aggregate(
            pubs, [msg] * len(pubs), self.rs, self.s_agg
        ):
            raise CommitError("aggregate signature failed verification")

    # -- wire --------------------------------------------------------------

    def encode(self, e: Encoder) -> None:
        e.write_u8(AGG_COMMIT_TAG)
        self.block_id.encode(e)
        e.write_varint(self.height)
        e.write_varint(self.round_)
        e.write_varint(self.signers.size)
        e.write_list(self.signers.indices(), lambda enc, i: enc.write_varint(i))
        e.write_raw(b"".join(self.rs))
        e.write_raw(self.s_agg)

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.buf()

    @classmethod
    def decode(cls, d: Decoder) -> "AggregateCommit":
        if d.read_u8() != AGG_COMMIT_TAG:
            raise ValueError("not an aggregate commit")
        block_id = BlockID.decode(d)
        height = d.read_varint()
        round_ = d.read_varint()
        size = d.read_varint()
        if not 0 < size <= MAX_AGG_SIGNERS:
            raise ValueError(f"bad signer-set size {size}")
        idxs = d.read_list(lambda dec: dec.read_varint())
        if len(idxs) > size or any(not 0 <= i < size for i in idxs):
            raise ValueError("signer index out of range")
        # strictly ascending is the canonical (and only) wire order:
        # verify() pairs rs with signers.indices() (sorted), so any
        # other order would mispair lanes and reject a valid aggregate
        if any(a >= b for a, b in zip(idxs, idxs[1:])):
            raise ValueError("signer indices not strictly ascending")
        rs = [d.read_raw(32) for _ in range(len(idxs))]
        s_agg = d.read_raw(32)
        return cls(block_id, height, round_,
                   BitArray.from_indices(size, idxs), rs, s_agg)

    @classmethod
    def from_bytes(cls, b: bytes) -> "AggregateCommit":
        d = Decoder(b)
        out = cls.decode(d)
        if not d.done():
            raise ValueError("trailing bytes after aggregate commit")
        return out

    # -- json --------------------------------------------------------------

    def to_json(self):
        return {
            "block_id": self.block_id.to_json(),
            "height": self.height,
            "round": self.round_,
            "signers": self.signers.to_json(),
            "rs": [r.hex().upper() for r in self.rs],
            "s_agg": self.s_agg.hex().upper(),
        }

    @classmethod
    def from_json(cls, obj) -> "AggregateCommit":
        from tendermint_tpu.codec import jsonval as jv

        obj = jv.require_dict(obj)
        signers_obj = jv.require_dict(obj.get("signers"))
        bits = jv.int_field(signers_obj, "bits", 1, MAX_AGG_SIGNERS)
        elems = signers_obj.get("elems")
        if not isinstance(elems, str) or len(elems) > (bits // 4) + 2:
            raise ValueError("bad signer bit array")
        try:
            signers = BitArray.from_int(bits, int(elems or "0", 16))
        except ValueError as exc:
            raise ValueError("bad signer bit array") from exc
        rs_hex = jv.list_field(obj, "rs", MAX_AGG_SIGNERS)
        rs = []
        for r in rs_hex:
            if not isinstance(r, str) or len(r) != 64:
                raise ValueError("bad nonce point hex")
            rs.append(bytes.fromhex(r))
        if len(rs) != signers.num_true_bits():
            raise ValueError("signer bits do not match nonce points")
        return cls(
            BlockID.from_json(jv.dict_field(obj, "block_id")),
            jv.int_field(obj, "height", 0, jv.MAX_HEIGHT),
            jv.int_field(obj, "round", 0, jv.MAX_ROUND),
            signers,
            rs,
            jv.hex_field(obj, "s_agg"),
        )

    def __repr__(self):
        return (
            f"AggregateCommit{{{len(self.rs)}/{self.signers.size} "
            f"for {self.block_id!r}}}"
        )


def decode_commit(d: Decoder, aggregate_commits: bool = False):
    """Format-flag-aware commit decode: dispatches on the aggregate
    magic tag. `aggregate_commits` is the chain's genesis
    ``commit_format == "aggregate"`` — a full-format node fed an
    aggregate commit refuses HERE, loudly (the mixed-net refusal test,
    tests/test_vote_batch.py)."""
    if d.peek_u8() == AGG_COMMIT_TAG:
        if not aggregate_commits:
            raise ValueError(
                "aggregate commit refused: this chain's genesis runs "
                "commit_format=full (mixed-net refusal, docs/committee.md)"
            )
        return AggregateCommit.decode(d)
    return Commit.decode(d)
