"""VoteSet: collects signatures from validators at one (height, round, type)
while tracking double-sign conflicts with bounded memory (reference:
types/vote_set.go — the two-store votes/votesByBlock design and its
memory-bounding argument are preserved).

The per-vote Ed25519 verify here (reference types/vote_set.go:175) is a TPU
hot path: `add_vote` takes an optional single-item verifier, and the
consensus layer batches votes through ops.gateway before insertion; the
observable accept/reject behavior is identical either way.

Round 16 (big committees, docs/committee.md) splits the add into its two
halves so the consensus thread can micro-batch signatures across a
drained run of gossiped votes: `begin_add` runs every NON-signature
check — index/address bounds, height/round/type, exact-duplicate and
different-signature screens — and returns a `PendingVote` whose
`item()` is the gateway verify tuple; `commit_add(pending, ok)` applies
the verdict with add_vote's exact error taxonomy (one bad signature
rejects only its own vote). `add_vote` is now a composition of the two,
so the split path cannot drift from the sequential one.
"""

from __future__ import annotations

import threading

from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.block import Commit
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.vote import (
    ConflictingVotesError,
    InvalidSignatureError,
    InvalidValidatorAddressError,
    InvalidValidatorIndexError,
    UnexpectedStepError,
    VOTE_TYPE_PRECOMMIT,
    Vote,
)


class _BlockVotes:
    """Votes for one particular block key (types/vote_set.go:483-520)."""

    def __init__(self, peer_maj23: bool, num_validators: int):
        self.peer_maj23 = peer_maj23
        self.bit_array = BitArray(num_validators)
        self.votes: list[Vote | None] = [None] * num_validators
        self.sum = 0

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        i = vote.validator_index
        if self.votes[i] is None:
            self.bit_array.set_index(i, True)
            self.votes[i] = vote
            self.sum += voting_power

    def get_by_index(self, index: int) -> Vote | None:
        return self.votes[index]


class PendingVote:
    """The structural half of an add (round 16): produced by
    `VoteSet.begin_add` once every non-signature check passed. `item()`
    is the gateway verify tuple; `commit(ok)` applies the signature
    verdict and finishes the insertion with add_vote's error taxonomy."""

    __slots__ = ("vote_set", "vote", "val", "sign_bytes", "block_key")

    def __init__(self, vote_set: "VoteSet", vote: Vote, val, sign_bytes: bytes,
                 block_key: bytes):
        self.vote_set = vote_set
        self.vote = vote
        self.val = val
        self.sign_bytes = sign_bytes
        self.block_key = block_key

    def item(self) -> tuple[bytes, bytes, bytes]:
        """(pubkey, message, signature) — the ops.gateway batch lane."""
        return (self.val.pub_key.raw, self.sign_bytes, self.vote.signature.raw)

    def commit(self, ok: bool) -> bool:
        return self.vote_set.commit_add(self, ok)


class VoteSet:
    def __init__(
        self, chain_id: str, height: int, round_: int, type_: int, val_set: ValidatorSet
    ):
        if height == 0:
            raise ValueError("cannot make VoteSet for height 0")
        self.chain_id = chain_id
        self.height = height
        self.round_ = round_
        self.type_ = type_
        self.val_set = val_set
        self._mtx = threading.RLock()
        self._votes_bit_array = BitArray(val_set.size())
        self._votes: list[Vote | None] = [None] * val_set.size()
        self._sum = 0
        self._maj23: BlockID | None = None
        self._votes_by_block: dict[bytes, _BlockVotes] = {}
        self._peer_maj23s: dict[str, BlockID] = {}
        # sign-bytes memo: every vote in this set at the same block id
        # shares ONE canonical payload (identity is excluded from sign
        # bytes), so a 400-validator quorum costs one serialization, not
        # 400. Small cap — adversarial distinct-block spam must not pin
        # memory (each entry is ~200 B; honest rounds see 1-2 blocks)
        self._sb_cache: dict[bytes, bytes] = {}
        self._sb_cache_cap = 8

    def size(self) -> int:
        return self.val_set.size()

    # -- adding votes ------------------------------------------------------

    def add_vote(self, vote: Vote, verifier=None) -> bool:
        """Returns True if the vote was added, False for a duplicate.
        Raises VoteError subclasses otherwise (the reference's error
        taxonomy, types/vote_set.go:120-126).

        verifier: callable(pubkey32, msg, sig64) -> bool; defaults to the
        CPU verify. The consensus layer passes the batching gateway's
        single-item interface so WAL-replayed and gossiped votes take the
        same code path.

        Composed from the split halves (round 16), so batched and
        sequential insertion cannot diverge."""
        pending = self.begin_add(vote)
        if pending is None:
            return False  # exact duplicate
        if verifier is not None:
            ok = verifier(*pending.item())
        else:
            ok = pending.val.pub_key.verify_bytes(
                pending.sign_bytes, vote.signature
            )
        return self.commit_add(pending, ok)

    def begin_add(self, vote: Vote) -> PendingVote | None:
        """Every check add_vote runs BEFORE the signature verify:
        index/address bounds, height/round/type, the exact-duplicate
        screen (returns None — add_vote's False), the different-
        signature and missing-signature screens (raised). The returned
        entry's signature still needs a verdict before commit_add."""
        with self._mtx:
            return self._begin_add(vote)

    def _begin_add(self, vote: Vote) -> PendingVote | None:
        val_index = vote.validator_index
        val_addr = vote.validator_address
        block_key = vote.block_id.key()

        if val_index < 0 or len(val_addr) == 0:
            raise ValueError("validator index/address not set in vote")

        if (
            vote.height != self.height
            or vote.round_ != self.round_
            or vote.type_ != self.type_
        ):
            raise UnexpectedStepError(
                f"expected {self.height}/{self.round_}/{self.type_}, "
                f"got {vote.height}/{vote.round_}/{vote.type_}"
            )

        lookup_addr, val = self.val_set.get_by_index(val_index)
        if val is None:
            raise InvalidValidatorIndexError(str(val_index))
        if val_addr != lookup_addr:
            raise InvalidValidatorAddressError(val_addr.hex())

        existing = self._get_vote(val_index, block_key)
        if existing is not None:
            if existing.signature == vote.signature:
                return None  # exact duplicate
            # same H/R/S/block but different signature: invalid, since
            # ed25519 signing is deterministic
            raise InvalidSignatureError("different signature for same vote")

        if vote.signature is None:
            raise InvalidSignatureError("missing signature")
        sign_bytes = self._sb_cache.get(block_key)
        if sign_bytes is None:
            sign_bytes = vote.sign_bytes(self.chain_id)
            self._sb_cache[block_key] = sign_bytes
            while len(self._sb_cache) > self._sb_cache_cap:
                self._sb_cache.pop(next(iter(self._sb_cache)))
        return PendingVote(self, vote, val, sign_bytes, block_key)

    def commit_add(self, pending: PendingVote, ok: bool) -> bool:
        """Apply a pending entry's signature verdict. Error taxonomy is
        add_vote's: a failed verdict raises InvalidSignatureError for
        THIS vote only, a conflict raises ConflictingVotesError. The
        duplicate screen re-runs under the lock so an interleaved add of
        the same vote degrades to add_vote's False, never a crash."""
        vote = pending.vote
        if not ok:
            raise InvalidSignatureError(repr(vote))
        with self._mtx:
            existing = self._get_vote(vote.validator_index, pending.block_key)
            if existing is not None:
                if existing.signature == vote.signature:
                    return False  # duplicate landed between begin and commit
                raise InvalidSignatureError("different signature for same vote")
            added, conflicting = self._add_verified_vote(
                vote, pending.block_key, pending.val.voting_power
            )
        if conflicting is not None:
            raise ConflictingVotesError(conflicting, vote)
        if not added:
            raise RuntimeError("expected to add non-conflicting vote")
        return True

    def _get_vote(self, val_index: int, block_key: bytes) -> Vote | None:
        existing = self._votes[val_index]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        bv = self._votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(val_index)
        return None

    def _add_verified_vote(
        self, vote: Vote, block_key: bytes, voting_power: int
    ) -> tuple[bool, Vote | None]:
        """types/vote_set.go:209-280, preserved case-for-case."""
        val_index = vote.validator_index
        conflicting: Vote | None = None

        existing = self._votes[val_index]
        if existing is not None:
            # different block: conflict (duplicates were screened above)
            conflicting = existing
            # replace canonical vote if the new one is for the maj23 block
            if self._maj23 is not None and self._maj23.key() == block_key:
                self._votes[val_index] = vote
                self._votes_bit_array.set_index(val_index, True)
        else:
            self._votes[val_index] = vote
            self._votes_bit_array.set_index(val_index, True)
            self._sum += voting_power

        bv = self._votes_by_block.get(block_key)
        if bv is not None:
            if conflicting is not None and not bv.peer_maj23:
                # conflict and no peer claims this block is special: reject
                return False, conflicting
        else:
            if conflicting is not None:
                # not tracking this block and it's a conflict: forget it
                return False, conflicting
            bv = _BlockVotes(False, self.val_set.size())
            self._votes_by_block[block_key] = bv

        orig_sum = bv.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        bv.add_verified_vote(vote, voting_power)

        if orig_sum < quorum <= bv.sum and self._maj23 is None:
            self._maj23 = vote.block_id
            # promote this block's votes to canonical
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self._votes[i] = v

        return True, conflicting

    # -- peer claims -------------------------------------------------------

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """A peer claims +2/3 for block_id: start tracking conflicting votes
        for that block (types/vote_set.go:284-317)."""
        with self._mtx:
            block_key = block_id.key()
            existing = self._peer_maj23s.get(peer_id)
            if existing is not None:
                return  # peer already told us something (same or different)
            self._peer_maj23s[peer_id] = block_id
            bv = self._votes_by_block.get(block_key)
            if bv is not None:
                bv.peer_maj23 = True
            else:
                self._votes_by_block[block_key] = _BlockVotes(
                    True, self.val_set.size()
                )

    # -- queries -----------------------------------------------------------

    def bit_array(self) -> BitArray:
        with self._mtx:
            return self._votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        with self._mtx:
            bv = self._votes_by_block.get(block_id.key())
            return bv.bit_array.copy() if bv else None

    def get_by_index(self, val_index: int) -> Vote | None:
        with self._mtx:
            return self._votes[val_index]

    def get_by_address(self, address: bytes) -> Vote | None:
        with self._mtx:
            idx, val = self.val_set.get_by_address(address)
            if val is None:
                return None
            return self._votes[idx]

    def has_two_thirds_majority(self) -> bool:
        with self._mtx:
            return self._maj23 is not None

    def is_commit(self) -> bool:
        if self.type_ != VOTE_TYPE_PRECOMMIT:
            return False
        with self._mtx:
            return self._maj23 is not None

    def has_two_thirds_any(self) -> bool:
        with self._mtx:
            return self._sum > self.val_set.total_voting_power() * 2 // 3

    def has_all(self) -> bool:
        with self._mtx:
            return self._sum == self.val_set.total_voting_power()

    def two_thirds_majority(self) -> BlockID | None:
        with self._mtx:
            return self._maj23

    def make_commit(self) -> Commit:
        if self.type_ != VOTE_TYPE_PRECOMMIT:
            raise ValueError("commit requires precommit vote set")
        with self._mtx:
            if self._maj23 is None:
                raise ValueError("cannot make commit without +2/3 majority")
            return Commit(self._maj23, list(self._votes))

    def __repr__(self):
        with self._mtx:
            return (
                f"VoteSet{{H:{self.height} R:{self.round_} T:{self.type_} "
                f"+2/3:{self._maj23!r} {self._votes_bit_array!r}}}"
            )
