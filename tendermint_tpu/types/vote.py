"""Vote: a signed prevote/precommit (reference: types/vote.go).

Sign-bytes are canonical JSON wrapped with the chain id, exactly the
reference's CanonicalJSONOnceVote layout (types/canonical_json.go:27-33,
52-55), so a vote's signed payload is reproducible byte-for-byte from its
fields — the property the TPU batch verifier relies on.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.codec.binary import Decoder, Encoder
from tendermint_tpu.codec.canonical import canonical_dumps
from tendermint_tpu.crypto.keys import SignatureEd25519, SignatureSecp256k1, signature_from_json
from tendermint_tpu.types.block_id import BlockID

VOTE_TYPE_PREVOTE = 0x01
VOTE_TYPE_PRECOMMIT = 0x02


def is_vote_type_valid(t: int) -> bool:
    return t in (VOTE_TYPE_PREVOTE, VOTE_TYPE_PRECOMMIT)


class VoteError(Exception):
    pass


class UnexpectedStepError(VoteError):
    pass


class InvalidValidatorIndexError(VoteError):
    pass


class InvalidValidatorAddressError(VoteError):
    pass


class InvalidSignatureError(VoteError):
    pass


class ConflictingVotesError(VoteError):
    def __init__(self, vote_a: "Vote", vote_b: "Vote"):
        super().__init__("conflicting votes")
        self.vote_a = vote_a
        self.vote_b = vote_b


@dataclass(frozen=True)
class Vote:
    validator_address: bytes
    validator_index: int
    height: int
    round_: int
    type_: int
    block_id: BlockID
    signature: SignatureEd25519 | None = None

    def canonical(self) -> dict:
        """CanonicalJSONVote field set — excludes the signature and the
        validator identity (types/canonical_json.go:27-33)."""
        return {
            "block_id": self.block_id.canonical(),
            "height": self.height,
            "round": self.round_,
            "type": self.type_,
        }

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_dumps({"chain_id": chain_id, "vote": self.canonical()})

    def with_signature(self, sig: SignatureEd25519) -> "Vote":
        return replace(self, signature=sig)

    # -- binary (for commit hashing / wire / WAL) --------------------------

    def encode(self, e: Encoder) -> None:
        e.write_bytes(self.validator_address)
        e.write_varint(self.validator_index)
        e.write_varint(self.height)
        e.write_varint(self.round_)
        e.write_u8(self.type_)
        self.block_id.encode(e)
        if self.signature is None:
            e.write_u8(0)
        elif self.signature.TYPE == SignatureEd25519.TYPE:
            e.write_raw(self.signature.bytes_())  # fixed 64-byte body
        else:
            e.write_u8(self.signature.TYPE)
            e.write_bytes(self.signature.raw)  # variable DER: length-prefixed

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.buf()

    @classmethod
    def decode(cls, d: Decoder) -> "Vote":
        addr = d.read_bytes()
        idx = d.read_varint()
        height = d.read_varint()
        rnd = d.read_varint()
        typ = d.read_u8()
        bid = BlockID.decode(d)
        sig_type = d.read_u8()
        sig = None
        if sig_type == SignatureEd25519.TYPE:
            sig = SignatureEd25519(d._take(64))
        elif sig_type == SignatureSecp256k1.TYPE:
            sig = SignatureSecp256k1(d.read_bytes())
        elif sig_type != 0:
            raise ValueError(f"unknown signature type {sig_type}")
        return cls(addr, idx, height, rnd, typ, bid, sig)

    @classmethod
    def from_bytes(cls, b: bytes) -> "Vote":
        return cls.decode(Decoder(b))

    def to_json(self):
        return {
            "validator_address": self.validator_address.hex().upper(),
            "validator_index": self.validator_index,
            "height": self.height,
            "round": self.round_,
            "type": self.type_,
            "block_id": self.block_id.to_json(),
            "signature": self.signature.to_json() if self.signature else None,
        }

    @classmethod
    def from_json(cls, obj) -> "Vote":
        from tendermint_tpu.codec import jsonval as jv

        return cls(
            jv.hex_field(obj, "validator_address"),
            jv.int_field(obj, "validator_index", 0, jv.MAX_INDEX),
            jv.int_field(obj, "height", 0, jv.MAX_HEIGHT),
            jv.int_field(obj, "round", 0, jv.MAX_ROUND),
            jv.int_field(obj, "type", 0, 255),
            BlockID.from_json(jv.dict_field(obj, "block_id")),
            signature_from_json(obj["signature"]) if obj.get("signature") else None,
        )

    def __repr__(self):
        t = {VOTE_TYPE_PREVOTE: "Prevote", VOTE_TYPE_PRECOMMIT: "Precommit"}.get(
            self.type_, f"?{self.type_}"
        )
        return (
            f"Vote{{{self.validator_index}:{self.validator_address.hex()[:8]} "
            f"{self.height}/{self.round_:02d}/{t} {self.block_id.hash.hex()[:8]}}}"
        )
