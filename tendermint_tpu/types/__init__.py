"""Consensus data model (reference: types/ — SURVEY.md section 2.1).

Blocks, votes, validator sets, part sets, transactions, proposals,
genesis docs, the priv-validator signing guard, and the event taxonomy.
Everything signed or hashed routes through codec.canonical / codec.binary
so the CPU and TPU verification planes agree byte-for-byte.
"""

from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.part_set import Part, PartSet
from tendermint_tpu.types.vote import (
    ConflictingVotesError,
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    Vote,
    VoteError,
    is_vote_type_valid,
)
from tendermint_tpu.types.tx import Tx, TxProof, TxResult, txs_hash, txs_proof
from tendermint_tpu.types.validator import Validator
from tendermint_tpu.types.validator_set import ValidatorSet
from tendermint_tpu.types.block import Block, Commit, Data, Header
from tendermint_tpu.types.vote_set import VoteSet
from tendermint_tpu.types.proposal import Proposal
from tendermint_tpu.types.heartbeat import Heartbeat
from tendermint_tpu.types.params import ConsensusParams
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.priv_validator import PrivValidator, PrivValidatorFS

__all__ = [
    "BlockID",
    "PartSetHeader",
    "Part",
    "PartSet",
    "Vote",
    "VoteError",
    "ConflictingVotesError",
    "VOTE_TYPE_PREVOTE",
    "VOTE_TYPE_PRECOMMIT",
    "is_vote_type_valid",
    "Tx",
    "TxProof",
    "TxResult",
    "txs_hash",
    "txs_proof",
    "Validator",
    "ValidatorSet",
    "Block",
    "Header",
    "Data",
    "Commit",
    "VoteSet",
    "Proposal",
    "Heartbeat",
    "ConsensusParams",
    "GenesisDoc",
    "GenesisValidator",
    "PrivValidator",
    "PrivValidatorFS",
]
