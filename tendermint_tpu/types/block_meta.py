"""BlockMeta: the header+blockID summary stored per height
(reference: types/block_meta.go)."""

from __future__ import annotations

from tendermint_tpu.types.block import Header
from tendermint_tpu.types.block_id import BlockID


class BlockMeta:
    def __init__(self, block_id: BlockID, header: Header):
        self.block_id = block_id
        self.header = header

    @classmethod
    def from_block(cls, block, part_set) -> "BlockMeta":
        return cls(BlockID(block.hash(), part_set.header()), block.header)

    def to_json(self):
        return {"block_id": self.block_id.to_json(), "header": self.header.to_json()}

    @classmethod
    def from_json(cls, obj) -> "BlockMeta":
        return cls(BlockID.from_json(obj["block_id"]), Header.from_json(obj["header"]))
