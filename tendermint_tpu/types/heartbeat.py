"""Heartbeat: signed liveness message a proposer broadcasts while waiting
for transactions in no-empty-blocks mode (reference: types/heartbeat.go,
fired from consensus/state.go:818)."""

from __future__ import annotations

from dataclasses import dataclass, replace

from tendermint_tpu.codec.canonical import canonical_dumps
from tendermint_tpu.crypto.keys import SignatureEd25519, signature_from_json


@dataclass(frozen=True)
class Heartbeat:
    validator_address: bytes
    validator_index: int
    height: int
    round_: int
    sequence: int
    signature: SignatureEd25519 | None = None

    def canonical(self) -> dict:
        """CanonicalJSONHeartbeat (types/canonical_json.go:35-41)."""
        return {
            "height": self.height,
            "round": self.round_,
            "sequence": self.sequence,
            "validator_address": self.validator_address,
            "validator_index": self.validator_index,
        }

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical_dumps({"chain_id": chain_id, "heartbeat": self.canonical()})

    def with_signature(self, sig: SignatureEd25519) -> "Heartbeat":
        return replace(self, signature=sig)

    def to_json(self):
        return {
            "validator_address": self.validator_address.hex().upper(),
            "validator_index": self.validator_index,
            "height": self.height,
            "round": self.round_,
            "sequence": self.sequence,
            "signature": self.signature.to_json() if self.signature else None,
        }

    @classmethod
    def from_json(cls, obj) -> "Heartbeat":
        from tendermint_tpu.codec import jsonval as jv

        return cls(
            jv.hex_field(obj, "validator_address"),
            jv.int_field(obj, "validator_index", 0, jv.MAX_INDEX),
            jv.int_field(obj, "height", 0, jv.MAX_HEIGHT),
            jv.int_field(obj, "round", 0, jv.MAX_ROUND),
            jv.int_field(obj, "sequence", 0, jv.MAX_ROUND),
            signature_from_json(obj["signature"]) if obj.get("signature") else None,
        )
