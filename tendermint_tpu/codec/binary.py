"""Deterministic c-style binary codec.

Format (matches the reference's wire-protocol spec,
docs/specification/wire-protocol.rst):

- fixed-width uints/ints: big-endian, 1/2/4/8 bytes
- `uvarint`: 1 length byte then that many big-endian bytes; 0 == b"\\x00"
- `varint`: like uvarint; negative sets the MSB of the length byte
- bytes/string: varint length prefix + raw bytes
- time: int64 nanoseconds since epoch, fixed 8 bytes
- lists: varint count + concatenated items
- interfaces/unions: 1 type byte + concrete encoding (0x00 == nil)
"""

from __future__ import annotations

import struct


def encode_uvarint(n: int) -> bytes:
    if n < 0:
        raise ValueError("uvarint must be non-negative")
    if n == 0:
        return b"\x00"
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    if len(body) > 255:
        raise ValueError("uvarint too large")
    return bytes([len(body)]) + body


def encode_varint(n: int) -> bytes:
    if n == 0:
        return b"\x00"
    neg = n < 0
    body = abs(n).to_bytes((abs(n).bit_length() + 7) // 8, "big")
    if len(body) > 127:
        raise ValueError("varint too large")
    return bytes([len(body) | (0x80 if neg else 0)]) + body


def encode_bytes(b: bytes) -> bytes:
    return encode_varint(len(b)) + b


def encode_string(s: str) -> bytes:
    return encode_bytes(s.encode("utf-8"))


def decode_bytes(buf: bytes, off: int = 0) -> tuple[bytes, int]:
    d = Decoder(buf, off)
    out = d.read_bytes()
    return out, d.off


class Encoder:
    """Accumulating encoder; all writes are deterministic."""

    def __init__(self):
        self._parts: list[bytes] = []

    def buf(self) -> bytes:
        return b"".join(self._parts)

    def write_raw(self, b: bytes) -> "Encoder":
        self._parts.append(b)
        return self

    def write_u8(self, n: int) -> "Encoder":
        return self.write_raw(struct.pack(">B", n))

    def write_u16(self, n: int) -> "Encoder":
        return self.write_raw(struct.pack(">H", n))

    def write_u32(self, n: int) -> "Encoder":
        return self.write_raw(struct.pack(">I", n))

    def write_u64(self, n: int) -> "Encoder":
        return self.write_raw(struct.pack(">Q", n))

    def write_i64(self, n: int) -> "Encoder":
        return self.write_raw(struct.pack(">q", n))

    def write_uvarint(self, n: int) -> "Encoder":
        return self.write_raw(encode_uvarint(n))

    def write_varint(self, n: int) -> "Encoder":
        return self.write_raw(encode_varint(n))

    def write_bytes(self, b: bytes) -> "Encoder":
        return self.write_raw(encode_bytes(b))

    def write_string(self, s: str) -> "Encoder":
        return self.write_raw(encode_string(s))

    def write_time_ns(self, ns: int) -> "Encoder":
        return self.write_i64(ns)

    def write_list(self, items, write_item) -> "Encoder":
        self.write_varint(len(items))
        for it in items:
            write_item(self, it)
        return self


class Decoder:
    def __init__(self, buf: bytes, off: int = 0):
        self.buf = buf
        self.off = off

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.buf):
            raise ValueError("unexpected end of buffer")
        out = self.buf[self.off : self.off + n]
        self.off += n
        return out

    def read_u8(self) -> int:
        return self._take(1)[0]

    def peek_u8(self) -> int:
        """The next byte without consuming it — format-tag dispatch
        (types/agg_commit.decode_commit reads the aggregate-commit
        magic off it)."""
        if self.off >= len(self.buf):
            raise ValueError("unexpected end of buffer")
        return self.buf[self.off]

    def read_raw(self, n: int) -> bytes:
        """Exactly n bytes, no length prefix — the mirror of
        Encoder.write_raw for fixed-width fields (32-byte points,
        folded scalars)."""
        return self._take(n)

    def read_u16(self) -> int:
        return struct.unpack(">H", self._take(2))[0]

    def read_u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def read_u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def read_i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def read_uvarint(self) -> int:
        ln = self.read_u8()
        if ln == 0:
            return 0
        body = self._take(ln)
        if body[0] == 0:
            raise ValueError("non-canonical uvarint (leading zero byte)")
        return int.from_bytes(body, "big")

    def read_varint(self) -> int:
        ln = self.read_u8()
        if ln == 0:
            return 0
        neg = bool(ln & 0x80)
        nbytes = ln & 0x7F
        if nbytes == 0:
            raise ValueError("non-canonical varint (negative zero)")
        body = self._take(nbytes)
        if body[0] == 0:
            raise ValueError("non-canonical varint (leading zero byte)")
        n = int.from_bytes(body, "big")
        return -n if neg else n

    def read_bytes(self) -> bytes:
        ln = self.read_varint()
        if ln < 0:
            raise ValueError("negative byte-slice length")
        return self._take(ln)

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_time_ns(self) -> int:
        return self.read_i64()

    def read_list(self, read_item) -> list:
        n = self.read_varint()
        if n < 0:
            raise ValueError("negative list length")
        return [read_item(self) for _ in range(n)]

    def done(self) -> bool:
        return self.off == len(self.buf)
