"""Canonical JSON — the deterministic sign-bytes encoding.

Mirrors the reference's format (types/canonical_json.go + the "Vote Sign
Bytes" example in docs/specification/block-structure.rst): compact
separators, keys in alphabetical order, byte slices as UPPERCASE hex
strings, and signed payloads wrapped with the chain id:

    {"chain_id":"my_chain","vote":{"block_id":{...},"height":1,...}}

The types build plain dicts; `canonical_dumps` sorts keys recursively so
field declaration order can never leak into signatures.
"""

from __future__ import annotations

import json
from typing import Any


def _canonicalize(obj: Any) -> Any:
    if isinstance(obj, (bytes, bytearray)):
        return bytes(obj).hex().upper()
    if isinstance(obj, dict):
        return {k: _canonicalize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_canonicalize(v) for v in obj]
    if isinstance(obj, float):
        raise TypeError("floats are not permitted in canonical JSON")
    return obj


def canonical_dumps(obj: Any) -> bytes:
    return json.dumps(
        _canonicalize(obj),
        sort_keys=True,
        separators=(",", ":"),
        ensure_ascii=True,
    ).encode("utf-8")


def sign_bytes(chain_id: str, key: str, payload: Any) -> bytes:
    """SignBytes(chainID, o) equivalent (reference types/signable.go:13-30):
    wrap the canonical payload under its message-kind key with the chain id."""
    return canonical_dumps({"chain_id": chain_id, key: payload})
