"""Deterministic serialization: the go-wire equivalent (SURVEY.md 2.2).

Two codecs, both byte-deterministic:
- `binary`: c-style binary per the reference's wire-protocol spec
  (docs/specification/wire-protocol.rst): big-endian fixed ints,
  length-of-length varints, length-prefixed bytes, structs as concatenated
  fields, interfaces as type byte + payload.
- `canonical`: the canonical-JSON sign-bytes format (alphabetical keys,
  uppercase-hex bytes, compact separators; reference
  types/canonical_json.go + docs block-structure.rst "Vote Sign Bytes").

Everything that is signed or hashed in this framework goes through one of
these, so the CPU and TPU planes agree byte-for-byte.
"""

from tendermint_tpu.codec.binary import (
    Decoder,
    Encoder,
    decode_bytes,
    encode_bytes,
    encode_string,
    encode_uvarint,
    encode_varint,
)
from tendermint_tpu.codec.canonical import canonical_dumps

__all__ = [
    "Encoder",
    "Decoder",
    "encode_bytes",
    "encode_string",
    "encode_uvarint",
    "encode_varint",
    "decode_bytes",
    "canonical_dumps",
]
