"""Bounded JSON field decoding for wire-facing types.

Everything decoded from a peer (consensus messages and the types nested
inside them: Vote, Proposal, Part, BlockID, Heartbeat...) is attacker
input. go-wire gave the reference typed, size-capped decoding for free
(wire.ReadBinary with byte-length limits); this module is that contract
for the JSON codec: every scalar is type- and range-checked, and any
violation raises ValueError — which the p2p receive paths treat as a
peer error (disconnect), never as a crash or an unbounded allocation.

The same from_json paths also decode our own WAL and RPC data, so the
bounds are generous protocol-level maxima, not policy limits: heights
up to 2^62, 2^20 validators/parts, 64-byte hashes.
"""

from __future__ import annotations

MAX_HEIGHT = 1 << 62
MAX_ROUND = 1 << 31
MAX_INDEX = 1 << 20  # validator / part indices and counts
MAX_HASH_BYTES = 64


def int_field(o, key, lo: int, hi: int) -> int:
    v = o.get(key) if isinstance(o, dict) else None
    if type(v) is not int or not (lo <= v <= hi):  # type() also rejects bool
        raise ValueError(f"bad {key!r}: {v!r}")
    return v


def hex_field(o, key, max_bytes: int = MAX_HASH_BYTES) -> bytes:
    v = o.get(key) if isinstance(o, dict) else None
    if not isinstance(v, str) or len(v) > 2 * max_bytes:
        raise ValueError(f"bad {key!r}")
    try:
        return bytes.fromhex(v)
    except ValueError as exc:
        raise ValueError(f"bad {key!r}: not hex") from exc


def dict_field(o, key) -> dict:
    v = o.get(key) if isinstance(o, dict) else None
    if not isinstance(v, dict):
        raise ValueError(f"bad {key!r}")
    return v


MAX_TX_BYTES = 1 << 22  # 4 MB, above any block-size policy
MAX_STR = 1 << 10
MAX_TIME_NS = 1 << 62  # ~year 2116 in unix nanoseconds


def require_dict(o) -> dict:
    """Entry guard for every wire-facing from_json: a peer sending a
    list/scalar where an object belongs must produce ValueError (-> peer
    disconnect), never a TypeError escaping into a reactor thread."""
    if not isinstance(o, dict):
        raise ValueError(f"expected object, got {type(o).__name__}")
    return o


def list_field(o, key, max_len: int) -> list:
    v = o.get(key) if isinstance(o, dict) else None
    if not isinstance(v, list) or len(v) > max_len:
        raise ValueError(f"bad {key!r}")
    return v


def str_field(o, key, max_len: int = MAX_STR) -> str:
    v = o.get(key) if isinstance(o, dict) else None
    if not isinstance(v, str) or len(v) > max_len:
        raise ValueError(f"bad {key!r}")
    return v
