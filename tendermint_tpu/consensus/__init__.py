from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.consensus.ticker import MockTicker, TimeoutTicker
from tendermint_tpu.consensus.wal import WAL

__all__ = ["ConsensusState", "TimeoutTicker", "MockTicker", "WAL"]
