"""Crash recovery (reference: consensus/replay.go).

Two tiers, run in order on node start (SURVEY.md §3.5):
1. ABCI handshake (Handshaker): query the app's (height, hash) via Info,
   then replay committed blocks from the store until app, state, and
   store agree — including the delicate "committed to app but state not
   saved" case, replayed against a mock app built from the saved
   ABCIResponses so the real app never sees Commit twice
   (consensus/replay.go:180-403).
2. WAL catchup (catchup_replay): feed every WAL line since the last
   #ENDHEIGHT marker back through the consensus state machine; the
   priv-validator's double-sign guard makes re-signing idempotent
   (consensus/replay.go:98-148).

Pipelined execution (round 14, docs/execution-pipeline.md): replay is
SERIAL by contract — cs.replay_mode forces the inline finalize path, so
the WAL's single-thread total order is reproduced exactly. The pipeline
also widens the legal crash images this module must absorb: the WAL
``#ENDHEIGHT: H`` marker is written BEFORE the deferred apply of H runs,
so a crash leaves store=H, state=H-1, app=H-1 with the marker (and even
H+1 messages) on disk. That is the handshake's store==state+1 /
app==state case — `_apply_final_block` replays block H against the real
app — and catchup then resumes from the surviving marker as ever; no new
machinery, proven end to end by tests/test_wal_torture.py's
pipeline-stage crash cycles.
"""

from __future__ import annotations

import logging

from tendermint_tpu.abci.types import Application, ResponseCommit, ResponseDeliverTx
from tendermint_tpu.consensus.wal import decode_wal_line
from tendermint_tpu.state import execution as sm
from tendermint_tpu.types.services import MockMempool

logger = logging.getLogger("consensus.replay")


# -- tier 2: WAL catchup ------------------------------------------------------


def catchup_replay(cs, cs_height: int) -> None:
    """Replay WAL lines since the last height boundary through `cs`
    (consensus/replay.go:98-148). Call before the receive routine starts."""
    lines = cs.wal.lines_after_height(cs_height - 1)
    if lines is None:
        # The exact boundary can be legitimately gone after a tail repair
        # (a torn `#ENDHEIGHT: h` write is cut by wal.py's repair pass).
        # Fall back to the last surviving marker: the extra lines replayed
        # belong to heights <= cs_height-1, which the state machine drops
        # (wrong height) or the privval double-sign guard makes idempotent
        # — strictly more live than the reference's panic, and safe
        # (docs/crash-recovery.md "Repair semantics").
        fallback = cs.wal.lines_after_last_marker()
        if fallback is not None and fallback[0] < cs_height - 1:
            logger.warning(
                "WAL missing #ENDHEIGHT %d (tail repair?); replaying from "
                "surviving #ENDHEIGHT %d", cs_height - 1, fallback[0],
            )
            lines = fallback[1]
        elif cs_height > 1:
            raise RuntimeError(
                f"WAL has no #ENDHEIGHT for height {cs_height - 1}; cannot replay"
            )
        else:
            return  # fresh chain, nothing to replay
    replayed = 0
    cs.replay_mode = True
    try:
        for i, line in enumerate(lines):
            try:
                entry = decode_wal_line(line)
            except Exception as e:
                if i == len(lines) - 1:
                    # a truncated/corrupt FINAL line is the expected residue
                    # of a crash mid-write; everything before it replayed
                    logger.warning("skipping corrupt WAL tail line: %s", e)
                    break
                raise RuntimeError(
                    f"corrupt WAL line {i} (not at tail): {e}"
                ) from e
            if entry is None:
                continue
            kind = entry[0]
            if kind == "endheight":
                # a later ENDHEIGHT means this height completed; stop
                if entry[1] >= cs_height:
                    break
                continue
            if kind == "event":
                continue  # step markers are for sanity only
            if kind == "msg_info":
                from tendermint_tpu.consensus.state import MsgInfo

                _, msg, peer_id = entry
                cs.handle_msg(MsgInfo(msg, peer_id))
            elif kind == "timeout":
                cs.handle_timeout(entry[1])
            replayed += 1
    finally:
        cs.replay_mode = False
    logger.info("replayed %d WAL messages for height %d", replayed, cs_height)


# -- tier 1: ABCI handshake ---------------------------------------------------


class HandshakeError(Exception):
    pass


class Handshaker:
    def __init__(self, state, store):
        self.state = state
        self.store = store
        self.n_blocks = 0  # blocks applied to the app (for tests)

    def handshake(self, proxy_app) -> None:
        """consensus/replay.go:194-226. proxy_app: AppConns."""
        res = proxy_app.query().info_sync()
        app_block_height = res.last_block_height
        app_hash = res.last_block_app_hash
        logger.info(
            "ABCI handshake: app height %d hash %s", app_block_height, app_hash.hex()[:12]
        )
        app_hash = self.replay_blocks(app_hash, app_block_height, proxy_app)
        self.state.app_hash = app_hash

    def replay_blocks(self, app_hash: bytes, app_block_height: int, proxy_app) -> bytes:
        """The (storeH, stateH, appH) case analysis
        (consensus/replay.go:230-301)."""
        store_height = self.store.height()
        state_height = self.state.last_block_height
        logger.info(
            "replay_blocks: store %d state %d app %d",
            store_height, state_height, app_block_height,
        )

        if app_block_height == 0:
            # fresh app: play genesis validators via InitChain
            from tendermint_tpu.types.protobuf import tm2pb_validators

            validators = tm2pb_validators(self.state.genesis_doc.validators)
            proxy_app.consensus().init_chain_sync(validators)

        if store_height == 0:
            return app_hash

        if store_height < state_height:
            raise HandshakeError(f"store height {store_height} < state height {state_height}")
        if store_height > state_height + 1:
            raise HandshakeError(
                f"store height {store_height} > state height {state_height}+1"
            )

        if store_height == state_height:
            # chain and state agree; bring the app up to them
            if app_block_height < store_height:
                return self._replay_through_app(app_block_height, store_height, proxy_app, False)
            if app_block_height == store_height:
                return app_hash
            raise HandshakeError(
                f"app height {app_block_height} > store height {store_height}"
            )

        # store == state + 1: we crashed between SaveBlock and state.save
        if app_block_height < state_height:
            # app even further behind: replay up to state height, then the
            # final block with the real app
            app_hash = self._replay_through_app(app_block_height, store_height, proxy_app, True)
            return app_hash
        if app_block_height == state_height:
            # app committed through the state height; apply the last block
            # fully (updates state) with the real app
            return self._apply_final_block(proxy_app)
        if app_block_height == store_height:
            # app already has the last block but our state doesn't: replay
            # it against a mock app fed the saved ABCIResponses, so the
            # real app never re-executes (consensus/replay.go:280-295)
            responses = self.state.load_abci_responses()
            if responses is None:
                raise HandshakeError("missing saved ABCIResponses for final block replay")
            mock_conn = _mock_proxy_conn(responses, app_hash)
            self._apply_block(mock_conn, store_height)
            return app_hash
        raise HandshakeError(f"unexpected app height {app_block_height}")

    def _replay_through_app(
        self, app_block_height: int, store_height: int, proxy_app, mutate_state: bool
    ) -> bytes:
        """Replay blocks appH+1..storeH against the real app without state
        mutation, except possibly the final one (consensus/replay.go:303-337)."""
        app_hash = b""
        final_block = store_height if not mutate_state else store_height - 1
        for h in range(app_block_height + 1, final_block + 1):
            logger.info("applying block %d to the app", h)
            block = self.store.load_block(h)
            app_hash = sm.exec_commit_block(proxy_app.consensus(), block)
            self.n_blocks += 1
        if mutate_state:
            # final block gets the full ApplyBlock treatment
            return self._apply_final_block(proxy_app)
        return app_hash

    def _apply_final_block(self, proxy_app) -> bytes:
        return self._apply_block(proxy_app.consensus(), self.store.height())

    def _apply_block(self, consensus_conn, height: int) -> bytes:
        block = self.store.load_block(height)
        meta = self.store.load_block_meta(height)
        event_cache = _NullCache()
        sm.apply_block(
            self.state, event_cache, consensus_conn, block,
            meta.block_id.parts_header, MockMempool(),
        )
        self.n_blocks += 1
        return self.state.app_hash


class _NullCache:
    def fire_event(self, event, data):
        pass

    def flush(self):
        pass


# -- mock app built from saved ABCIResponses ---------------------------------


class _MockReplayApp(Application):
    """Replays recorded DeliverTx/Commit results (consensus/replay.go:367-403)."""

    def __init__(self, responses, app_hash: bytes):
        self._responses = responses
        self._app_hash = app_hash
        self._tx_index = 0

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        r = self._responses.deliver_tx[self._tx_index]
        self._tx_index += 1
        return r or ResponseDeliverTx()

    def end_block(self, height: int):
        return self._responses.end_block

    def commit(self) -> ResponseCommit:
        return ResponseCommit(data=self._app_hash)


def _mock_proxy_conn(responses, app_hash: bytes):
    from tendermint_tpu.abci.client import LocalClient
    from tendermint_tpu.proxy.app_conn import AppConnConsensus

    return AppConnConsensus(LocalClient(_MockReplayApp(responses, app_hash)))
