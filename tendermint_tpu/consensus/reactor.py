"""Consensus gossip reactor (reference: consensus/reactor.go).

Four p2p channels (reactor.go:21-24):
  0x20 STATE       — NewRoundStep / CommitStep / HasVote / ProposalHeartbeat
  0x21 DATA        — Proposal / ProposalPOL / BlockPart
  0x22 VOTE        — Vote
  0x23 VOTE_SET_BITS — VoteSetMaj23 / VoteSetBits

Each peer gets a mirrored PeerRoundState and three gossip threads
(reactor.go:133-135): gossip_data (block parts + catch-up), gossip_votes
(needed-vote picker), query_maj23. Step transitions and new votes are
broadcast event-driven via the event switch (reactor.go:321-337).
"""

from __future__ import annotations

import json
import random
import threading
import time

from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus.round_state import RoundStep
from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn import ChannelDescriptor
from tendermint_tpu.p2p.switch import Reactor
from tendermint_tpu.types import events as tev
from tendermint_tpu.types.agg_commit import AggregateLastCommit, commit_is_aggregate
from tendermint_tpu.types.validator_set import CommitError
from tendermint_tpu.types.block_id import BlockID, PartSetHeader
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

PEER_GOSSIP_SLEEP = 0.1  # reactor.go peerGossipSleepDuration
PEER_QUERY_MAJ23_SLEEP = 2.0
# lazy-relay hold (round 20, gossip_dedup): a vote we RECEIVED moments
# ago is being fanned out by its origin right now, and every recipient
# announces it via HasVote within the same window — re-pushing it
# immediately is how k relayers race each other into the 2NxN
# redundancy. One gossip tick is enough for those announcements to set
# the mirror bit (ms on loopback, ~one link RTT under WAN); after it,
# anything still unmarked is genuinely needed and relays normally.
VOTE_RELAY_DELAY = PEER_GOSSIP_SLEEP
# RTT-adaptive hold (round 21): the window that lets HasVote
# announcements win the relay race is ~one link RTT — on a fast LAN the
# 0.1 s constant over-holds (announcements land in ms), under a slow WAN
# it under-holds (re-pushes fire before the announcement arrives). When
# ping RTT samples exist (the p2p ping_rtt EWMA), the hold tracks 2x the
# smoothed RTT (ping->pong is a full round trip; the announcement needs
# one leg each way too), clamped to [0.5x, 4x] of the constant so a
# garbage sample can neither disable the hold nor stall relays. The
# constant remains the exact no-sample fallback.
VOTE_RELAY_DELAY_MIN = 0.5 * VOTE_RELAY_DELAY
VOTE_RELAY_DELAY_MAX = 4.0 * VOTE_RELAY_DELAY

PEER_STATE_KEY = "ConsensusReactor.peerState"


def adaptive_relay_delay(rtt_s: float | None) -> float:
    """The lazy-relay hold for a smoothed peer RTT: None (no samples
    yet) keeps the VOTE_RELAY_DELAY constant; otherwise 2x the RTT
    clamped into [VOTE_RELAY_DELAY_MIN, VOTE_RELAY_DELAY_MAX]."""
    if rtt_s is None:
        return VOTE_RELAY_DELAY
    return min(VOTE_RELAY_DELAY_MAX, max(VOTE_RELAY_DELAY_MIN, 2.0 * rtt_s))


def _enc(msg) -> bytes:
    return json.dumps(msgs.msg_to_json(msg), sort_keys=True).encode()


def _dec(raw: bytes):
    return msgs.msg_from_json(json.loads(raw.decode()))


class PeerRoundState:
    """What we believe the peer's consensus state is (reactor.go:757-773)."""

    def __init__(self):
        self.height = 0
        self.round_ = -1
        self.step = RoundStep.NEW_HEIGHT
        self.start_time = 0.0
        self.proposal = False
        self.proposal_block_parts_header: PartSetHeader | None = None
        self.proposal_block_parts: BitArray | None = None
        self.proposal_pol_round = -1
        self.proposal_pol: BitArray | None = None
        self.prevotes: BitArray | None = None
        self.precommits: BitArray | None = None
        self.last_commit_round = -1
        self.last_commit: BitArray | None = None
        self.catchup_commit_round = -1
        self.catchup_commit: BitArray | None = None


def _peer_label(peer) -> str:
    """Best-effort peer id for metric labels ("?" for harness stubs)."""
    try:
        return peer.id()
    except Exception:  # noqa: BLE001 — labels must never break gossip
        return "?"


class PeerState:
    """Thread-safe mirror + vote bookkeeping for one peer
    (reactor.go:778-1060)."""

    def __init__(self, peer):
        self.peer = peer
        self.prs = PeerRoundState()
        self._mtx = threading.RLock()
        # per-peer gossip instrumentation (round 15): child series
        # resolved once — picks vs successful sends is the signal that
        # would have caught the PR-13 pick-marks-before-send wedge
        from tendermint_tpu.p2p.telemetry import peer_metrics

        fams = peer_metrics(getattr(peer, "metrics_registry", None))
        pid = _peer_label(peer)
        self.m_vote_picks = fams["vote_gossip_picks"].labels(peer=pid)
        self.m_vote_sends = fams["vote_gossip_sends"].labels(peer=pid)
        self.m_vote_send_failures = fams["vote_gossip_send_failures"].labels(
            peer=pid
        )
        self.m_catchup_commits = fams["catchup_commits"].labels(peer=pid)
        # aggregate catchup (round 22): one whole-commit send per lagging
        # height, re-armed after a hold so a lost frame can't wedge the
        # peer — (height, monotonic send time) of the last send
        self._agg_commit_sent: tuple[int, float] | None = None

    # -- reads -------------------------------------------------------------

    def get_round_state(self) -> PeerRoundState:
        with self._mtx:
            import copy

            return copy.copy(self.prs)

    def get_height(self) -> int:
        with self._mtx:
            return self.prs.height

    # -- proposal/parts ----------------------------------------------------

    def set_has_proposal(self, proposal) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != proposal.height or prs.round_ != proposal.round_:
                return
            if prs.proposal:
                return
            prs.proposal = True
            prs.proposal_block_parts_header = proposal.block_parts_header
            prs.proposal_block_parts = BitArray(proposal.block_parts_header.total)
            prs.proposal_pol_round = proposal.pol_round
            prs.proposal_pol = None  # until ProposalPOLMessage arrives

    def set_has_proposal_block_part(self, height: int, round_: int, index: int) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != height or prs.round_ != round_:
                return
            if prs.proposal_block_parts is None:
                return
            prs.proposal_block_parts.set_index(index, True)

    def apply_proposal_pol(self, msg: msgs.ProposalPOLMessage) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != msg.height or prs.proposal_pol_round != msg.proposal_pol_round:
                return
            prs.proposal_pol = msg.proposal_pol

    # -- votes -------------------------------------------------------------

    def set_has_vote(self, height: int, round_: int, type_: int, index: int) -> bool:
        """Mark the peer as holding a vote. Returns True when a tracking
        array existed and the bit landed — False means the coordinates
        matched no array (wrong height/round for this mirror) and the
        information was dropped."""
        with self._mtx:
            ba = self._get_vote_bit_array(height, round_, type_)
            if ba is not None:
                ba.set_index(index, True)
                return True
            return False

    def _get_vote_bit_array(self, height: int, round_: int, type_: int) -> BitArray | None:
        """reactor.go:813-850 — except the round-equal branch must not
        SHADOW the catchup branch with a None: for a peer lagging far
        behind, nothing ever ensures bit arrays at the PEER's height
        (gossip ensures them at OUR heights), so prs.precommits is None
        there and the stored-commit catchup picker would never find a
        tracking array — the round-4 chaos-soak stall."""
        prs = self.prs
        if prs.height == height:
            if prs.round_ == round_:
                ba = prs.prevotes if type_ == VOTE_TYPE_PREVOTE else prs.precommits
                if ba is not None:
                    return ba
            if prs.catchup_commit_round == round_ and type_ == VOTE_TYPE_PRECOMMIT:
                return prs.catchup_commit
            if prs.proposal_pol_round == round_ and type_ == VOTE_TYPE_PREVOTE:
                return prs.proposal_pol
            return None
        if prs.height == height + 1 and prs.last_commit_round == round_ and \
           type_ == VOTE_TYPE_PRECOMMIT:
            return prs.last_commit
        return None

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height == height:
                if prs.prevotes is None:
                    prs.prevotes = BitArray(num_validators)
                if prs.precommits is None:
                    prs.precommits = BitArray(num_validators)
                if prs.proposal_pol is None and prs.proposal_pol_round >= 0:
                    prs.proposal_pol = BitArray(num_validators)
                if prs.catchup_commit is None and prs.catchup_commit_round >= 0:
                    prs.catchup_commit = BitArray(num_validators)
            elif prs.height == height + 1:
                if prs.last_commit is None:
                    prs.last_commit = BitArray(num_validators)

    def ensure_catchup_commit_round(self, height: int, round_: int, num_validators: int) -> None:
        """reactor.go:855-873."""
        with self._mtx:
            prs = self.prs
            if prs.height != height or round_ < 0:
                return
            if prs.catchup_commit_round == round_:
                return
            prs.catchup_commit_round = round_
            self.m_catchup_commits.inc()
            # alias the live precommit array only when it EXISTS; a
            # far-behind peer's mirror has none at its own height, and
            # aliasing None here left the catchup picker with no
            # tracking array at all (it must be a fresh BitArray then)
            prs.catchup_commit = (
                prs.precommits
                if prs.round_ == round_ and prs.precommits is not None
                else BitArray(num_validators)
            )

    def pick_vote_to_send(self, vote_set) -> object | None:
        """A random vote the peer needs from `vote_set` (reactor.go:899-933).

        Does NOT mark the peer as having it — the caller marks via
        set_has_vote only AFTER peer.send succeeds (reactor.go's
        PickSendVote order). Marking at pick time meant a vote whose
        send failed on a full channel queue (exactly the burst-load
        moment) was skipped for that peer FOREVER — no other mechanism
        resends it, and a 2-2 height split could wedge the whole net
        (the netchaos smoke's stall signature)."""
        if vote_set is None or vote_set.size() == 0:
            return None
        with self._mtx:
            ps_bits = self._get_vote_bit_array(
                vote_set.height, vote_set.round_, vote_set.type_
            )
            if ps_bits is None:
                return None
            needed = vote_set.bit_array().sub(ps_bits)
            if needed.is_empty():
                return None
            index, ok = needed.pick_random()
            if not ok:
                return None
            return vote_set.get_by_index(index)

    def agg_commit_due(self, height: int, hold: float = 1.0) -> bool:
        """Whether the aggregate catchup commit for `height` should be
        (re)sent to this peer: never sent, sent for another height, or
        sent over `hold` seconds ago with the peer still stuck there."""
        with self._mtx:
            sent = self._agg_commit_sent
            if sent is None or sent[0] != height:
                return True
            return time.monotonic() - sent[1] >= hold

    def mark_agg_commit_sent(self, height: int) -> None:
        with self._mtx:
            self._agg_commit_sent = (height, time.monotonic())

    # -- step transitions --------------------------------------------------

    def apply_new_round_step(self, msg: msgs.NewRoundStepMessage) -> None:
        """reactor.go:1046-1090."""
        with self._mtx:
            prs = self.prs
            psheight, psround, psstep = prs.height, prs.round_, prs.step
            # stale/duplicate guard (reactor.go:1050-1053): a reordered or
            # replayed step message must never move peer state backwards —
            # without this, an attacker replaying an old NewRoundStep wipes
            # the vote bit-arrays we track for the peer
            if (msg.height, msg.round_, msg.step) <= (psheight, psround, int(psstep)):
                return
            ps_catchup_round = prs.catchup_commit_round
            ps_catchup = prs.catchup_commit

            ps_precommits = prs.precommits  # before the reset below

            prs.height = msg.height
            prs.round_ = msg.round_
            prs.step = msg.step
            prs.start_time = time.time() - msg.seconds_since_start_time
            if psheight != msg.height or psround != msg.round_:
                prs.proposal = False
                prs.proposal_block_parts_header = None
                prs.proposal_block_parts = None
                prs.proposal_pol_round = -1
                prs.proposal_pol = None
                prs.prevotes = None
                prs.precommits = None
            if psheight == msg.height and psround != msg.round_ and \
               msg.round_ == ps_catchup_round:
                prs.precommits = ps_catchup
            if psheight != msg.height:
                # shift the H-precommits the peer had to last_commit
                if psheight + 1 == msg.height and psround == msg.last_commit_round:
                    prs.last_commit_round = msg.last_commit_round
                    prs.last_commit = ps_precommits
                else:
                    prs.last_commit_round = msg.last_commit_round
                    prs.last_commit = None
                prs.catchup_commit_round = -1
                prs.catchup_commit = None

    def apply_commit_step(self, msg: msgs.CommitStepMessage) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != msg.height:
                return
            prs.proposal_block_parts_header = msg.block_parts_header
            prs.proposal_block_parts = msg.block_parts

    def apply_has_vote(self, msg: msgs.HasVoteMessage,
                       allow_last_commit: bool = False) -> bool:
        """Feed a HasVote announcement into the mirror. The strict gate
        (peer height only) is the pre-round-20 behavior; with
        allow_last_commit (the gossip_dedup knob) a HasVote for the
        height BELOW the peer's also lands — _get_vote_bit_array routes
        it to the last_commit array, which is exactly the height a node
        keeps broadcasting HasVotes for right after committing (those
        announcements were silently dropped before, so the laggard's
        commit votes kept being re-pushed by everyone)."""
        with self._mtx:
            if self.prs.height != msg.height and not (
                allow_last_commit and self.prs.height == msg.height + 1
            ):
                return False
        return self.set_has_vote(msg.height, msg.round_, msg.type_, msg.index)

    def apply_vote_set_bits(self, msg: msgs.VoteSetBitsMessage, our_votes: BitArray | None) -> None:
        """reactor.go:1126-1149. ourVotes is a MASK of what we know we
        hold for that BlockID: keep the peer-bits that aren't ours, OR in
        the peer's report, and REPLACE — never mark the peer as having
        votes only we hold."""
        with self._mtx:
            ba = self._get_vote_bit_array(msg.height, msg.round_, msg.type_)
            if ba is None:
                return
            if our_votes is not None:
                ba.update(ba.sub(our_votes).or_(msg.votes))
            else:
                ba.update(msg.votes)


class ConsensusReactor(Reactor, BaseService):
    def __init__(self, consensus_state, fast_sync: bool = False):
        BaseService.__init__(self, name="consensus.reactor")
        self.con_s = consensus_state
        self.fast_sync = fast_sync
        self.evsw = None
        self._peer_threads: dict[str, list] = {}
        self._peer_stops: dict[str, threading.Event] = {}
        self._mtx = threading.Lock()
        # has-vote-aware gossip dedup (round 20): when on, STATE-channel
        # HasVotes ensure the tracking arrays before applying (a fresh
        # height's first announcement window was silently dropped
        # before), last-commit-height HasVotes land, local part adds
        # broadcast HasBlockPart screens, and the vote pick loops hold
        # re-pushes of just-received votes for one gossip tick so the
        # announcements can set the mirror bits first (_relay_ready).
        # Off restores the pre-round-20 gossip for the before/after
        # bench.
        self.gossip_dedup = bool(
            getattr(consensus_state.config, "gossip_dedup", True)
        )
        # flat dedup accounting (consensus_gossip_* on both surfaces)
        self.has_votes_applied = 0
        self.part_announces_sent = 0
        self.part_announces_applied = 0
        # aggregate-format catchup accounting (round 22, docs/upgrade.md)
        self.agg_commits_sent = 0      # whole-commit catchup sends
        self.agg_commits_rejected = 0  # forged/sub-quorum screened out

    # -- wiring ------------------------------------------------------------

    def set_event_switch(self, evsw) -> None:
        """Subscribe broadcast triggers (reactor.go:321-337)."""
        self.evsw = evsw
        evsw.add_listener_for_event(
            "conR", tev.EVENT_NEW_ROUND_STEP, lambda _d: self._broadcast_step()
        )
        evsw.add_listener_for_event(
            "conR", tev.EVENT_VOTE, lambda d: self._broadcast_has_vote(d.vote)
        )
        evsw.add_listener_for_event(
            "conR",
            tev.EVENT_PROPOSAL_BLOCK_PART,
            lambda d: self._broadcast_has_part(d),
        )
        evsw.add_listener_for_event(
            "conR",
            tev.EVENT_PROPOSAL_HEARTBEAT,
            lambda d: self._broadcast_heartbeat(d.heartbeat),
        )

    # -- Reactor interface -------------------------------------------------

    def get_channels(self) -> list[ChannelDescriptor]:
        from tendermint_tpu.types.params import MAX_BLOCK_PART_SIZE_BYTES

        # recv_message_capacity right-sized per channel (round 18): the
        # default 21 MiB is the BLOCK ceiling — on the consensus
        # channels the largest legal messages are a block part at the
        # params-validated MAX_BLOCK_PART_SIZE_BYTES bound (hex-doubled
        # + proof inside JSON — the DATA cap derives from that bound)
        # and sub-KiB steps/votes/bitarrays. Before this, an
        # oversized-frame peer could park 21 MiB of never-delivered
        # reassembly bytes on EVERY channel of every connection
        # (~147 MiB per hostile peer); now an over-claim errors the
        # peer at the right-sized bound.
        return [
            ChannelDescriptor(id=STATE_CHANNEL, priority=5, send_queue_capacity=100,
                              recv_message_capacity=1 << 16),
            ChannelDescriptor(
                id=DATA_CHANNEL, priority=10, send_queue_capacity=100,
                recv_buffer_capacity=50 * 4096,
                # 2x for hex + proof steps / envelope headroom
                recv_message_capacity=2 * MAX_BLOCK_PART_SIZE_BYTES + (1 << 16),
            ),
            ChannelDescriptor(
                id=VOTE_CHANNEL, priority=5, send_queue_capacity=100,
                recv_buffer_capacity=100 * 100,
                recv_message_capacity=1 << 16,
            ),
            ChannelDescriptor(
                id=VOTE_SET_BITS_CHANNEL, priority=1, send_queue_capacity=2,
                recv_buffer_capacity=1024,
                recv_message_capacity=1 << 16,
            ),
        ]

    def add_peer(self, peer) -> None:
        ps = PeerState(peer)
        peer.set(PEER_STATE_KEY, ps)
        stop = threading.Event()
        threads = []
        for fn, nm in (
            (self._gossip_data_routine, "gossipData"),
            (self._gossip_votes_routine, "gossipVotes"),
            (self._query_maj23_routine, "queryMaj23"),
        ):
            t = threading.Thread(
                target=fn, args=(peer, ps, stop), daemon=True,
                name=f"conR.{nm}:{peer.id()[:8]}",
            )
            threads.append(t)
        with self._mtx:
            self._peer_stops[peer.id()] = stop
            self._peer_threads[peer.id()] = threads
        for t in threads:
            t.start()
        # tell the new peer our current state
        if not self.fast_sync:
            for m in self._round_step_messages():
                peer.send(STATE_CHANNEL, _enc(m))

    def remove_peer(self, peer, reason) -> None:
        with self._mtx:
            stop = self._peer_stops.pop(peer.id(), None)
            self._peer_threads.pop(peer.id(), None)
        if stop:
            stop.set()

    def receive(self, ch_id: int, peer, msg_bytes: bytes) -> None:
        """reactor.go:159-302."""
        if not self.is_running():
            return
        try:
            msg = _dec(msg_bytes)
        except (ValueError, KeyError, TypeError, UnicodeDecodeError) as exc:
            self.switch.stop_peer_for_error(peer, exc)
            return
        ps: PeerState | None = peer.get(PEER_STATE_KEY)
        if ps is None:
            return

        if ch_id == STATE_CHANNEL:
            if isinstance(msg, msgs.NewRoundStepMessage):
                ps.apply_new_round_step(msg)
            elif isinstance(msg, msgs.CommitStepMessage):
                ps.apply_commit_step(msg)
            elif isinstance(msg, msgs.HasVoteMessage):
                if self.gossip_dedup:
                    # ensure the tracking arrays BEFORE applying — at a
                    # fresh height the mirror has none yet, and every
                    # HasVote in that first window used to vanish into
                    # the set_has_vote no-op (the biggest single source
                    # of the 2NxN duplicate pushes: peers kept picking
                    # votes the neighbor had announced long ago)
                    rs = self.con_s.get_round_state()
                    size = rs.validators.size() if rs.validators else 0
                    last_size = rs.last_commit.size() if rs.last_commit else 0
                    ps.ensure_vote_bit_arrays(rs.height, size)
                    ps.ensure_vote_bit_arrays(rs.height - 1, last_size)
                if ps.apply_has_vote(msg, allow_last_commit=self.gossip_dedup):
                    self.has_votes_applied += 1
            elif isinstance(msg, msgs.HasBlockPartMessage):
                # round 20 part dedup screen: the peer announced a part
                # it holds — mark the mirror so gossip_data skips it
                # (applied regardless of our own knob: the information
                # is free and only ever REDUCES redundant sends)
                ps.set_has_proposal_block_part(msg.height, msg.round_, msg.index)
                self.part_announces_applied += 1
            elif isinstance(msg, msgs.ProposalHeartbeatMessage):
                self.con_s._fire(
                    tev.EVENT_PROPOSAL_HEARTBEAT,
                    tev.EventDataProposalHeartbeat(msg.heartbeat),
                )
            elif isinstance(msg, msgs.VoteSetMaj23Message):
                self._handle_vote_set_maj23(peer, ps, msg)
            else:
                self.switch.stop_peer_for_error(peer, f"bad state msg {type(msg)}")
        elif ch_id == DATA_CHANNEL:
            if self.fast_sync:
                return
            if isinstance(msg, msgs.ProposalMessage):
                ps.set_has_proposal(msg.proposal)
                self.con_s.add_peer_message(msg, peer.id())
            elif isinstance(msg, msgs.ProposalPOLMessage):
                ps.apply_proposal_pol(msg)
            elif isinstance(msg, msgs.BlockPartMessage):
                ps.set_has_proposal_block_part(msg.height, msg.round_, msg.part.index)
                self.con_s.add_peer_message(msg, peer.id())
            elif isinstance(msg, msgs.AggregateCommitMessage):
                if self._screen_agg_commit(peer, msg):
                    self.con_s.add_peer_message(msg, peer.id())
            else:
                self.switch.stop_peer_for_error(peer, f"bad data msg {type(msg)}")
        elif ch_id == VOTE_CHANNEL:
            if self.fast_sync:
                return
            if isinstance(msg, msgs.VoteMessage):
                rs = self.con_s.get_round_state()
                height = rs.height
                size = rs.validators.size() if rs.validators else 0
                # the height-1 array tracks LastCommit votes, whose set can
                # differ in size from the current one (reactor.go:291-296
                # uses cs.LastCommit.Size(), not cs.Validators.Size())
                last_size = rs.last_commit.size() if rs.last_commit else 0
                ps.ensure_vote_bit_arrays(height, size)
                ps.ensure_vote_bit_arrays(height - 1, last_size)
                ps.set_has_vote(
                    msg.vote.height, msg.vote.round_, msg.vote.type_,
                    msg.vote.validator_index,
                )
                self.con_s.add_peer_message(msg, peer.id())
            else:
                self.switch.stop_peer_for_error(peer, f"bad vote msg {type(msg)}")
        elif ch_id == VOTE_SET_BITS_CHANNEL:
            if self.fast_sync:
                return
            if isinstance(msg, msgs.VoteSetBitsMessage):
                rs = self.con_s.get_round_state()
                if rs.height == msg.height and rs.votes is not None:
                    vs = (
                        rs.votes.prevotes(msg.round_)
                        if msg.type_ == VOTE_TYPE_PREVOTE
                        else rs.votes.precommits(msg.round_)
                    )
                    ours = vs.bit_array_by_block_id(msg.block_id) if vs else None
                else:
                    ours = None
                ps.apply_vote_set_bits(msg, ours)
            else:
                self.switch.stop_peer_for_error(peer, f"bad bits msg {type(msg)}")

    def _screen_agg_commit(self, peer, msg: msgs.AggregateCommitMessage) -> bool:
        """Verify a received aggregate catchup commit on the peer thread
        BEFORE it reaches the consensus queue: a forged or sub-quorum
        aggregate is a peer error (stop_peer_for_error) — the aggregate
        form makes the whole commit one signature check, so the screen
        costs one gateway batch, not N serial verifies. True = enqueue
        for the consensus thread (which re-verifies: WAL replay must
        re-derive the verdict)."""
        rs = self.con_s.get_round_state()
        if msg.height != msg.commit.height():
            self.switch.stop_peer_for_error(
                peer, "aggregate commit message height mismatch"
            )
            return False
        if msg.height != rs.height or rs.validators is None:
            return False  # stale (we moved on) or not ready — drop quietly
        err = msg.commit.validate_basic()
        if err is None:
            try:
                msg.commit.verify(self.con_s.state.chain_id, rs.validators)
            except CommitError as exc:
                err = str(exc)
        if err is not None:
            self.agg_commits_rejected += 1
            fr = getattr(self.con_s, "flightrec", None)
            if fr is not None:
                fr.record("agg_commit_reject", height=msg.height,
                          err=err, peer=_peer_label(peer))
            self.switch.stop_peer_for_error(
                peer, f"bad aggregate commit: {err}"
            )
            return False
        return True

    def _handle_vote_set_maj23(self, peer, ps: PeerState, msg: msgs.VoteSetMaj23Message) -> None:
        """reactor.go:230-263: record the claim, respond with our bits."""
        rs = self.con_s.get_round_state()
        if rs.height != msg.height or rs.votes is None:
            return
        rs.votes.set_peer_maj23(msg.round_, msg.type_, peer.id(), msg.block_id)
        vs = (
            rs.votes.prevotes(msg.round_)
            if msg.type_ == VOTE_TYPE_PREVOTE
            else rs.votes.precommits(msg.round_)
        )
        ours = vs.bit_array_by_block_id(msg.block_id) if vs else None
        if ours is None:
            return
        peer.try_send(
            VOTE_SET_BITS_CHANNEL,
            _enc(
                msgs.VoteSetBitsMessage(
                    msg.height, msg.round_, msg.type_, msg.block_id, ours
                )
            ),
        )

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if not self.fast_sync:
            self.con_s.start()

    def on_stop(self) -> None:
        self.con_s.stop()
        with self._mtx:
            stops = list(self._peer_stops.values())
        for s in stops:
            s.set()

    def switch_to_consensus(self, state) -> None:
        """Fast sync complete (reactor.go:78-90). Note: update BEFORE
        reconstruct (the NewConsensusState ordering, state.go:327-330) —
        the reactor's reconstruct-first ordering in the reference lets
        updateToState clobber the freshly rebuilt LastCommit to nil,
        which breaks proposing at the switch height."""
        self.logger.info("switching to consensus at height %d", state.last_block_height + 1)
        self.con_s.update_to_state(state.copy())
        if state.last_block_height > 0:
            self.con_s.reconstruct_last_commit(state)
        self.fast_sync = False
        self.con_s.start()

    # -- broadcasts --------------------------------------------------------

    def _round_step_messages(self) -> list:
        rs = self.con_s.get_round_state()
        out = [
            msgs.NewRoundStepMessage(
                height=rs.height,
                round_=rs.round_,
                step=rs.step,
                seconds_since_start_time=int(time.time() - rs.start_time),
                last_commit_round=rs.last_commit.round_ if rs.last_commit else -1,
            )
        ]
        if rs.step == RoundStep.COMMIT and rs.proposal_block_parts is not None:
            out.append(
                msgs.CommitStepMessage(
                    height=rs.height,
                    block_parts_header=rs.proposal_block_parts.header(),
                    block_parts=rs.proposal_block_parts.bit_array(),
                )
            )
        return out

    def _broadcast_step(self) -> None:
        if not hasattr(self, "switch") or self.switch is None:
            return
        for m in self._round_step_messages():
            self.switch.broadcast(STATE_CHANNEL, _enc(m))

    def _broadcast_has_vote(self, vote) -> None:
        if not hasattr(self, "switch") or self.switch is None:
            return
        msg = msgs.HasVoteMessage(
            height=vote.height, round_=vote.round_, type_=vote.type_,
            index=vote.validator_index,
        )
        self.switch.broadcast(STATE_CHANNEL, _enc(msg))

    def _broadcast_has_part(self, data) -> None:
        """Round 20: a part landed in OUR part-set — announce it so
        peers' mirrors mark the bit and their gossip_data loops stop
        picking it for us. try_send like the maj23 path: a full STATE
        queue drops the announcement (the part relay itself still dedups
        the hard way), it must never block the consensus thread firing
        the event."""
        if not self.gossip_dedup:
            return
        if not hasattr(self, "switch") or self.switch is None:
            return
        msg = msgs.HasBlockPartMessage(
            height=data.height, round_=data.round_, index=data.index
        )
        self.switch.broadcast(STATE_CHANNEL, _enc(msg))
        self.part_announces_sent += 1

    def _broadcast_heartbeat(self, heartbeat) -> None:
        if not hasattr(self, "switch") or self.switch is None:
            return
        self.switch.broadcast(
            STATE_CHANNEL, _enc(msgs.ProposalHeartbeatMessage(heartbeat))
        )

    # -- gossip_data (reactor.go:413-535) ----------------------------------

    def _gossip_data_routine(self, peer, ps: PeerState, stop: threading.Event) -> None:
        while self.is_running() and not stop.is_set():
            if self.fast_sync:
                stop.wait(PEER_GOSSIP_SLEEP)
                continue
            rs = self.con_s.get_round_state()
            prs = ps.get_round_state()
            # 1. send a block part the peer lacks
            if (
                rs.proposal_block_parts is not None
                and prs.proposal_block_parts is not None
                and rs.height == prs.height
                and rs.round_ == prs.round_
            ):
                have = rs.proposal_block_parts.bit_array()
                needed = have.sub(prs.proposal_block_parts)
                if not needed.is_empty():
                    index, ok = needed.pick_random()
                    if ok:
                        part = rs.proposal_block_parts.get_part(index)
                        msg = msgs.BlockPartMessage(rs.height, rs.round_, part)
                        if peer.send(DATA_CHANNEL, _enc(msg)):
                            ps.set_has_proposal_block_part(prs.height, prs.round_, index)
                        continue
            # 2. peer is on an older height: catch them up from the store
            if prs.height != 0 and rs.height > prs.height:
                if self._gossip_data_catchup(peer, ps, prs):
                    continue
                stop.wait(PEER_GOSSIP_SLEEP)
                continue
            # 3. send the proposal (+POL) if the peer doesn't have it
            if (
                rs.height == prs.height
                and rs.round_ == prs.round_
                and rs.proposal is not None
                and not prs.proposal
            ):
                if peer.send(DATA_CHANNEL, _enc(msgs.ProposalMessage(rs.proposal))):
                    ps.set_has_proposal(rs.proposal)
                if 0 <= rs.proposal.pol_round < rs.round_ and rs.votes is not None:
                    pol = rs.votes.prevotes(rs.proposal.pol_round)
                    if pol is not None:
                        peer.send(
                            DATA_CHANNEL,
                            _enc(
                                msgs.ProposalPOLMessage(
                                    rs.height, rs.proposal.pol_round, pol.bit_array()
                                )
                            ),
                        )
                continue
            stop.wait(PEER_GOSSIP_SLEEP)

    def _gossip_data_catchup(self, peer, ps: PeerState, prs: PeerRoundState) -> bool:
        """Send a part of a committed block (reactor.go:494-535)."""
        store = getattr(self.con_s, "block_store", None)
        if store is None:
            return False
        meta = store.load_block_meta(prs.height)
        if meta is None:
            return False
        if prs.proposal_block_parts is None:
            # init from the committed block's part-set header
            ps_header = meta.block_id.parts_header
            ps.apply_commit_step(
                msgs.CommitStepMessage(
                    height=prs.height,
                    block_parts_header=ps_header,
                    block_parts=BitArray(ps_header.total),
                )
            )
            return True
        if meta.block_id.parts_header != prs.proposal_block_parts_header:
            return False
        needed = prs.proposal_block_parts.not_()
        if needed.is_empty():
            return False
        index, ok = needed.pick_random()
        if not ok:
            return False
        part = store.load_block_part(prs.height, index)
        if part is None:
            return False
        msg = msgs.BlockPartMessage(prs.height, prs.round_, part)
        if peer.send(DATA_CHANNEL, _enc(msg)):
            ps.set_has_proposal_block_part(prs.height, prs.round_, index)
        return True

    # -- gossip_votes (reactor.go:537-645) ---------------------------------

    def _gossip_votes_routine(self, peer, ps: PeerState, stop: threading.Event) -> None:
        while self.is_running() and not stop.is_set():
            if self.fast_sync:
                stop.wait(PEER_GOSSIP_SLEEP)
                continue
            rs = self.con_s.get_round_state()
            prs = ps.get_round_state()
            if rs.validators is not None:
                ps.ensure_vote_bit_arrays(rs.height, rs.validators.size())
                # a peer lagging one height needs last-commit bit arrays
                # before pick_vote_to_send can track what it has
                if rs.last_validators is not None:
                    ps.ensure_vote_bit_arrays(
                        rs.height - 1, rs.last_validators.size()
                    )
            if self._pick_and_send_vote(peer, ps, rs, prs):
                continue
            stop.wait(PEER_GOSSIP_SLEEP)

    def _send_vote(self, peer, ps: PeerState, vote) -> bool:
        """Send one vote and, ONLY on success, mark the peer as having
        it (the vote carries its own coordinates). A failed send leaves
        the bit clear so the gossip loop retries it later — and counts
        on the per-peer failure series, so a wedge shows up as picks
        outrunning sends instead of a frozen height vector."""
        ps.m_vote_picks.inc()
        if peer.send(VOTE_CHANNEL, _enc(msgs.VoteMessage(vote))):
            ps.set_has_vote(
                vote.height, vote.round_, vote.type_, vote.validator_index
            )
            ps.m_vote_sends.inc()
            return True
        ps.m_vote_send_failures.inc()
        fr = getattr(getattr(self, "con_s", None), "flightrec", None)
        if fr is not None:
            # picks-without-sends IS the gossip-stall signature a wedge
            # dump must carry (node/flightrec.py)
            fr.record("gossip_send_fail", peer=_peer_label(peer))
        return False

    def _relay_delay(self) -> float:
        """The current lazy-relay hold: RTT-adaptive when the switch's
        registry carries ping RTT samples (adaptive_relay_delay), the
        VOTE_RELAY_DELAY constant otherwise — including for harness
        reactors with no switch at all."""
        reg = getattr(getattr(self, "switch", None), "metrics_registry",
                      None)
        if reg is None:
            return VOTE_RELAY_DELAY
        from tendermint_tpu.p2p.telemetry import peer_metrics

        return adaptive_relay_delay(peer_metrics(reg)["ping_rtt_ewma"].value())

    def _relay_ready(self, vote) -> bool:
        """The lazy-relay screen: hold re-pushes of a vote we received
        less than _relay_delay() ago (VOTE_RELAY_DELAY, RTT-adapted when
        samples exist). Unstamped votes — our own, and store-backed
        catchup commits — relay immediately; a held vote stays pickable
        and goes out on a later tick if the peer's mirror bit is still
        clear then."""
        if not self.gossip_dedup:
            return True
        t = self.con_s.vote_recv_mono.get(
            (vote.height, vote.round_, vote.type_, vote.validator_index)
        )
        return t is None or time.monotonic() - t >= self._relay_delay()

    def _pick_and_send_vote(self, peer, ps: PeerState, rs, prs: PeerRoundState) -> bool:
        """One needed vote, if any (reactor.go:609-645 gossipVotesForHeight
        + same-height/lastCommit/catchup cases)."""
        # same height
        if rs.height == prs.height and rs.votes is not None:
            # peer is lagging in rounds: their POL prevotes
            if prs.step <= RoundStep.PROPOSE and prs.round_ != -1 and \
               prs.round_ <= rs.round_ and prs.proposal_pol_round != -1:
                pol = rs.votes.prevotes(prs.proposal_pol_round)
                vote = ps.pick_vote_to_send(pol) if pol else None
                if vote is not None and self._relay_ready(vote):
                    return self._send_vote(peer, ps, vote)
            if prs.step <= RoundStep.PREVOTE_WAIT and prs.round_ != -1 and \
               prs.round_ <= rs.round_:
                vote = ps.pick_vote_to_send(rs.votes.prevotes(prs.round_))
                if vote is not None and self._relay_ready(vote):
                    return self._send_vote(peer, ps, vote)
            if prs.step <= RoundStep.PRECOMMIT_WAIT and prs.round_ != -1 and \
               prs.round_ <= rs.round_:
                vote = ps.pick_vote_to_send(rs.votes.precommits(prs.round_))
                if vote is not None and self._relay_ready(vote):
                    return self._send_vote(peer, ps, vote)
            if prs.proposal_pol_round != -1:
                pol = rs.votes.prevotes(prs.proposal_pol_round)
                vote = ps.pick_vote_to_send(pol) if pol else None
                if vote is not None and self._relay_ready(vote):
                    return self._send_vote(peer, ps, vote)
        # peer is at our last height: send from our last commit. The
        # peer's CURRENT round usually raced past the commit round (it
        # entered a timeout round precisely because the commit votes
        # didn't reach it), so its prevote/precommit arrays track the
        # wrong round and _get_vote_bit_array would find NOTHING —
        # ensure the catchup-commit tracking array at the commit's round
        # first, exactly like the >= +2 stored-commit branch below. This
        # hole wedged 2-2 height splits permanently: the two ahead nodes
        # couldn't advance (no quorum at the new height), so the +2
        # branch never engaged, and the laggards never saw the commit.
        if rs.height == prs.height + 1 and rs.last_commit is not None:
            if isinstance(rs.last_commit, AggregateLastCommit):
                # our last commit exists only in aggregate form (we
                # ourselves finalized from a proof): no per-vote sends
                # possible — ship the whole commit
                return self._send_agg_commit(
                    peer, ps, prs.height, rs.last_commit.agg
                )
            if rs.last_validators is not None:
                ps.ensure_catchup_commit_round(
                    prs.height, rs.last_commit.round_,
                    rs.last_validators.size(),
                )
                prs = ps.get_round_state()
            vote = ps.pick_vote_to_send(rs.last_commit)
            if vote is not None and self._relay_ready(vote):
                return self._send_vote(peer, ps, vote)
        # peer is far behind: catch up with the stored seen-commit
        if rs.height >= prs.height + 2 and prs.height > 0:
            store = getattr(self.con_s, "block_store", None)
            if store is not None:
                commit = store.load_block_commit(prs.height)
                if commit is not None:
                    if commit_is_aggregate(commit):
                        # the stored commit IS the aggregate (post-flip
                        # heights, docs/upgrade.md): per-vote catchup is
                        # impossible — one AggregateCommitMessage carries
                        # the whole quorum
                        return self._send_agg_commit(
                            peer, ps, prs.height, commit
                        )
                    ps.ensure_catchup_commit_round(
                        prs.height, commit.round_(), len(commit.precommits)
                    )
                    vote = self._pick_commit_vote_to_send(ps, prs, commit)
                    if vote is not None:
                        return self._send_vote(peer, ps, vote)
        return False

    def _send_agg_commit(self, peer, ps: PeerState, height: int, agg) -> bool:
        """One whole-commit catchup send, per-peer deduplicated: the
        aggregate replaces N per-vote sends, so it goes out once per
        lagging height (re-armed after a short hold in case the frame
        was lost). Marks only on successful send, like _send_vote."""
        if not ps.agg_commit_due(height):
            return False
        msg = msgs.AggregateCommitMessage(height, agg)
        if peer.send(DATA_CHANNEL, _enc(msg)):
            ps.mark_agg_commit_sent(height)
            ps.m_catchup_commits.inc()
            self.agg_commits_sent += 1
            return True
        fr = getattr(self.con_s, "flightrec", None)
        if fr is not None:
            fr.record("gossip_send_fail", peer=_peer_label(peer))
        return False

    def _pick_commit_vote_to_send(self, ps: PeerState, prs: PeerRoundState, commit):
        """Catch-up votes come from a Commit, not a VoteSet. Like
        pick_vote_to_send, this does NOT mark — _send_vote marks only
        after the send actually succeeds."""
        with ps._mtx:
            ba = ps._get_vote_bit_array(prs.height, commit.round_(), VOTE_TYPE_PRECOMMIT)
            if ba is None:
                return None
            have = BitArray.from_indices(
                len(commit.precommits),
                [i for i, pc in enumerate(commit.precommits) if pc is not None],
            )
            needed = have.sub(ba)
            if needed.is_empty():
                return None
            index, ok = needed.pick_random()
            if not ok:
                return None
            return commit.precommits[index]

    # -- query_maj23 (reactor.go:647-739) ----------------------------------

    def _query_maj23_routine(self, peer, ps: PeerState, stop: threading.Event) -> None:
        while self.is_running() and not stop.is_set():
            stop.wait(PEER_QUERY_MAJ23_SLEEP)
            if self.fast_sync or not self.is_running() or stop.is_set():
                continue
            rs = self.con_s.get_round_state()
            prs = ps.get_round_state()
            if rs.votes is None or rs.height != prs.height:
                continue
            sends = []
            prevotes = rs.votes.prevotes(prs.round_)
            if prevotes is not None:
                maj = prevotes.two_thirds_majority()
                if maj is not None:
                    sends.append((prs.round_, VOTE_TYPE_PREVOTE, maj))
            precommits = rs.votes.precommits(prs.round_)
            if precommits is not None:
                maj = precommits.two_thirds_majority()
                if maj is not None:
                    sends.append((prs.round_, VOTE_TYPE_PRECOMMIT, maj))
            if prs.proposal_pol_round >= 0:
                pol = rs.votes.prevotes(prs.proposal_pol_round)
                if pol is not None:
                    maj = pol.two_thirds_majority()
                    if maj is not None:
                        sends.append((prs.proposal_pol_round, VOTE_TYPE_PREVOTE, maj))
            for round_, type_, block_id in sends:
                # maj23 claims ride the STATE channel, where receive()
                # handles them (reference reactor.go:662 sends these on
                # StateChannel too)
                peer.try_send(
                    STATE_CHANNEL,
                    _enc(msgs.VoteSetMaj23Message(prs.height, round_, type_, block_id)),
                )
