"""Consensus write-ahead log (reference: consensus/wal.go).

Every input to the receive routine — peer/internal messages and timeouts
— is logged BEFORE processing, plus step-transition events; on restart the
tail since the last `#ENDHEIGHT: h` marker replays through the state
machine (consensus/replay.go:98-148). JSON lines over an autofile Group;
flushed on every write (consensus/wal.go:73-95). "light" mode skips
logging gossiped block parts (consensus/wal.go:79-86).
"""

from __future__ import annotations

import json
import os
import time

from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus.ticker import TimeoutInfo
from tendermint_tpu.libs.autofile import Group
from tendermint_tpu.libs.service import BaseService


class WALMessage:
    """Tagged union of loggable inputs: msg_info (peer or internal
    message), timeout, or event (step marker)."""

    @staticmethod
    def msg_info(msg, peer_id: str) -> dict:
        return {"type": "msg_info", "peer_id": peer_id, "msg": msgs.msg_to_json(msg)}

    @staticmethod
    def timeout(ti: TimeoutInfo) -> dict:
        return {"type": "timeout", "timeout": ti.to_json()}

    @staticmethod
    def event_round_state(rs_event) -> dict:
        return {
            "type": "event",
            "height": rs_event.height,
            "round": rs_event.round_,
            "step": rs_event.step,
        }


class WAL(BaseService):
    def __init__(self, wal_file: str, light: bool = False):
        super().__init__("WAL")
        self.light = light
        self._path = wal_file
        os.makedirs(os.path.dirname(wal_file) or ".", exist_ok=True)
        self.group = Group(wal_file)

    def on_start(self) -> None:
        # a brand-new WAL gets a height-0 boundary so the first catchup
        # replay has a marker to search from (the reference seeds #ENDHEIGHT
        # on fresh WALs via its height-0 write path)
        if os.path.getsize(self._path) == 0:
            self.group.write_line("#ENDHEIGHT: 0")
            self.group.flush(sync=True)

    def on_stop(self) -> None:
        self.group.close()

    def save(self, wal_msg: dict) -> None:
        """Write + flush one input line (consensus/wal.go:73-95)."""
        if not self.is_running():
            return
        if self.light:
            # skip block parts and full proposals from peers
            if wal_msg.get("type") == "msg_info" and wal_msg.get("peer_id"):
                tag = wal_msg["msg"]["type"]
                if tag in ("block_part", "proposal"):
                    return
        line = json.dumps({"time": time.time(), **wal_msg}, sort_keys=True)
        self.group.write_line(line)
        self.group.flush(sync=True)

    def write_end_height(self, height: int) -> None:
        """Marker: height fully committed (consensus/wal.go:97-104)."""
        if not self.is_running():
            return
        self.group.write_line(f"#ENDHEIGHT: {height}")
        self.group.flush(sync=True)

    # -- replay reads ------------------------------------------------------

    def lines_after_height(self, height: int) -> list[str] | None:
        """All lines after `#ENDHEIGHT: height`, or None if the marker is
        absent (the autofile Search, consensus/replay.go:107-126)."""
        return self.group.search_lines_after_marker(f"#ENDHEIGHT: {height}")


def decode_wal_line(line: str):
    """Parse one WAL line into ('msg_info', msg, peer_id) |
    ('timeout', TimeoutInfo) | ('event', height, round, step) |
    ('endheight', h) (consensus/replay.go:38-94)."""
    line = line.strip()
    if not line:
        return None
    if line.startswith("#ENDHEIGHT:"):
        return ("endheight", int(line.split(":", 1)[1].strip()))
    obj = json.loads(line)
    t = obj["type"]
    if t == "msg_info":
        return ("msg_info", msgs.msg_from_json(obj["msg"]), obj.get("peer_id", ""))
    if t == "timeout":
        return ("timeout", TimeoutInfo.from_json(obj["timeout"]))
    if t == "event":
        return ("event", obj["height"], obj["round"], obj["step"])
    raise ValueError(f"unknown WAL line type {t!r}")
