"""Consensus write-ahead log (reference: consensus/wal.go).

Every input to the receive routine — peer/internal messages and timeouts
— is logged BEFORE processing, plus step-transition events; on restart the
tail since the last `#ENDHEIGHT: h` marker replays through the state
machine (consensus/replay.go:98-148). "light" mode skips logging gossiped
block parts (consensus/wal.go:79-86).

Round 9 rebuilt the storage format (docs/crash-recovery.md):

v2 — CRC-framed records with group commit. Every chunk starts with the
8-byte magic ``TMWAL2\\r\\n``; each record is framed as

    u32 crc32c(payload) | u32 len(payload) | payload        (big-endian)

where the payload is the exact JSON line (or ``#ENDHEIGHT: h`` marker)
the legacy format stored, so `decode_wal_line` is format-agnostic.
Records never span chunks (autofile.Group only rotates between writes).

Durability contract (group commit):
- `save()` buffers to the OS (write+flush, no fsync); a background
  flusher fsyncs at a bounded interval (`flush_interval_s`, default
  0.1 s) — so at most one interval of UNCOMMITTED inputs can be lost to
  a power failure, which is safe: replay treats them as never arrived.
- `write_end_height()` fsyncs synchronously — a committed height is
  durable before the block applies, so recovery can never lose a height
  past its last synced ``#ENDHEIGHT``.
- `sync_every_write=True` restores fsync-per-record (the legacy-strength
  bound; ~10-40x slower on real disks, benches/bench_wal.py).

Repair on open: scan every chunk forward; at the first record whose
magic/length/CRC fails, back the damaged tail (and any later chunks) up
to ``<wal>.corrupt-<stamp>`` and truncate — a torn write anywhere in the
tail leaves a clean, replayable log instead of wedging the validator.

Legacy JSON-line WALs are detected by their first byte and served
read/write-compatible with the old code (per-line fsync, line search) so
pre-round-9 node homes keep replaying.
"""

from __future__ import annotations

import json
import logging
import math
import os
import struct
import threading
import time

from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus.ticker import TimeoutInfo
from tendermint_tpu.libs.autofile import Group
from tendermint_tpu.libs.crc32c import crc32c
from tendermint_tpu.libs.envknob import env_number
from tendermint_tpu.libs.service import BaseService

logger = logging.getLogger("consensus.wal")

MAGIC = b"TMWAL2\r\n"
_FRAME = struct.Struct(">II")  # crc32c(payload), len(payload)
# bound on a single record: a block part is <= 64 KiB, hex-expanded and
# json-wrapped well under this; anything larger is framing damage
MAX_RECORD_BYTES = 8 * 1024 * 1024
# ceiling for the flusher's Event.wait — threading.TIMEOUT_MAX overflows
# on some platforms when handed to the C layer, and no sane group-commit
# interval approaches an hour anyway
_FLUSH_WAIT_CAP_S = 3600.0
# the clean watermark (round 10) re-persists once the synced position has
# advanced this far within one chunk (rotation crossings always persist):
# bounds the post-crash deep scan to ~stride + the unsynced tail without
# putting a sidecar write on every group commit
_WATERMARK_STRIDE = 1024 * 1024


def _frame(payload: bytes) -> bytes:
    # enforce the reader's bound at the producer: an oversize (or empty)
    # record would frame + fsync fine today and then read back as DAMAGE on
    # the next open — repair would truncate there and quarantine everything
    # after it, retroactively discarding durable records. Fail loudly now.
    if not 0 < len(payload) <= MAX_RECORD_BYTES:
        raise ValueError(
            f"WAL record of {len(payload)} bytes is outside "
            f"(0, {MAX_RECORD_BYTES}]; refusing to write a frame the "
            "repair pass would treat as corruption"
        )
    return _FRAME.pack(crc32c(payload), len(payload)) + payload


def _unused_path(path: str) -> str:
    """First non-existing name in path, path.1, path.2, ... — every repair
    artifact (tail backup, quarantined chunk) gets its own file, even when
    the head's quarantine name collides with the tail backup's."""
    cand, k = path, 0
    while os.path.exists(cand):
        k += 1
        cand = f"{path}.{k}"
    return cand


def scan_frames(buf: bytes, start: int = 0) -> tuple[list[bytes], int | None]:
    """Parse one chunk's bytes into record payloads.

    Returns (payloads, bad_offset): bad_offset is None for a clean chunk,
    else the byte offset of the first record whose magic/length/CRC check
    fails — exactly where the repair pass truncates.

    An EMPTY buffer is clean, not damaged: a prior repair that cut a
    chunk at offset 0 leaves a zero-byte file in the group, and flagging
    it bad again on every later open would re-quarantine every newer
    chunk — including freshly fsynced #ENDHEIGHTs.

    `start` > 0 resumes mid-chunk at a known frame boundary (the clean
    watermark, round 10): the magic check is skipped — bytes before
    `start` were covered by a synced flush and are trusted unread.
    """
    if not buf:
        return [], None
    if start > 0:
        off = start
    else:
        if not buf.startswith(MAGIC):
            return [], 0
        off = len(MAGIC)
    payloads: list[bytes] = []
    n = len(buf)
    while off < n:
        if off + _FRAME.size > n:
            return payloads, off
        crc, length = _FRAME.unpack_from(buf, off)
        # length 0 is also damage: no writer emits empty records, and
        # all-zero fill (a torn allocation) would otherwise VALIDATE —
        # crc32c(b"") == 0 matches four zero crc bytes
        if not 0 < length <= MAX_RECORD_BYTES or off + _FRAME.size + length > n:
            return payloads, off
        payload = buf[off + _FRAME.size : off + _FRAME.size + length]
        if crc32c(payload) != crc:
            return payloads, off
        payloads.append(payload)
        off += _FRAME.size + length
    return payloads, None


class WALMessage:
    """Tagged union of loggable inputs: msg_info (peer or internal
    message), timeout, or event (step marker)."""

    @staticmethod
    def msg_info(msg, peer_id: str) -> dict:
        return {"type": "msg_info", "peer_id": peer_id, "msg": msgs.msg_to_json(msg)}

    @staticmethod
    def timeout(ti: TimeoutInfo) -> dict:
        return {"type": "timeout", "timeout": ti.to_json()}

    @staticmethod
    def event_round_state(rs_event) -> dict:
        return {
            "type": "event",
            "height": rs_event.height,
            "round": rs_event.round_,
            "step": rs_event.step,
        }


class WAL(BaseService):
    def __init__(
        self,
        wal_file: str,
        light: bool = False,
        flush_interval_s: float = 0.1,
        sync_every_write: bool = False,
        chunk_size: int | None = None,
    ):
        super().__init__("WAL")
        self.light = light
        self._path = wal_file
        self._flush_interval_s = env_number(
            "TENDERMINT_WAL_FLUSH_S", flush_interval_s
        )
        # range-clamp the knobs, same never-kill-startup contract as the
        # parse: zero/negative/nan intervals busy-spin the flusher thread,
        # inf overflows Event.wait with an uncaught OverflowError that
        # silently KILLS it (records then durable only at ENDHEIGHT)
        if not (0 < self._flush_interval_s <= _FLUSH_WAIT_CAP_S):
            clamped = min(
                max(self._flush_interval_s, 0.001), _FLUSH_WAIT_CAP_S
            )
            if not math.isfinite(clamped):  # nan propagates through min/max
                clamped = 0.1
            logger.warning(
                "wal flush interval %r outside (0, %g]; clamping to %gs",
                self._flush_interval_s, _FLUSH_WAIT_CAP_S, clamped,
            )
            self._flush_interval_s = clamped
        self._sync_every = sync_every_write
        if chunk_size is None:
            chunk_size = env_number(
                "TENDERMINT_WAL_CHUNK_BYTES", 10 * 1024 * 1024, cast=int
            )
        # a chunk bound at or below the magic header would rotate on every
        # flush (a fresh head is born >= the bound) — one file + fsync per
        # record, silently worse than fsync-per-record mode
        if chunk_size < 64:
            logger.warning(
                "wal chunk bound %d B < 64 B floor; clamping", chunk_size
            )
            chunk_size = 64
        os.makedirs(os.path.dirname(wal_file) or ".", exist_ok=True)

        # latency distributions (round 11): how long each group-commit
        # fsync took and how many records it covered — the histograms
        # the durability-policy knobs are tuned against (scrape-only;
        # the flat wal_* gauges stay the legacy metrics-RPC surface)
        from tendermint_tpu.libs import telemetry

        reg = telemetry.default_registry()
        self._fsync_hist = reg.histogram(
            "wal_fsync_seconds",
            "WAL group-commit fsync latency (one fsync per group)",
        )
        self._group_hist = reg.histogram(
            "wal_group_records",
            "records covered by one WAL group-commit fsync",
            buckets=telemetry.size_buckets(16384),
        )

        # gauges (exported as wal_* via the metrics RPC)
        self._records = 0
        self._fsyncs = 0
        self._pending = 0  # records buffered since the last fsync
        self._group_last = 0
        self._group_max = 0
        self._synced_records = 0  # sum of group sizes (for the avg)
        self._repairs = 0
        self._truncated_bytes = 0
        # retention plane (round 19): whole rotated chunks dropped by
        # prune_to, plus a per-chunk max-#ENDHEIGHT memo (rotated chunks
        # are immutable, so one scan per chunk per process suffices)
        self._chunks_pruned = 0
        self._chunk_marker_cache: dict[str, int | None] = {}
        # clean-watermark plane (round 10, ROADMAP open item): chunks a
        # synced flush already covered skip the open-time CRC deep scan
        self._wm_path = wal_file + ".clean"
        self._wm_written: tuple[int, int] | None = None  # (chunk_index, offset)
        self._scan_skipped_chunks = 0
        self._scan_skipped_bytes = 0

        self._legacy = self._detect_legacy()
        self._records_at_open = 0
        if not self._legacy:
            self._records_at_open = self._repair()
        self._wmtx = threading.Lock()  # guards the gauge/fsync bookkeeping
        self._sync_mtx = threading.Lock()  # serializes fsyncers only
        self._last_sync = time.monotonic()
        self._flusher: threading.Thread | None = None
        self._flush_stop = threading.Event()
        self.group = Group(
            wal_file,
            chunk_size=chunk_size,
            header=b"" if self._legacy else MAGIC,
            crash_hooks=True,
        )

    # -- format detection + repair ----------------------------------------

    def _detect_legacy(self) -> bool:
        """A pre-round-9 WAL stored JSON text lines, so its chunks open
        with '{' (a json record) or '#' (the ENDHEIGHT seed) — exactly
        and only those two bytes; v2 chunks open with MAGIC. The two
        alphabets are disjoint and a WAL is never mixed, so ONE chunk
        with either signature decides the format. Scan every chunk
        before deciding: judging only the oldest non-empty chunk would
        let a single damaged byte at its offset 0 misread a legacy log
        as v2 and hand it to the MUTATING v2 repair, which would
        quarantine every (intact, replayable) later chunk wholesale.
        No evidence anywhere (fresh home, or every chunk head damaged)
        = v2: its repair backs all bytes up before cutting."""
        legacy_seen = False
        for p in Group.list_chunks(self._path):
            try:
                with open(p, "rb") as f:
                    head = f.read(len(MAGIC))
            except OSError:
                continue
            if head.startswith(MAGIC):
                return False
            if head[:1] in (b"{", b"#"):
                legacy_seen = True
        return legacy_seen

    # -- clean watermark (round 10) ----------------------------------------

    def _load_watermark(self) -> dict | None:
        """The persisted clean watermark, validated against the chunk
        files on disk — None (with a warning where it matters) whenever
        anything disagrees, which falls back to the full deep scan. The
        sidecar is written AFTER each covering fsync returns, so a valid
        watermark can only ever trail durability, never lead it."""
        try:
            with open(self._wm_path) as f:
                obj = json.load(f)
            idx, off, rec = obj["chunk_index"], obj["offset"], obj["records"]
        except (OSError, ValueError, KeyError):
            return None
        if not all(isinstance(v, int) and v >= 0 for v in (idx, off, rec)):
            return None
        if off < len(MAGIC):
            return None
        indices = Group._chunk_indices(self._path)
        index_to_path = {i: f"{self._path}.{i:03d}" for i in indices}
        if os.path.exists(self._path):
            index_to_path[(indices[-1] + 1) if indices else 0] = self._path
        target = index_to_path.get(idx)
        # chunks below idx must be contiguous EXCEPT for a pruned prefix:
        # retention (round 19, prune_to) deletes whole chunks from the
        # front of the group, which must not invalidate the watermark —
        # but a chunk missing from the MIDDLE means the log was mangled
        missing = [i for i in range(idx) if i not in index_to_path]
        prefix_pruned = missing == list(range(len(missing)))
        if target is None or not prefix_pruned:
            logger.warning(
                "WAL clean watermark names chunk %d which is missing; "
                "deep-scanning the full history", idx,
            )
            return None
        if os.path.getsize(target) < off:
            # fsynced bytes vanished: either the filesystem lost data or
            # the log was hand-edited — both are full-forensics territory
            logger.warning(
                "WAL clean watermark covers %d byte(s) of %s but only %d "
                "exist; deep-scanning the full history",
                off, os.path.basename(target), os.path.getsize(target),
            )
            return None
        return {"chunk_index": idx, "offset": off, "records": rec,
                "path": target}

    def _write_watermark(self, pos: tuple[int, int], records: int) -> None:
        """Persist (chunk_index, offset, records-covered) atomically. Not
        fsynced on purpose: a lost or torn sidecar only widens the next
        open's scan — JSON that fails to parse reads as 'no watermark'."""
        tmp = self._wm_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(
                    {"chunk_index": pos[0], "offset": pos[1],
                     "records": records}, f,
                )
            os.replace(tmp, self._wm_path)
            self._wm_written = pos
        except OSError:
            logger.exception("WAL clean watermark write failed")

    def _maybe_write_watermark(self, pos: tuple[int, int], records: int) -> None:
        last = self._wm_written
        if last is None or pos[0] > last[0] or (
            pos[0] == last[0] and pos[1] - last[1] >= _WATERMARK_STRIDE
        ):
            self._write_watermark(pos, records)

    def _drop_watermark(self) -> None:
        try:
            os.unlink(self._wm_path)
        except FileNotFoundError:
            pass
        self._wm_written = None

    def _repair(self) -> int:
        """Forward-scan the chunks; truncate at the first damaged record,
        backing the cut tail (and all later chunks) up to
        <wal>.corrupt-<stamp>. Returns the surviving record count.

        Chunks (and the watermark chunk's prefix) covered by the clean
        watermark skip the deep scan: those bytes were fsynced before the
        sidecar was written and a crash cannot have torn them — the scan
        that used to be O(total history) per open is now O(bytes since
        the last persisted watermark). TENDERMINT_WAL_DEEP_SCAN=1 forces
        the full-history scan for forensics (historical-chunk bit rot is
        out of the crash model, exactly like silent payload rot on
        trusted local IPC in the device plane's contract)."""
        wm = None
        if int(env_number("TENDERMINT_WAL_DEEP_SCAN", 0, cast=int)):
            logger.info("TENDERMINT_WAL_DEEP_SCAN=1: full-history WAL scan")
        else:
            wm = self._load_watermark()
        paths = Group.list_chunks(self._path)
        records = wm["records"] if wm else 0
        wm_at = paths.index(wm["path"]) if wm else -1
        for i, p in enumerate(paths):
            start = 0
            if wm is not None:
                if i < wm_at:
                    self._scan_skipped_chunks += 1
                    self._scan_skipped_bytes += os.path.getsize(p)
                    continue
                if i == wm_at:
                    start = wm["offset"]
                    self._scan_skipped_bytes += start
                    self._wm_written = (wm["chunk_index"], wm["offset"])
            try:
                with open(p, "rb") as f:
                    buf = f.read()
            except OSError:
                continue
            payloads, bad = scan_frames(buf, start=start)
            records += len(payloads)
            if bad is None:
                continue
            stamp = time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}"
            backup = _unused_path(f"{self._path}.corrupt-{stamp}")
            with open(backup, "wb") as f:
                f.write(buf[bad:])
            with open(p, "r+b") as f:
                f.truncate(bad)
            cut = len(buf) - bad
            # anything after a damaged record cannot be ordered safely:
            # later chunks leave the group's namespace wholesale (when the
            # damaged chunk is not the head, the HEAD's quarantine name is
            # exactly the tail backup's — _unused_path keeps them distinct)
            for q in paths[i + 1 :]:
                dest = _unused_path(f"{q}.corrupt-{stamp}")
                os.replace(q, dest)
                cut += os.path.getsize(dest)
            self._repairs += 1
            self._truncated_bytes += cut
            # the watermark may name bytes (or whole chunks) the cut just
            # removed; rather than reason about partial overlap, drop it —
            # the next synced flush rebuilds it over the repaired log
            self._drop_watermark()
            logger.warning(
                "WAL repair: truncated %d byte(s) at %s offset %d (backup %s)",
                cut, os.path.basename(p), bad, backup,
            )
            break
        return records

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        # a brand-new WAL gets a height-0 boundary so the first catchup
        # replay has a marker to search from (the reference seeds #ENDHEIGHT
        # on fresh WALs via its height-0 write path)
        if self._legacy:
            if os.path.getsize(self._path) == 0:
                self.group.write_line("#ENDHEIGHT: 0")
                self.group.flush(sync=True)
        elif self._records_at_open == 0:
            self.write_end_height(0, _force=True)
        if not self._legacy and not self._sync_every:
            self._flush_stop.clear()
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True, name="wal.flusher"
            )
            self._flusher.start()
        logger.info(
            "WAL open: format=%s %s (records=%d repairs=%d)",
            "legacy-json" if self._legacy else "v2-crc32c",
            "fsync-per-record" if (self._legacy or self._sync_every)
            else f"group-commit flush_interval={self._flush_interval_s}s "
                 f"sync-on-ENDHEIGHT",
            self._records_at_open, self._repairs,
        )

    def on_stop(self) -> None:
        self._flush_stop.set()
        stuck = False
        if self._flusher is not None:
            self._flusher.join(timeout=2.0)
            stuck = self._flusher.is_alive()
            self._flusher = None
        if stuck:
            # the flusher is wedged inside os.fsync on a dying disk while
            # holding _sync_mtx — a final sync() here would block shutdown
            # forever on the same stuck device, defeating the timed join
            logger.warning(
                "WAL flusher stuck in fsync after 2s; skipping final sync "
                "(%d record(s) OS-buffered but not known durable)",
                self._pending,
            )
        else:
            self.sync()
            if not self._legacy:
                # exact watermark on clean close: the next open deep-scans
                # nothing (the final sync drained every pending record)
                with self._wmtx:
                    pos = self.group.position() if self._pending == 0 else None
                    covered = self._records_at_open + self._records
                if pos is not None:
                    self._write_watermark(pos, covered)
        self.group.close()

    def _flush_loop(self) -> None:
        while not self._flush_stop.wait(self._flush_interval_s):
            try:
                self.sync()
            except Exception:  # a dying disk must not kill the flusher
                logger.exception("WAL group-commit fsync failed")

    def sync(self) -> None:
        """Group commit: one fsync covering every record buffered since the
        last one. No-op when nothing is pending.

        The fsync runs OUTSIDE _wmtx AND outside the Group's append lock
        (flush(sync=True) dups the fd and fsyncs after releasing it): a
        save() on the consensus receive hot path must never stall behind
        the flusher's disk round trip — that latency is exactly what group
        commit exists to remove. Records landing mid-fsync are durable
        early or ride the next group; either way the batch counted below
        was fully written (and OS-flushed) before the dup was taken."""
        with self._sync_mtx:
            with self._wmtx:
                batch = self._pending
                # clean-watermark coordinate, captured while _wmtx blocks
                # writers: the group position corresponds EXACTLY to the
                # _records written so far, and the fsync below covers at
                # least these bytes
                pos = None if self._legacy else self.group.position()
                covered = self._records_at_open + self._records
            if batch == 0:
                return
            t0 = time.perf_counter()
            self.group.flush(sync=True)
            self._fsync_hist.observe(time.perf_counter() - t0)
            self._group_hist.observe(batch)
            with self._wmtx:
                self._account_sync(batch)
            if pos is not None:
                self._maybe_write_watermark(pos, covered)

    def _account_sync(self, batch: int) -> None:
        # caller holds self._wmtx
        self._fsyncs += 1
        self._pending -= batch
        self._group_last = batch
        self._group_max = max(self._group_max, batch)
        self._synced_records += batch
        self._last_sync = time.monotonic()

    # -- writing -----------------------------------------------------------

    def _write_record(self, payload: bytes, sync: bool) -> None:
        with self._wmtx:
            self._records += 1
            self._pending += 1
            if self._legacy:
                self.group.write_bytes(payload + b"\n")
            else:
                self.group.write_bytes(_frame(payload))
                if not (sync or self._sync_every):
                    # publish to the OS now (readers + rotation); fsync
                    # rides the flusher's bounded interval
                    self.group.flush(sync=False)
                    return
        # synchronous durability points — #ENDHEIGHT, sync_every mode, and
        # the legacy per-line contract — fsync outside the write lock too
        self.sync()

    def save(self, wal_msg: dict) -> None:
        """Write one input record; durable within flush_interval_s
        (consensus/wal.go:73-95 wrote+fsynced every line)."""
        if not self.is_running():
            return
        if self.light:
            # skip block parts and full proposals from peers
            if wal_msg.get("type") == "msg_info" and wal_msg.get("peer_id"):
                tag = wal_msg["msg"]["type"]
                if tag in ("block_part", "proposal"):
                    return
        line = json.dumps({"time": time.time(), **wal_msg}, sort_keys=True)
        self._write_record(line.encode(), sync=False)

    def write_end_height(self, height: int, _force: bool = False) -> None:
        """Marker: height fully committed (consensus/wal.go:97-104).
        Always fsynced — the group-commit durability contract's floor."""
        if not self.is_running() and not _force:
            return
        self._write_record(f"#ENDHEIGHT: {height}".encode(), sync=True)

    # -- retention (round 19) ----------------------------------------------

    def _chunk_max_marker(self, path: str) -> int | None:
        """Largest #ENDHEIGHT height in a ROTATED chunk (None when the
        chunk carries no marker). Memoized — rotated chunks never change."""
        if path in self._chunk_marker_cache:
            return self._chunk_marker_cache[path]
        best: int | None = None
        try:
            with open(path, "rb") as f:
                payloads, _bad = scan_frames(f.read())
        except OSError:
            # transient read failure (fd pressure, NFS blip): do NOT
            # cache — a memoized None here could disable pruning for
            # the process lifetime if this chunk held the anchor marker
            return None
        for p in payloads:
            if p.startswith(b"#ENDHEIGHT:"):
                try:
                    best = int(p.split(b":", 1)[1])
                except ValueError:
                    continue
        self._chunk_marker_cache[path] = best
        return best

    def prune_to(self, retain_height: int) -> int:
        """Drop rotated chunks whose entire content precedes history the
        node still retains; returns the number of chunk files deleted.

        Replay only ever searches `#ENDHEIGHT: h` markers for heights the
        node still holds (h >= retain_height - 1, since retention keeps
        the head blocks). Markers are strictly increasing through the
        group, so every chunk OLDER than the newest chunk containing a
        marker <= retain_height - 1 can only hold records below every
        marker replay can be asked for — deletable wholesale. Chunk
        granularity keeps this a pure unlink of immutable files: the
        head and any chunk at/after the anchor are never touched, and
        the clean watermark stays valid across a pruned prefix
        (_load_watermark tolerates missing LEADING chunks)."""
        if self._legacy:
            return 0  # pre-framed logs predate retention; leave them be
        paths = self.group.chunk_paths()
        rotated = paths[:-1]  # head (last) is live, never pruned
        anchor = None
        for k in range(len(rotated) - 1, -1, -1):
            m = self._chunk_max_marker(rotated[k])
            if m is not None and m <= retain_height - 1:
                anchor = k
                break
        if anchor is None or anchor == 0:
            return 0
        pruned = 0
        for p in rotated[:anchor]:
            try:
                os.unlink(p)
            except OSError:
                # STOP at the first failure: deleting newer chunks past
                # a surviving older one would punch a mid-log hole that
                # permanently invalidates the clean watermark (its
                # pruned-prefix tolerance requires the missing indices
                # to be a LEADING run); the stuck chunk retries next pass
                break
            pruned += 1
            self._chunk_marker_cache.pop(p, None)
        self._chunks_pruned += pruned
        return pruned

    # -- replay reads ------------------------------------------------------

    def _chunk_payload_lists(self) -> list[tuple[str, list[bytes]]]:
        """(path, payloads) per chunk, oldest→newest (chunk_paths() OS-
        flushes the head under the Group lock before listing)."""
        out = []
        for p in self.group.chunk_paths():
            with open(p, "rb") as f:
                payloads, _bad = scan_frames(f.read())
            # _bad!=None post-repair means damage landed after open (or
            # the head grew mid-read); serve the clean prefix like the
            # repair pass would
            out.append((p, payloads))
        return out

    def lines_after_height(self, height: int) -> list[str] | None:
        """All lines after `#ENDHEIGHT: height`, or None if the marker is
        absent (the autofile Search, consensus/replay.go:107-126).

        Like the legacy Group search, chunks are read lazily newest-first
        and the scan STOPS at the first chunk containing the marker — a
        long multi-chunk WAL costs one chunk read on node start."""
        if self._legacy:
            return self.group.search_lines_after_marker(f"#ENDHEIGHT: {height}")
        marker = f"#ENDHEIGHT: {height}".encode()
        tail: list[str] = []
        for p in reversed(self.group.chunk_paths()):
            with open(p, "rb") as f:
                payloads, _bad = scan_frames(f.read())
            for i in range(len(payloads) - 1, -1, -1):
                if payloads[i] == marker:
                    return [
                        b.decode(errors="replace") for b in payloads[i + 1 :]
                    ] + tail
            tail = [b.decode(errors="replace") for b in payloads] + tail
        return None

    def lines_after_last_marker(self) -> tuple[int, list[str]] | None:
        """(height, lines) after the LAST #ENDHEIGHT marker of any height —
        the repair fallback when the exact boundary was cut from the tail
        (consensus/replay.py). None if no marker survives."""
        lines = self.read_all_lines()
        for i in range(len(lines) - 1, -1, -1):
            if lines[i].startswith("#ENDHEIGHT:"):
                try:
                    h = int(lines[i].split(":", 1)[1].strip())
                except ValueError:
                    continue
                return h, lines[i + 1 :]
        return None

    def read_all_lines(self) -> list[str]:
        """Every record payload as text, format-agnostic (the operator
        replay tool, consensus/replay_file.py)."""
        if self._legacy:
            return self.group.read_all_lines()
        return [
            b.decode(errors="replace")
            for _, payloads in self._chunk_payload_lists()
            for b in payloads
        ]

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._wmtx:
            synced_groups = max(self._fsyncs, 1)
            return {
                "format": 1 if self._legacy else 2,
                "records": self._records,
                "fsyncs": self._fsyncs,
                "pending": self._pending,
                "group_size": self._group_last,
                "group_size_max": self._group_max,
                "group_size_avg": round(self._synced_records / synced_groups, 2),
                "repairs": self._repairs,
                "truncated_bytes": self._truncated_bytes,
                # retention plane (round 19): rotated chunks dropped
                # below the retain horizon
                "chunks_pruned": self._chunks_pruned,
                # clean-watermark plane (round 10): how much history the
                # last open trusted without re-reading — skipped bytes at 0
                # on a long-lived home means the watermark is not landing
                "scan_skipped_chunks": self._scan_skipped_chunks,
                "scan_skipped_bytes": self._scan_skipped_bytes,
                "flush_interval_s": self._flush_interval_s,
                "sync_every_write": int(self._sync_every),
                # seconds since the last fsync: pending>0 with a growing
                # age means the flusher is stuck, not merely idle
                "sync_age_s": round(time.monotonic() - self._last_sync, 3),
            }


def read_wal_lines(wal_file: str) -> list[str]:
    """Read-only, format-aware view of a WAL's record lines — NO repair,
    no truncation, no backups, no head creation. The operator replay tool
    (consensus/replay_file.py) must never mutate the home it inspects
    (it may be damaged evidence, or a live node's open files); a damaged
    frame ends the readable stream RIGHT THERE, exactly where the node's
    own repair would cut — records in later chunks cannot be ordered
    across the hole, and repair would quarantine them, so the read-only
    view must not splice them in either. A MISSING WAL raises (like the
    open() this replaced): a typo'd --home must not read as an empty
    log."""
    chunks = Group.list_chunks(wal_file)
    if not chunks:
        raise FileNotFoundError(wal_file)
    out: list[str] = []
    for i, p in enumerate(chunks):
        with open(p, "rb") as f:
            buf = f.read()
        if not buf:
            continue
        if buf[:1] in (b"{", b"#"):  # legacy JSON lines
            out.extend(ln.decode(errors="replace") for ln in buf.splitlines())
        else:
            payloads, bad = scan_frames(buf)
            out.extend(b.decode(errors="replace") for b in payloads)
            if bad is not None:
                logger.warning(
                    "read_wal_lines: damaged frame in %s at offset %d; "
                    "stopping (%d later chunk(s) unreadable past the hole)",
                    os.path.basename(p), bad, len(chunks) - i - 1,
                )
                break
    return out


def decode_wal_line(line: str):
    """Parse one WAL line into ('msg_info', msg, peer_id) |
    ('timeout', TimeoutInfo) | ('event', height, round, step) |
    ('endheight', h) (consensus/replay.go:38-94)."""
    line = line.strip()
    if not line:
        return None
    if line.startswith("#ENDHEIGHT:"):
        return ("endheight", int(line.split(":", 1)[1].strip()))
    obj = json.loads(line)
    t = obj["type"]
    if t == "msg_info":
        return ("msg_info", msgs.msg_from_json(obj["msg"]), obj.get("peer_id", ""))
    if t == "timeout":
        return ("timeout", TimeoutInfo.from_json(obj["timeout"]))
    if t == "event":
        return ("event", obj["height"], obj["round"], obj["step"])
    raise ValueError(f"unknown WAL line type {t!r}")
