"""Operator WAL-replay tool (reference: consensus/replay_file.go).

Replays a node's consensus WAL against a fresh state built from the
genesis doc + a fresh app, recomputing every commit. `console=True` gives
an interactive stepper (next [N] / locate / status / quit — replay_file.go:144).
Because blocks re-execute from scratch, a divergence between the WAL and
the app surfaces as a commit failure at the offending height.
"""

from __future__ import annotations

import threading

from tendermint_tpu.consensus.state import ConsensusState, MsgInfo
from tendermint_tpu.consensus.wal import decode_wal_line
from tendermint_tpu.libs.db import MemDB
from tendermint_tpu.libs.events import EventSwitch


def new_consensus_state_for_replay(cfg):
    """replay_file.go:237-267: fresh state + stores + proxy app."""
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.consensus.replay import Handshaker
    from tendermint_tpu.proxy.client_creator import default_client_creator
    from tendermint_tpu.proxy.multi_app_conn import AppConns
    from tendermint_tpu.state.state import State
    from tendermint_tpu.types import GenesisDoc

    doc = GenesisDoc.from_file(cfg.base.genesis_file())
    state = State.get_state(MemDB(), doc)
    store = BlockStore(MemDB())
    creator = default_client_creator(cfg.base.proxy_app, cfg.base.db_dir())
    proxy_app = AppConns(creator, Handshaker(state, store))
    proxy_app.start()
    evsw = EventSwitch()
    evsw.start()

    from tendermint_tpu.mempool.mempool import Mempool

    mempool = Mempool(cfg.mempool, proxy_app.mempool())
    cs = ConsensusState(
        cfg.consensus, state, proxy_app.consensus(), store, mempool
    )
    cs.set_event_switch(evsw)
    return cs


def run_replay_file(cfg, console: bool = False) -> int:
    """Feed the node's WAL through a fresh consensus state; returns the
    number of replayed messages."""
    wal_file = cfg.consensus.wal_file()
    # format-aware READ-ONLY view (v2 CRC frames or legacy JSON lines):
    # an operator tool must never run the mutating repair pass against
    # the home it inspects — a damaged frame just ends the prefix here
    from tendermint_tpu.consensus.wal import read_wal_lines

    lines = read_wal_lines(wal_file)

    cs = new_consensus_state_for_replay(cfg)
    cs.replay_mode = True
    cs.start_routines(max_steps=0)  # ticker + routine, no WAL, no round-0
    replayed = 0
    step_budget = [float("inf")]

    def prompt() -> bool:
        """console UI; False = quit."""
        while True:
            try:
                cmdline = input("> ").strip().split()
            except EOFError:
                return False
            if not cmdline:
                continue
            cmd = cmdline[0]
            if cmd in ("q", "quit"):
                return False
            if cmd in ("n", "next"):
                step_budget[0] = int(cmdline[1]) if len(cmdline) > 1 else 1
                return True
            if cmd == "status":
                rs = cs.get_round_state()
                print(rs.to_json())
                continue
            print("commands: next [N] | status | quit")

    if console:
        print(f"replaying {wal_file} ({len(lines)} lines); commands: next [N] | status | quit")
        step_budget[0] = 0

    for i, line in enumerate(lines):
        try:
            entry = decode_wal_line(line)
        except Exception as exc:  # noqa: BLE001
            if i == len(lines) - 1:
                print(f"skipping corrupt tail line: {exc}")
                break
            raise
        if entry is None or entry[0] in ("event", "endheight"):
            continue
        if console and step_budget[0] <= 0:
            if not prompt():
                break
        # feed synchronously through the handler (replay determinism)
        if entry[0] == "msg_info":
            cs.handle_msg(MsgInfo(entry[1], entry[2]))
        elif entry[0] == "timeout":
            cs.handle_timeout(entry[1])
        replayed += 1
        step_budget[0] -= 1

    rs = cs.get_round_state()
    print(f"replayed {replayed} messages; final height/round/step: "
          f"{rs.height}/{rs.round_}/{rs.step}")
    cs.stop()
    return replayed
