"""Per-height consensus trace spans (round 11).

``consensus_height_seconds_last`` says a height was slow; it never said
WHERE the wall time went. Every latency-overlap lever on the ROADMAP
(big-committee batch verify, pipelined execution, sharded device plane)
needs exactly that breakdown, so the receive routine now attributes each
committed height's wall clock to named segments:

    new_height -> new_round -> propose -> prevote -> prevote_wait ->
    precommit -> precommit_wait -> commit (waiting for the full block)
    -> block_save -> apply -> snapshot_hook -> events

The step segments fall out of the existing ``new_step`` transitions (the
receive routine is the single writer, so marks are lock-free); the
finalize sub-phases are marked explicitly in ``finalize_commit``. The
segments PARTITION the height's wall time — they sum to the same clock
``height_seconds_last`` reads (the consensus_trace RPC contract asserts
within 5%). Auxiliary attributions that OVERLAP segments (part hashing
inside propose) ride ``aux`` and never enter the sum.

Device attribution: the recorder snapshots the verify/hash gateway
counters and breaker state at height start and commit, so each trace
carries the height's device-vs-CPU split — a breaker-open height
visibly attributes its verify/hash work to the CPU fallback (the chaos
tier asserts this).

Pipelined execution (round 14, docs/execution-pipeline.md): the deferred
apply of height H runs on the executor thread WHILE this recorder traces
height H+1, so the executor attributes its runtime to the height it
overlaps via ``note_overlap(H+1, "overlap_apply_s", ...)`` — a locked
side table (the lock-free single-writer rule holds for ``mark``/``note``;
overlap notes are the one cross-thread writer and pay a lock). Overlay
keys are aux attributions: reported, never summed into the partition —
the consensus thread's segments still partition its own wall clock, and
the join wait it actually pays surfaces as the ``pipeline_join_wait_s``
aux note inside whichever segment blocked (normally propose).

Completed traces land in a ring buffer (TENDERMINT_TRACE_RING, default
128) served by the ``consensus_trace`` RPC and the operator CLI
``python -m tendermint_tpu.ops.trace``.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from tendermint_tpu.consensus.round_state import RoundStep
from tendermint_tpu.libs.envknob import env_number as _env_number

# canonical segment order (display + docs/observability.md diagram)
SEGMENTS = (
    "new_height", "new_round", "propose", "prevote", "prevote_wait",
    "precommit", "precommit_wait", "commit", "block_save", "apply",
    "snapshot_hook", "events",
)

_STEP_SEGMENTS = {
    RoundStep.NEW_HEIGHT: "new_height",
    RoundStep.NEW_ROUND: "new_round",
    RoundStep.PROPOSE: "propose",
    RoundStep.PREVOTE: "prevote",
    RoundStep.PREVOTE_WAIT: "prevote_wait",
    RoundStep.PRECOMMIT: "precommit",
    RoundStep.PRECOMMIT_WAIT: "precommit_wait",
    RoundStep.COMMIT: "commit",
}

# device-probe keys differenced per height; anything else in the probe
# dict records as <key>_start / <key>_end (state, not a counter)
_DELTA_KEYS = (
    "verify_tpu_sigs", "verify_cpu_sigs",
    "hash_tpu_leaves", "hash_cpu_leaves",
    "breaker_opens",
)


def step_segment(step: int) -> str:
    return _STEP_SEGMENTS.get(step, "new_height")


# gossip arrival marks (round 15): wall-clock instants recorded once per
# height, in canonical order. Absolute epoch seconds — the fleet
# aggregator (ops/fleet.py) compares them ACROSS nodes to reconstruct
# proposer->peer propagation lag, quorum-formation time, and commit skew
ARRIVALS = (
    "proposal",          # proposal message accepted
    "first_block_part",  # first proposal part added (build or gossip)
    "prevote_quorum",    # +2/3 prevotes for a block observed
    "precommit_quorum",  # +2/3 precommits for a block observed
    "commit",            # finalize began (quorum AND full block held)
)


def arrival_hists(reg=None) -> dict:
    """The scrape-side distributions of the arrival marks (create-or-get,
    so node/telemetry.py can materialize them per-node): seconds from
    height start to quorum formation, by phase. A partition shows up
    here as a spike — the first post-heal height carries the whole
    outage in its quorum-formation observation."""
    from tendermint_tpu.libs import telemetry

    if reg is None:
        reg = telemetry.default_registry()
    return {
        "quorum": reg.histogram(
            "consensus_quorum_seconds",
            "seconds from height start to +2/3 quorum formation, by phase",
            labelnames=("phase",),
        ),
        "first_part": reg.histogram(
            "consensus_first_part_seconds",
            "seconds from height start to the first proposal part held",
        ),
    }


class HeightTrace:
    """One committed height's wall-time breakdown. Immutable once built
    (the ring hands references to RPC readers on other threads)."""

    __slots__ = ("height", "segments", "aux", "device", "total_s",
                 "wall_s", "rounds", "completed_at", "arrivals",
                 "started_at")

    def __init__(self, height, segments, aux, device, wall_s, rounds,
                 arrivals=None, started_at=None):
        self.height = height
        self.segments = segments
        self.aux = aux
        self.device = device
        self.total_s = sum(segments.values())
        self.wall_s = wall_s
        self.rounds = rounds
        self.completed_at = time.time()
        # gossip arrival marks (round 15): absolute wall-clock instants
        # the fleet aggregator aligns across nodes
        self.arrivals = dict(arrivals or {})
        self.started_at = (
            started_at if started_at is not None
            else self.completed_at - wall_s
        )

    def to_json(self) -> dict:
        return {
            "height": self.height,
            "rounds": self.rounds,
            "wall_s": round(self.wall_s, 6),
            "total_s": round(self.total_s, 6),
            "segments": {k: round(v, 6) for k, v in self.segments.items()},
            "aux": {k: round(v, 6) for k, v in self.aux.items()},
            "device": dict(self.device),
            "started_at": self.started_at,
            "arrivals": {k: round(v, 6) for k, v in self.arrivals.items()},
            "completed_at": self.completed_at,
        }


class TraceRecorder:
    """Single-writer segment clock + ring of completed HeightTraces.

    ``mark``/``note`` run only on the consensus receive routine and touch
    no lock (lock-cheap by construction); ``finish`` seals the active
    trace into the ring under the ring lock; ``last`` reads the ring from
    RPC threads under the same lock."""

    def __init__(self, device_probe=None, ring: int | None = None):
        if ring is None:
            ring = max(1, int(_env_number("TENDERMINT_TRACE_RING", 128,
                                          cast=int)))
        self._ring: deque[HeightTrace] = deque(maxlen=ring)
        self._ring_mtx = threading.Lock()
        self._device_probe = device_probe
        self._height = 0
        self._segments: dict[str, float] = {}
        self._aux: dict[str, float] = {}
        self._rounds = 0
        self._cur = "new_height"
        self._last_t = time.monotonic()
        # gossip arrival marks (round 15): wall-clock instants, set once
        # per height on the receive routine (lock-free single writer like
        # mark/note). metrics_registry scopes the quorum histograms the
        # marks feed at finish (node/telemetry.py sets the node registry)
        self._arrivals: dict[str, float] = {}
        self._started_wall = time.time()
        self.metrics_registry = None
        # finish()'s end snapshot doubles as the next begin()'s start —
        # one probe per height boundary, not two back-to-back on the
        # receive routine
        self._dev_carry: dict | None = None
        self._dev_start: dict = self._probe()
        # cross-thread overlap attributions (round 14): the apply
        # executor notes its runtime against the height it overlapped;
        # notes landing before that height's begin() park in _ov_pending
        self._ov_mtx = threading.Lock()
        self._overlay: dict[str, float] = {}
        self._ov_pending: dict[int, dict[str, float]] = {}

    def _probe(self) -> dict:
        if self._device_probe is None:
            return {}
        try:
            return dict(self._device_probe())
        except Exception:  # noqa: BLE001 — attribution must never wedge
            # the receive routine; a failed probe costs one height's
            # device split, nothing else
            return {}

    def begin(self, height: int, now: float | None = None) -> None:
        """Start the clock for `height` (fresh segment table + device
        snapshot)."""
        self._segments = {}
        self._aux = {}
        self._rounds = 0
        self._cur = "new_height"
        self._last_t = now if now is not None else time.monotonic()
        self._arrivals = {}
        self._started_wall = time.time()
        with self._ov_mtx:
            # _height moves under the overlay lock so a concurrent
            # note_overlap either parks in _ov_pending (and is adopted
            # here) or lands in the fresh overlay — never in a dict this
            # reset is about to discard
            self._height = height
            self._overlay = self._ov_pending.pop(height, {})
            # drop stale parked overlays (a restart/fast-sync jump can
            # strand entries below the new height forever otherwise)
            for h in [h for h in self._ov_pending if h < height]:
                del self._ov_pending[h]
        if self._dev_carry is not None:
            self._dev_start, self._dev_carry = self._dev_carry, None
        else:
            self._dev_start = self._probe()

    def mark(self, segment: str, now: float | None = None) -> None:
        """Close the current segment at `now` and start `segment`.
        Re-marking the current segment is a cheap no-op boundary."""
        now = now if now is not None else time.monotonic()
        dt = now - self._last_t
        if dt > 0:
            self._segments[self._cur] = self._segments.get(self._cur, 0.0) + dt
        self._last_t = now
        self._cur = segment

    def note(self, key: str, seconds: float) -> None:
        """Auxiliary overlapping attribution (e.g. part_hash_s inside
        propose) — reported, never summed into the partition."""
        self._aux[key] = self._aux.get(key, 0.0) + seconds

    def note_round(self, round_: int) -> None:
        self._rounds = max(self._rounds, round_ + 1)

    def mark_arrival(self, key: str, at: float | None = None) -> None:
        """Record a gossip arrival instant (ARRIVALS key) ONCE per
        height — later duplicates (a re-proposed round, catchup parts)
        keep the FIRST instant, which is what propagation-lag math
        wants. Wall-clock epoch seconds so the fleet aggregator can
        align instants across nodes. Single-writer like mark/note."""
        if key not in self._arrivals:
            self._arrivals[key] = at if at is not None else time.time()

    def note_overlap(self, height: int, key: str, seconds: float) -> None:
        """Cross-thread aux attribution (round 14): the apply executor
        credits work to the height it OVERLAPPED (apply of H runs under
        consensus of H+1). Notes for a height not yet begun park until
        its begin(); notes for an already-sealed height are dropped —
        attribution must never resurrect a published trace."""
        with self._ov_mtx:
            if height == self._height:
                self._overlay[key] = self._overlay.get(key, 0.0) + seconds
            elif height > self._height:
                d = self._ov_pending.setdefault(height, {})
                d[key] = d.get(key, 0.0) + seconds

    def finish(self, height: int, wall_s: float,
               now: float | None = None) -> HeightTrace:
        """Seal the active trace (closing the open segment at `now`) and
        push it onto the ring."""
        self.mark("done", now=now)
        with self._ov_mtx:
            overlay, self._overlay = self._overlay, {}
        for k, v in overlay.items():
            self._aux[k] = self._aux.get(k, 0.0) + v
        end = self._probe()
        self._dev_carry = end  # the next begin() starts from this reading
        start = self._dev_start
        device: dict = {}
        for k in _DELTA_KEYS:
            if k in end or k in start:
                device[k] = end.get(k, 0) - start.get(k, 0)
        for k in end:
            if k not in _DELTA_KEYS:
                device[f"{k}_start"] = start.get(k)
                device[f"{k}_end"] = end.get(k)
        arrivals = dict(self._arrivals)
        tr = HeightTrace(height, dict(self._segments), dict(self._aux),
                         device, wall_s, max(self._rounds, 1),
                         arrivals=arrivals, started_at=self._started_wall)
        self._observe_arrivals(arrivals)
        with self._ring_mtx:
            self._ring.append(tr)
        return tr

    def _observe_arrivals(self, arrivals: dict) -> None:
        """Feed the height's arrival marks into the scrape-side
        distributions (consensus_quorum_seconds{phase},
        consensus_first_part_seconds). Failure-proof like the device
        probe: attribution must never wedge the receive routine."""
        if not arrivals:
            return
        try:
            hists = arrival_hists(self.metrics_registry)
            start = self._started_wall
            for phase in ("prevote", "precommit"):
                at = arrivals.get(f"{phase}_quorum")
                if at is not None:
                    hists["quorum"].labels(phase=phase).observe(
                        max(0.0, at - start)
                    )
            at = arrivals.get("first_block_part")
            if at is not None:
                hists["first_part"].observe(max(0.0, at - start))
        except Exception:  # noqa: BLE001
            pass

    def last(self, n: int = 10) -> list[HeightTrace]:
        """Newest-first slice of the completed-trace ring."""
        n = max(1, int(n))
        with self._ring_mtx:
            items = list(self._ring)
        return list(reversed(items))[:n]
