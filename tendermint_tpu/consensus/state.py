"""ConsensusState: the Tendermint BFT state machine
(reference: consensus/state.go — SURVEY.md §3.2 is the call-stack map).

One receive routine serializes ALL inputs — peer messages, our own
messages, timeouts, the mempool's txs-available signal — into a total
order, writes each to the WAL before acting, and drives the step cycle
NewHeight → NewRound → Propose → Prevote(+Wait) → Precommit(+Wait) →
Commit (consensus/state.go:604-659). That single-owner discipline is what
makes WAL replay deterministic.

TPU integration: gossiped vote signatures ride the round-16 VoteBatcher
(consensus/vote_batcher.py — the receive routine drains each queued run
into ONE `verifier.verify_batch_async` gateway call per (height, round,
type) group, per-lane verdicts popped by each add_vote; singletons take
the CPU latency path) and block validation's VerifyCommit rides
`verifier.commit_batch_verifier()` (wide batch → TPU kernel), both from
ops.gateway. Accept/reject semantics are identical to the reference's
sequential loops.

Pipelined execution (round 14, docs/execution-pipeline.md): with
``config.pipeline_apply`` (default on), finalize_commit stages the
height: stage 1 — validate, save the block, write the WAL ``#ENDHEIGHT``
marker — stays synchronous on this routine; stage 2 — ``sm.apply_block``
+ app Commit + snapshot hook + event flush — runs on a single ordered
executor thread (consensus/pipeline.py) while this routine advances to
H+1 over a PROVISIONAL next state (the no-valset-diff transform of
``set_block_and_validators``; its ``app_hash`` is still H−1's, which is
exactly what header H claims). The first H+1 step that actually needs
the applied state — entering propose, verifying a received proposal,
adding an H+1 vote — calls ``_join_apply()``, which blocks on the
deferred apply, swaps in the applied state, and (in the rare case a
valset diff landed) reconciles ``rs.validators``/``rs.votes`` before any
H+1 vote was verified (every vote path joins FIRST, so the provisional
set is never consulted for crypto). Replay and the FAIL_TEST_INDEX crash
model force the serial path — their determinism is single-thread by
construction (state/fail.py).

Test seams, as in the reference (consensus/state.go:222-226): the
decide_proposal / do_prevote / set_proposal methods are assignable, and
the ticker is injectable (MockTicker fires only NewHeight). Round 14
adds ``propose_time_source`` (height -> time_ns) so benches can pin
block times for cross-run byte-identity.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass

from tendermint_tpu.consensus import messages as msgs
from tendermint_tpu.consensus import pipeline as cpipeline
from tendermint_tpu.consensus import trace as ctrace
from tendermint_tpu.consensus import vote_batcher as cvb
from tendermint_tpu.consensus.height_vote_set import HeightVoteSet
from tendermint_tpu.consensus.round_state import RoundState, RoundStep
from tendermint_tpu.consensus.ticker import TickerI, TimeoutInfo, TimeoutTicker
from tendermint_tpu.consensus.wal import WAL, WALMessage
from tendermint_tpu.libs.events import EventCache, EventSwitch
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.ops import gateway
from tendermint_tpu.state import execution as sm
from tendermint_tpu.state.fail import fail_point
from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    Block,
    BlockID,
    ConflictingVotesError,
    Heartbeat,
    Proposal,
    Vote,
    VoteError,
    VoteSet,
)
from tendermint_tpu.types import events as tev
from tendermint_tpu.types.agg_commit import (
    AggregateCommit,
    AggregateLastCommit,
    commit_is_aggregate,
)
from tendermint_tpu.types.block import empty_commit
from tendermint_tpu.types.validator_set import CommitError
from tendermint_tpu.types.vote import UnexpectedStepError


@dataclass
class MsgInfo:
    msg: object
    peer_id: str = ""  # "" = internal (our own proposal/parts/votes)


class ConsensusState(BaseService):
    def __init__(
        self,
        config,
        state,
        proxy_app_conn,
        block_store,
        mempool,
        verifier: gateway.Verifier | None = None,
    ):
        super().__init__("ConsensusState")
        self.config = config
        self.proxy_app_conn = proxy_app_conn
        self.block_store = block_store
        self.mempool = mempool
        self.verifier = verifier or gateway.default_verifier()
        self.part_hasher = gateway.default_hasher()

        self.priv_validator = None
        self.rs = RoundState()
        self.state = None  # sm.State, set by update_to_state

        self.peer_msg_queue: queue.Queue = queue.Queue(maxsize=1000)
        self._peer_msg_drops = 0
        self._peer_msg_drop_logged = 0.0
        self._peer_drop_mtx = threading.Lock()
        self.internal_msg_queue: queue.Queue = queue.Queue(maxsize=1000)
        self.timeout_ticker: TickerI = TimeoutTicker()
        # combined input queue preserving the reference's select semantics
        self._inputs: queue.Queue = queue.Queue()

        self.wal: WAL | None = None
        self.replay_mode = False
        # post-apply hook (round 10): called synchronously after a block
        # applies, between Commit and the next height — the statesync
        # snapshot producer's interval point (node/node.py wires it)
        self.post_apply_hook = None
        self.done_height = threading.Event()  # pulses on each commit (tests)
        self.n_steps = 0
        # liveness observability (round 8): wall seconds per committed
        # height, last and max — the direct gauge for "a consensus round
        # stalled past its budget" (e.g. behind a sick device plane, the
        # exact regression the chaos soak guards), exported by the
        # metrics RPC as consensus_height_seconds_{last,max}
        self._height_started = time.monotonic()
        self.height_seconds_last = 0.0
        self.height_seconds_max = 0.0
        # per-height trace spans (round 11): the liveness gauges say a
        # height was slow, the recorder says WHERE the time went —
        # step-partitioned wall clock + device-vs-CPU attribution,
        # served by the consensus_trace RPC (consensus/trace.py)
        self.trace = ctrace.TraceRecorder(
            device_probe=self._trace_device_probe
        )
        # round 17 observability plane (node/node.py wires both; None in
        # bare harnesses — every site guards):
        # - txtrace: sampled per-tx lifecycle spans (libs/txtrace.py)
        # - flightrec: the black-box event ring (node/flightrec.py)
        self.txtrace = None
        self.flightrec = None
        # votes begin_add screened as already-seen — the 2NxN gossip
        # redundancy number the queued dedup PR needs a before for
        # (per-peer attribution rides p2p_peer_vote_duplicates_total)
        self.vote_duplicates = 0
        # gossiped votes genuinely ADDED (round 20): the denominator of
        # the duplicate-vote ratio duplicates/accepted that BENCH_r20
        # reads off scrapes — own re-delivered votes stay uncounted like
        # the duplicate side
        self.vote_accepted = 0
        # when each gossiped vote was ACCEPTED, by coordinates (round
        # 20): the reactor's lazy-relay screen holds re-pushes of a
        # just-received vote for one gossip tick so the origin's own
        # fan-out + the recipients' HasVote announcements win the race
        # (reactor._relay_ready). Own votes are never stamped — they
        # relay immediately.
        self.vote_recv_mono: dict[tuple, float] = {}
        # aggregate commit-proof plane (round 22, docs/upgrade.md):
        # catchup under the aggregate format ships whole commits, and a
        # lagging node finalizes from the proof instead of a VoteSet —
        # counted so an upgrade flip's catchup traffic is scrape-visible
        self.agg_commit_proofs = 0    # verified proofs accepted
        self.agg_commit_rejects = 0   # stale/forged/sub-quorum refused
        self.agg_commits_proposed = 0  # proposals built with an aggregate

        # pipelined execution plane (round 14): stage-2 (apply) rides an
        # ordered executor; the consensus thread holds at most ONE
        # pending apply (for rs.height - 1) and joins it at the first
        # H+1 step needing the applied state
        self.pipeline_apply = bool(getattr(config, "pipeline_apply", True))
        self._apply_executor: cpipeline.ApplyExecutor | None = None
        self._pending_apply: cpipeline.DeferredApply | None = None
        self._state_provisional = False  # self.state awaits the join
        self._apply_poisoned: BaseException | None = None
        self.pipeline_applies = 0      # heights committed via stage 2
        self.pipeline_serial_commits = 0
        self.pipeline_valset_reconciles = 0
        self.pipeline_join_wait_last = 0.0
        self.pipeline_overlap_last = 0.0
        # test/bench seam: height -> block time_ns for deterministic
        # cross-run block bytes (None = wall clock, the default)
        self.propose_time_source = None

        # big-committee vote plane (round 16, docs/committee.md): the
        # receive routine drains each run of gossiped votes into
        # per-(height, round, type) micro-batches — ONE gateway call per
        # group — and every add_vote pops its per-lane verdict.
        # vote_batching=False (bench A/B seam) restores the true
        # one-signature-at-a-time path; replay never batches (the WAL
        # feeds messages outside the receive routine's drain).
        self.vote_batching = True
        self.vote_batcher = cvb.VoteBatcher(lambda: self.verifier)

        # duplicate-vote evidence (beyond reference: state.go:1438-1447
        # punts with a TODO; we record validated pairs — types/evidence)
        from tendermint_tpu.types.evidence import EvidencePool

        self.evidence_pool = EvidencePool()

        self.evsw: EventSwitch | None = None

        # test seams (consensus/state.go:222-226)
        self.decide_proposal = self.default_decide_proposal
        self.do_prevote = self.default_do_prevote
        self.set_proposal = self.default_set_proposal

        self._thread: threading.Thread | None = None
        self._forwarders: list[threading.Thread] = []
        self._stopping = threading.Event()

        self.update_to_state(state)
        self.reconstruct_last_commit(state)

    # -- wiring ------------------------------------------------------------

    def set_event_switch(self, evsw: EventSwitch) -> None:
        self.evsw = evsw

    def set_priv_validator(self, pv) -> None:
        self.priv_validator = pv

    def set_timeout_ticker(self, ticker: TickerI) -> None:
        self.timeout_ticker = ticker

    def get_round_state(self) -> RoundState:
        return self.rs  # single-writer; readers treat as snapshot

    def height_age_s(self) -> float:
        """Seconds since the current height opened — the liveness signal
        the health plane (node/health.py) gates on: a stalled chain is a
        growing age, a healthy one resets every commit."""
        return time.monotonic() - self._height_started

    def pipeline_poisoned(self) -> bool:
        """True once a deferred apply failed — the node is wedged at the
        join and the health plane must report FAILING."""
        return self._apply_poisoned is not None

    def _trace_device_probe(self) -> dict:
        """Gateway counter snapshot for per-height device attribution
        (consensus/trace.py): how many verify sigs / hash leaves this
        height ran on-device vs on the CPU fallback, and the breaker
        state bracketing it. breaker_state -1 = no breaker (not the devd
        route)."""
        v = self.verifier.stats()
        h = self.part_hasher.stats()
        return {
            "verify_tpu_sigs": v.get("tpu_sigs", 0),
            "verify_cpu_sigs": v.get("cpu_sigs", 0),
            "hash_tpu_leaves": h.get("tpu_leaves", 0),
            "hash_cpu_leaves": h.get("cpu_leaves", 0),
            "breaker_opens": v.get("breaker_opens",
                                   h.get("breaker_opens", 0)),
            "breaker_state": v.get("breaker_state",
                                   h.get("breaker_state", -1)),
        }

    def is_proposer(self) -> bool:
        proposer = self.rs.validators.get_proposer()
        return (
            self.priv_validator is not None
            and proposer is not None
            and proposer.address == self.priv_validator.get_address()
        )

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self.wal is None and not self.replay_mode:
            self.open_wal(self.config.wal_file())
        self.timeout_ticker.start()
        self._stopping.clear()

        # WAL catchup BEFORE accepting new inputs (consensus/state.go:337-344).
        # A replay error (e.g. fresh WAL after fast sync, with no ENDHEIGHT
        # marker for our height) is logged and consensus starts anyway
        # (consensus/state.go:340-344 does exactly this).
        if self.wal is not None and not self.replay_mode:
            from tendermint_tpu.consensus.replay import catchup_replay

            try:
                catchup_replay(self, self.rs.height)
            except Exception:
                self.logger.exception(
                    "error on catchup replay; proceeding to start anyway"
                )

        self._start_forwarders()
        self._thread = threading.Thread(
            target=self.receive_routine, args=(0,), daemon=True, name="cs.receiveRoutine"
        )
        self._thread.start()
        # height clock starts when consensus starts CONSUMING, not at
        # construction — otherwise the first height's gauge absorbs
        # fast-sync/handshake/idle time and pins height_seconds_max to a
        # number that never measured a consensus round
        self._height_started = time.monotonic()
        self.trace.begin(self.rs.height, now=self._height_started)
        self.schedule_round_0(self.rs)

    def start_routines(self, max_steps: int = 0) -> None:
        """Test entry (consensus/state.go:363-370): start ticker +
        routines without WAL replay or round-0 scheduling."""
        self.timeout_ticker.start()
        self._stopping.clear()
        self._start_forwarders()
        self._thread = threading.Thread(
            target=self.receive_routine, args=(max_steps,), daemon=True,
            name="cs.receiveRoutine",
        )
        self._thread.start()
        self._height_started = time.monotonic()  # see on_start
        self.trace.begin(self.rs.height, now=self._height_started)

    # soft cap on peer-originated messages waiting in _inputs: beyond it
    # the PEER forwarder drops instead of growing the combined queue
    # without bound (a flooding peer would otherwise OOM a live node —
    # peer_msg_queue alone can't bound anything while its forwarder
    # drains it). Internal/timeout forwarders are never capped: the
    # receive routine itself enqueues internal messages, so blocking or
    # dropping THOSE could deadlock or corrupt the state machine.
    PEER_INPUT_BACKLOG_CAP = 2000

    def _start_forwarders(self) -> None:
        """Drain the three source queues into the combined input queue."""

        def fwd(src: queue.Queue, tag: str, peer_capped: bool = False):
            while not self._stopping.is_set():
                try:
                    item = src.get(timeout=0.1)
                except queue.Empty:
                    continue
                if item is None:
                    continue
                if peer_capped and self._inputs.qsize() >= self.PEER_INPUT_BACKLOG_CAP:
                    self._note_peer_drop(item)
                    continue
                self._inputs.put((tag, item))

        for src, tag, capped in (
            (self.peer_msg_queue, "msg", True),
            (self.internal_msg_queue, "msg", False),
            (self.timeout_ticker.chan, "timeout", False),
        ):
            t = threading.Thread(target=fwd, args=(src, tag, capped), daemon=True)
            t.start()
            self._forwarders.append(t)

        if hasattr(self.mempool, "enable_txs_available") and not self.config.create_empty_blocks:
            self.mempool.enable_txs_available(lambda: self._inputs.put(("txs_available", None)))

    def on_stop(self) -> None:
        self._stopping.set()
        self.timeout_ticker.stop()
        self._inputs.put(("quit", None))
        if self._thread:
            self._thread.join(timeout=5)
        # drain the deferred apply so state/app land on a consistent
        # height for the restart handshake; a wedged app is abandoned
        # (bounded wait — shutdown never blocks on a stuck apply, the
        # executor thread is a daemon)
        pending = self._pending_apply
        if pending is not None:
            if not pending.wait(timeout=10):
                self.logger.warning(
                    "deferred apply of %d still running at stop; abandoning",
                    pending.height,
                )
            self._pending_apply = None
        if self._apply_executor is not None:
            self._apply_executor.stop(timeout=2)
            self._apply_executor = None
        if self.wal is not None:
            self.wal.stop()

    def open_wal(self, wal_file: str) -> None:
        wal = WAL(
            wal_file,
            light=self.config.wal_light,
            flush_interval_s=self.config.wal_flush_interval_s,
            sync_every_write=self.config.wal_sync_every_write,
        )
        wal.start()
        self.wal = wal

    # -- queues ------------------------------------------------------------

    def send_internal_message(self, mi: MsgInfo) -> None:
        self.internal_msg_queue.put(mi)

    # every peer-originated enqueue goes through _enqueue_peer_msg so the
    # bounded-wait invariant below cannot be bypassed by a sibling entry
    # point
    PEER_PUT_TIMEOUT = 0.5  # s

    def _enqueue_peer_msg(self, msg, peer_id: str) -> None:
        """Called (indirectly) from the peer RECV routine — must never
        wedge it. A bounded-timeout put gives a briefly-behind state
        machine time to drain (no message loss under transient pressure —
        important because gossip senders optimistically mark parts/votes
        as delivered and won't re-offer them within the round); only when
        the queue stays full past the timeout — a flooding peer or a
        stopped state machine — is the message dropped. An UNbounded put
        here wedges the recv routine, freezes the whole multiplexed
        connection, and hands any flooding peer a denial-of-service lever
        (found via the fast-sync stall flake: a stopped consensus state
        filled the queue, the blocked put froze the peer, and both sides
        eventually dropped 'stream closed'). Drops are counted and logged
        at most once per 5s so the flood can't also spam the log."""
        try:
            self.peer_msg_queue.put(MsgInfo(msg, peer_id), timeout=self.PEER_PUT_TIMEOUT)
            return
        except queue.Full:
            self._note_peer_drop(MsgInfo(msg, peer_id))

    def _note_peer_drop(self, mi) -> None:
        """Count + rate-limited-log a dropped peer message (locked: drop
        sites run on concurrent peer recv/forwarder threads, and an
        unsynchronized read-modify-write would undercount exactly during
        the floods the counter exists to observe)."""
        with self._peer_drop_mtx:
            self._peer_msg_drops += 1
            drops = self._peer_msg_drops
            now = time.monotonic()
            if now - self._peer_msg_drop_logged <= 5.0:
                return
            self._peer_msg_drop_logged = now
        self.logger.warning(
            "peer message backlog full; dropped %d total (latest: %s from %.8s)",
            drops, type(mi.msg).__name__, mi.peer_id,
        )

    def add_peer_message(self, msg, peer_id: str) -> None:
        self._enqueue_peer_msg(msg, peer_id)

    @property
    def peer_msg_drops(self) -> int:
        """Messages dropped by the ingress backpressure (/metrics)."""
        return self._peer_msg_drops

    def set_proposal_msg(self, proposal: Proposal, peer_id: str = "") -> None:
        m = msgs.ProposalMessage(proposal)
        if peer_id:
            self._enqueue_peer_msg(m, peer_id)
        else:
            self.internal_msg_queue.put(MsgInfo(m, peer_id))

    def add_vote_msg(self, vote: Vote, peer_id: str = "") -> None:
        m = msgs.VoteMessage(vote)
        if peer_id:
            self._enqueue_peer_msg(m, peer_id)
        else:
            self.internal_msg_queue.put(MsgInfo(m, peer_id))

    # -- state sync --------------------------------------------------------

    def reconstruct_last_commit(self, state) -> None:
        """Rebuild rs.last_commit from the block store's seen commit
        (consensus/state.go:407-429)."""
        if state.last_block_height == 0:
            return
        seen_commit = self.block_store.load_seen_commit(state.last_block_height)
        if seen_commit is None:
            raise RuntimeError(
                f"failed to reconstruct last commit; seen commit for height {state.last_block_height} missing"
            )
        if commit_is_aggregate(seen_commit):
            # fast-sync/statesync stored the NEXT block's aggregate
            # last_commit as the seen commit — there are no individual
            # precommits to rebuild a VoteSet from. Verify the aggregate
            # against the signing set and install it as the last-commit
            # stand-in: proposing at the next height emits it verbatim
            # (the schedule requires the aggregate form there anyway)
            try:
                seen_commit.verify(state.chain_id, state.last_validators)
            except CommitError as exc:
                raise RuntimeError(
                    f"failed to reconstruct last commit; stored aggregate "
                    f"for height {state.last_block_height} is invalid: {exc}"
                )
            self.rs.last_commit = AggregateLastCommit(
                seen_commit, state.last_validators
            )
            return
        last_precommits = VoteSet(
            state.chain_id,
            state.last_block_height,
            seen_commit.round_(),
            VOTE_TYPE_PRECOMMIT,
            state.last_validators,
        )
        # one gateway batch for the whole seen commit (round 16): each
        # add_vote's verify_one below pops its primed lane instead of
        # paying a cold-start serial verify per precommit
        items = []
        sb_cache: dict[bytes, bytes] = {}  # quorum = ONE canonical payload
        for pc in seen_commit.precommits:
            if pc is None or pc.signature is None:
                continue
            _, val = state.last_validators.get_by_index(pc.validator_index)
            if val is not None:
                sbk = pc.block_id.key()
                sb = sb_cache.get(sbk)
                if sb is None:
                    sb = sb_cache[sbk] = pc.sign_bytes(state.chain_id)
                items.append((val.pub_key.raw, sb, pc.signature.raw))
        if len(items) >= 2:
            self.verifier.prime_cache(items)
        for pc in seen_commit.precommits:
            if pc is None:
                continue
            added = last_precommits.add_vote(pc, verifier=self.verifier.vote_verifier())
            if not added:
                raise RuntimeError("failed to reconstruct last commit: vote not added")
        if not last_precommits.has_two_thirds_majority():
            raise RuntimeError("failed to reconstruct last commit: no +2/3")
        self.rs.last_commit = last_precommits

    def update_to_state(self, state) -> None:
        """Reset RoundState for the next height (consensus/state.go:432-488)."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height != state.last_block_height:
            raise RuntimeError(
                f"update_to_state expected state height {rs.height}, got {state.last_block_height}"
            )
        if self.state is not None and self.state.last_block_height + 1 != rs.height:
            raise RuntimeError(
                f"inconsistent internal state: {self.state.last_block_height + 1} vs cs height {rs.height}"
            )
        # ignore stale states (consensus/state.go:449-455)
        if self.state is not None and state.last_block_height <= self.state.last_block_height:
            self.logger.debug("ignoring update_to_state for stale height")
            return

        validators = state.validators
        # the +2/3 precommits we just committed with become the next
        # height's last_commit (consensus/state.go:457-464); on cold start
        # (commit_round == -1) reconstruct_last_commit fills it instead
        last_precommits = None
        if rs.commit_round > -1 and rs.votes is not None:
            pc = rs.votes.precommits(rs.commit_round)
            if pc is not None and pc.has_two_thirds_majority():
                last_precommits = pc
            elif rs.commit_proof is not None:
                # finalized from an aggregate commit proof (catchup under
                # the aggregate format): the proof, already verified, IS
                # the last commit — wrapped so H+1 proposing works
                last_precommits = AggregateLastCommit(
                    rs.commit_proof, state.last_validators
                )
            else:
                raise RuntimeError("update_to_state called but last precommit round lacks +2/3")

        height = state.last_block_height + 1
        rs.height = height
        rs.round_ = 0
        rs.step = RoundStep.NEW_HEIGHT
        if rs.commit_time == 0:
            rs.start_time = time.time() + self.config.timeout_commit
        else:
            rs.start_time = rs.commit_time + self.config.timeout_commit
        rs.commit_time = 0.0
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.commit_proof = None
        rs.last_commit = last_precommits
        rs.last_validators = state.last_validators
        self.state = state
        self.new_step()

    def new_step(self) -> None:
        rs_event = self.rs.round_state_event()
        if self.wal is not None:
            self.wal.save(WALMessage.event_round_state(rs_event))
        self.n_steps += 1
        # step transitions drive the height trace's segment clock
        # (single-writer: only this receive routine marks)
        self.trace.mark(ctrace.step_segment(self.rs.step))
        self.trace.note_round(self.rs.round_)
        fr = self.flightrec
        if fr is not None:
            # the flight ring's progress spine: a wedge reads as these
            # freezing at one height (node/flightrec.py)
            fr.record("step", height=self.rs.height, round=self.rs.round_,
                      step=int(self.rs.step))
        if self.evsw is not None:
            self.evsw.fire_event(tev.EVENT_NEW_ROUND_STEP, rs_event)

    # -- the receive routine ----------------------------------------------

    def receive_routine(self, max_steps: int) -> None:
        """consensus/state.go:609-659. max_steps=0 means run forever.

        An exception ESCAPING this routine kills the consensus thread —
        the node is dead from that instant, silently. The flight
        recorder captures the crash and dumps the ring first (round 17),
        so the post-mortem artifact exists even when nobody was
        watching; the exception still propagates (the thread must not
        limp on)."""
        try:
            self._receive_routine(max_steps)
        except BaseException as exc:
            fr = self.flightrec
            if fr is not None:
                fr.note_exception("consensus", exc)
            raise

    def _receive_routine(self, max_steps: int) -> None:
        steps = 0
        while True:
            if max_steps > 0 and steps >= max_steps:
                self.logger.debug("receive_routine reached max_steps")
                return
            try:
                tag, item = self._inputs.get(timeout=0.5)
            except queue.Empty:
                if self._stopping.is_set():
                    return
                continue
            if tag == "quit":
                return
            # When a vote heads a burst, drain the already-queued run and
            # batch-verify the signatures ahead of dispatch (SURVEY §7;
            # round 16 groups per (height, round, type) through the
            # VoteBatcher): each item is then handled strictly in order —
            # WAL layout and observable accept/reject are identical to
            # one-at-a-time — but the signature work rode one batched
            # gateway call per group.
            batch = [(tag, item)]
            if max_steps == 0 and tag == "msg" and isinstance(item.msg, msgs.VoteMessage):
                while len(batch) < 512:
                    try:
                        batch.append(self._inputs.get_nowait())
                    except queue.Empty:
                        break
                try:
                    if self.vote_batching and not self.replay_mode:
                        self.vote_batcher.prepare(
                            [
                                i.msg.vote
                                for t, i in batch
                                if t == "msg" and isinstance(i.msg, msgs.VoteMessage)
                            ],
                            self.rs,
                            self.state.chain_id,
                        )
                except Exception:
                    # batching is purely an accelerator over adversarial
                    # input — it must never kill the receive routine
                    self.logger.exception("vote verify-ahead failed; falling through")
            for tag, item in batch:
                if tag == "quit":
                    return
                steps += 1
                try:
                    if tag == "msg":
                        mi: MsgInfo = item
                        if self.wal is not None:
                            self.wal.save(WALMessage.msg_info(mi.msg, mi.peer_id))
                        self.handle_msg(mi)
                    elif tag == "timeout":
                        ti: TimeoutInfo = item
                        if self.wal is not None:
                            self.wal.save(WALMessage.timeout(ti))
                        self.handle_timeout(ti)
                    elif tag == "txs_available":
                        self.handle_txs_available(self.rs.height)
                except Exception:
                    self.logger.exception("error in receive routine handling %s", tag)

    def handle_msg(self, mi: MsgInfo) -> None:
        """consensus/state.go:662-698."""
        msg, peer_id = mi.msg, mi.peer_id
        if isinstance(msg, msgs.ProposalMessage):
            self.set_proposal(msg.proposal)
        elif isinstance(msg, msgs.BlockPartMessage):
            self.add_proposal_block_part(msg.height, msg.part, verify=bool(peer_id))
        elif isinstance(msg, msgs.VoteMessage):
            self.try_add_vote(msg.vote, peer_id)
        elif isinstance(msg, msgs.AggregateCommitMessage):
            self.apply_commit_proof(msg.commit, peer_id)
        else:
            self.logger.warning("unknown msg type %r", type(msg))

    def handle_timeout(self, ti: TimeoutInfo) -> None:
        """consensus/state.go:701-745."""
        rs = self.rs
        if ti.height != rs.height or ti.round_ < rs.round_ or (
            ti.round_ == rs.round_ and ti.step < rs.step
        ):
            self.logger.debug("ignoring tock because we're ahead: %s", ti)
            return
        if ti.step == RoundStep.NEW_HEIGHT:
            self.enter_new_round(ti.height, 0)
        elif ti.step == RoundStep.NEW_ROUND:
            self.enter_propose(ti.height, 0)
        elif ti.step == RoundStep.PROPOSE:
            self._fire(tev.EVENT_TIMEOUT_PROPOSE, rs.round_state_event())
            self.enter_prevote(ti.height, ti.round_)
        elif ti.step == RoundStep.PREVOTE_WAIT:
            self._fire(tev.EVENT_TIMEOUT_WAIT, rs.round_state_event())
            self.enter_precommit(ti.height, ti.round_)
        elif ti.step == RoundStep.PRECOMMIT_WAIT:
            self._fire(tev.EVENT_TIMEOUT_WAIT, rs.round_state_event())
            self.enter_new_round(ti.height, ti.round_ + 1)
        else:
            raise ValueError(f"invalid timeout step {ti.step}")

    def handle_txs_available(self, height: int) -> None:
        """consensus/state.go:747-750."""
        self.enter_propose(height, 0)

    def _fire(self, event: str, data) -> None:
        if self.evsw is not None:
            self.evsw.fire_event(event, data)

    def _schedule_timeout(self, duration: float, height: int, round_: int, step: int) -> None:
        self.timeout_ticker.schedule_timeout(TimeoutInfo(duration, height, round_, step))

    def schedule_round_0(self, rs: RoundState) -> None:
        sleep = max(0.0, rs.start_time - time.time())
        self._schedule_timeout(sleep, rs.height, 0, RoundStep.NEW_HEIGHT)

    # -- step: new round ---------------------------------------------------

    def enter_new_round(self, height: int, round_: int) -> None:
        """consensus/state.go:753-804."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step != RoundStep.NEW_HEIGHT
        ):
            self.logger.debug(
                "enter_new_round(%d/%d): invalid args, currently %d/%d/%d",
                height, round_, rs.height, rs.round_, rs.step,
            )
            return
        self.logger.info("enter_new_round(%d/%d)", height, round_)

        if round_ != 0:
            # later rounds copy + re-accum the validator set: that must
            # be the APPLIED set, not the provisional one
            self._join_apply("new_round")

        validators = rs.validators
        if rs.round_ < round_:
            validators = validators.copy()
            validators.increment_accum(round_ - rs.round_)

        rs.round_ = round_
        rs.step = RoundStep.NEW_ROUND
        rs.validators = validators
        if round_ != 0:
            # round 0 keeps proposal from NewHeight setup; later rounds reset
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)  # track next-round votes too

        self._fire(tev.EVENT_NEW_ROUND, rs.round_state_event())

        # no-empty-blocks: wait for txs before proposing (state.go:786-803)
        wait_for_txs = (
            not self.config.create_empty_blocks and round_ == 0 and not self.need_proof_block(height)
        )
        if wait_for_txs:
            if self.config.create_empty_blocks_interval > 0:
                self._schedule_timeout(
                    self.config.create_empty_blocks_interval, height, round_, RoundStep.NEW_ROUND
                )
            if self.mempool.size() > 0:
                # txs already waiting — the one-shot signal may have fired
                # before we subscribed at this height
                self.enter_propose(height, round_)
            elif not self.replay_mode:
                self._maybe_start_heartbeat(height, round_)
        else:
            self.enter_propose(height, round_)

    def need_proof_block(self, height: int) -> bool:
        """Propose an empty block anyway if the app hash changed — it
        "proves" the app results (consensus/state.go:806-816)."""
        if height == 1:
            return True
        self._join_apply("need_proof_block")  # reads the applied app_hash
        last_block_meta = self.block_store.load_block_meta(height - 1)
        if last_block_meta is None:
            return False
        return self.state.app_hash != last_block_meta.header.app_hash

    def _maybe_start_heartbeat(self, height: int, round_: int) -> None:
        """Proposer liveness beacon while waiting for txs
        (consensus/state.go:818-848)."""
        if self.priv_validator is None or not self.is_proposer():
            return

        def beat():
            counter = 0
            addr = self.priv_validator.get_address()
            while self.is_running():
                rs = self.rs
                if rs.height != height or rs.round_ != round_ or rs.step != RoundStep.NEW_ROUND:
                    return
                val_index, _ = rs.validators.get_by_address(addr)
                hb = Heartbeat(
                    validator_address=addr,
                    validator_index=val_index,
                    height=height,
                    round_=round_,
                    sequence=counter,
                )
                hb = self.priv_validator.sign_heartbeat(self.state.chain_id, hb)
                self._fire(tev.EVENT_PROPOSAL_HEARTBEAT, tev.EventDataProposalHeartbeat(hb))
                counter += 1
                time.sleep(self.config.peer_gossip_sleep_duration * 2)

        threading.Thread(target=beat, daemon=True, name="cs.heartbeat").start()

    # -- step: propose -----------------------------------------------------

    def enter_propose(self, height: int, round_: int) -> None:
        """consensus/state.go:850-895."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStep.PROPOSE
        ):
            return
        self.logger.info("enter_propose(%d/%d)", height, round_)
        # propose is THE join point of the deferred-app-hash contract:
        # everything from here on (our proposal's header, proposer
        # selection, proposal/vote verification) reads the applied state
        self._join_apply("propose")

        def defer_():
            rs.round_ = round_
            rs.step = RoundStep.PROPOSE
            self.new_step()
            if self.is_proposal_complete():
                self.enter_prevote(height, rs.round_)

        self._schedule_timeout(self.config.propose(round_), height, round_, RoundStep.PROPOSE)

        if self.priv_validator is not None and self.is_proposer():
            self.decide_proposal(height, round_)
        defer_()

    def default_decide_proposal(self, height: int, round_: int) -> None:
        """consensus/state.go:897-944."""
        rs = self.rs
        if rs.locked_block is not None:
            block, block_parts = rs.locked_block, rs.locked_block_parts
        else:
            block, block_parts = self.create_proposal_block()
            if block is None:
                return  # nothing to propose (no txs and no commit yet)

        pol_round, pol_block_id = rs.votes.pol_info()
        proposal = Proposal(
            height=height,
            round_=round_,
            block_parts_header=block_parts.header(),
            pol_round=pol_round,
            pol_block_id=pol_block_id or BlockID(),
        )
        try:
            proposal = self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception:
            if not self.replay_mode:
                self.logger.exception("enter_propose: error signing proposal")
            return

        self.send_internal_message(MsgInfo(msgs.ProposalMessage(proposal)))
        for i in range(block_parts.total):
            part = block_parts.get_part(i)
            self.send_internal_message(MsgInfo(msgs.BlockPartMessage(rs.height, rs.round_, part)))
        self.logger.info("signed proposal %d/%d", height, round_)

    def is_proposal_complete(self) -> bool:
        """consensus/state.go:946-957."""
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def create_proposal_block(self):
        """consensus/state.go:959-985: reap mempool, build block+parts.
        PartSet leaf hashing routes through the TPU hasher."""
        rs = self.rs
        # the header needs the applied app_hash, and the reap must run
        # AFTER the deferred apply's mempool.update(H) — joining first
        # covers both (the mempool-lock-scope invariant,
        # docs/execution-pipeline.md)
        self._join_apply("create_proposal")
        if rs.height == 1:
            commit = empty_commit()
        elif rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            commit = self._commit_for_proposal(rs.last_commit.make_commit())
        else:
            self.logger.error("propose without last commit (+2/3 missing)")
            return None, None
        txs = self.mempool.reap(self.config.max_block_size_txs)
        if self.txtrace is not None:
            # lifecycle mark: reaped into OUR proposal (a non-proposer
            # stamps the same stage when the gossiped proposal block
            # completes — add_proposal_block_part)
            self.txtrace.stamp_present(txs, "proposal")
        t0 = time.perf_counter()
        # submitted-early future: the tx root starts hashing on the hash
        # plane NOW, overlapping commit/evidence/header assembly below;
        # Data.hash() joins it inside make_block (gateway in-flight table)
        submit_tx_root = getattr(self.part_hasher, "submit_tx_root", None)
        if submit_tx_root is not None and len(txs) >= 2:
            submit_tx_root([bytes(t) for t in txs])
        time_ns = None
        if self.propose_time_source is not None:
            time_ns = self.propose_time_source(rs.height)
        try:
            return Block.make_block(
                height=rs.height,
                chain_id=self.state.chain_id,
                txs=txs,
                commit=commit,
                prev_block_id=self.state.last_block_id,
                val_hash=self.state.validators.hash(),
                app_hash=self.state.app_hash,
                part_size=self.state.params().block_gossip.block_part_size_bytes,
                time_ns=time_ns,
                part_hasher=self.part_hasher.part_leaf_hashes,
                # proposal part sets: leaf digests + the whole proof tree in
                # one offload pass when the hash plane serves (devd
                # hash_stream tree frame); None -> the flat host builder.
                # Round 14: submitted as a future so the device round trip
                # overlaps Part construction (types/part_set.py)
                part_tree_hasher=self.part_hasher.part_set_tree,
                part_tree_submitter=getattr(
                    self.part_hasher, "submit_part_set_tree", None
                ),
                # drain detected-but-uncommitted double-signs into the
                # proposal: one detecting node puts the proof ON CHAIN
                # for everyone (types/evidence.py round 12; a block may
                # only carry evidence STRICTLY older than itself)
                evidence=self.evidence_pool.pending(before_height=rs.height),
            )
        finally:
            # overlapping attribution: block build (part hashing + tx
            # root) happens INSIDE the propose segment, so it rides the
            # trace's aux table, never the segment sum
            self.trace.note("part_hash_s", time.perf_counter() - t0)

    def _commit_for_proposal(self, commit):
        """The last_commit section in the format the chain's schedule
        requires at rs.height (genesis commit_format_at, docs/upgrade.md):
        the quorum half-aggregates into an AggregateCommit when the
        aggregate format is active, and passes through untouched below
        the upgrade height — the proposer is where the cutover actually
        happens on a live net."""
        gd = getattr(self.state, "genesis_doc", None)
        if gd is None or not gd.aggregate_commits_at(self.rs.height):
            return commit
        if commit_is_aggregate(commit):
            return commit  # AggregateLastCommit.make_commit() already is
        if not commit.is_commit():
            return commit  # empty (height 1); schedule never aggregates it
        agg = AggregateCommit.from_commit(
            commit, self.state.chain_id, self.rs.last_validators
        )
        self.agg_commits_proposed += 1
        if self.agg_commits_proposed == 1 and self.flightrec is not None:
            # the flip itself, in the black box: this proposer just built
            # its first aggregate last-commit (height == upgrade_height on
            # a clean flip)
            self.flightrec.record(
                "upgrade_flip", height=self.rs.height,
                signers=agg.num_signers(), of=agg.size(),
            )
        return agg

    # -- step: prevote -----------------------------------------------------

    def enter_prevote(self, height: int, round_: int) -> None:
        """consensus/state.go:987-1017."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStep.PREVOTE
        ):
            return
        self.logger.info("enter_prevote(%d/%d)", height, round_)

        # fire Polka event if we have one from a previous condition check
        self.do_prevote(height, round_)

        rs.round_ = round_
        rs.step = RoundStep.PREVOTE
        self.new_step()
        # wait for more prevotes; the 2/3-any case schedules prevote_wait

    def default_do_prevote(self, height: int, round_: int) -> None:
        """consensus/state.go:1019-1057."""
        rs = self.rs
        self._join_apply("prevote")  # validate_block reads self.state
        if rs.locked_block is not None:
            self.logger.info("prevote: locked block")
            self.sign_add_vote(VOTE_TYPE_PREVOTE, rs.locked_block.hash(), rs.locked_block_parts.header())
            return
        if rs.proposal_block is None:
            self.logger.info("prevote: proposal block is nil")
            self.sign_add_vote(VOTE_TYPE_PREVOTE, b"", None)
            return
        try:
            sm.validate_block(
                self.state, rs.proposal_block,
                batch_verifier=self.verifier.commit_batch_verifier(),
            )
        except sm.InvalidBlockError as e:
            self.logger.error("prevote: proposal block invalid: %s", e)
            self.sign_add_vote(VOTE_TYPE_PREVOTE, b"", None)
            return
        self.sign_add_vote(
            VOTE_TYPE_PREVOTE, rs.proposal_block.hash(), rs.proposal_block_parts.header()
        )

    def enter_prevote_wait(self, height: int, round_: int) -> None:
        """consensus/state.go:1059-1073."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStep.PREVOTE_WAIT
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            raise RuntimeError(f"enter_prevote_wait({height}/{round_}) without +2/3 prevotes")
        self.logger.info("enter_prevote_wait(%d/%d)", height, round_)
        rs.round_ = round_
        rs.step = RoundStep.PREVOTE_WAIT
        self.new_step()
        self._schedule_timeout(self.config.prevote(round_), height, round_, RoundStep.PREVOTE_WAIT)

    # -- step: precommit ---------------------------------------------------

    def enter_precommit(self, height: int, round_: int) -> None:
        """The locking logic (consensus/state.go:1075-1188)."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStep.PRECOMMIT
        ):
            return
        self.logger.info("enter_precommit(%d/%d)", height, round_)

        def defer_():
            rs.round_ = round_
            rs.step = RoundStep.PRECOMMIT
            self.new_step()

        prevotes = rs.votes.prevotes(round_)
        block_id = prevotes.two_thirds_majority() if prevotes else None

        # no +2/3 for anything: precommit nil
        if block_id is None:
            self.logger.info("precommit: no +2/3 prevotes; precommitting nil")
            self.sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", None)
            defer_()
            return

        self._fire(tev.EVENT_POLKA, rs.round_state_event())

        pol_round, _ = rs.votes.pol_info()
        if pol_round < round_:
            raise RuntimeError(f"POLRound {pol_round} < round {round_}")

        # +2/3 for nil: unlock if locked, precommit nil (state.go:1112-1126)
        if not block_id.hash:
            if rs.locked_block is None:
                self.logger.info("precommit: +2/3 prevoted nil")
            else:
                self.logger.info("precommit: +2/3 prevoted nil; unlocking")
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                self._fire(tev.EVENT_UNLOCK, rs.round_state_event())
            self.sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", None)
            defer_()
            return

        # +2/3 for the block we're locked on: relock (state.go:1130-1138)
        if rs.locked_block is not None and rs.locked_block.hashes_to(block_id.hash):
            self.logger.info("precommit: relocking")
            rs.locked_round = round_
            self._fire(tev.EVENT_RELOCK, rs.round_state_event())
            self.sign_add_vote(VOTE_TYPE_PRECOMMIT, block_id.hash, block_id.parts_header)
            defer_()
            return

        # +2/3 for the proposal block: lock it (state.go:1142-1157)
        if rs.proposal_block is not None and rs.proposal_block.hashes_to(block_id.hash):
            try:
                sm.validate_block(
                    self.state, rs.proposal_block,
                    batch_verifier=self.verifier.commit_batch_verifier(),
                )
            except sm.InvalidBlockError as e:
                raise RuntimeError(f"enter_precommit: +2/3 prevoted an invalid block: {e}")
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            self._fire(tev.EVENT_LOCK, rs.round_state_event())
            self.sign_add_vote(VOTE_TYPE_PRECOMMIT, block_id.hash, block_id.parts_header)
            defer_()
            return

        # +2/3 for a block we don't have: unlock, fetch it (state.go:1160-1177)
        self.logger.info("precommit: +2/3 for unknown block; unlocking and precommitting nil")
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
            block_id.parts_header
        ):
            rs.proposal_block = None
            from tendermint_tpu.types import PartSet

            rs.proposal_block_parts = PartSet.from_header(block_id.parts_header)
        self._fire(tev.EVENT_UNLOCK, rs.round_state_event())
        self.sign_add_vote(VOTE_TYPE_PRECOMMIT, b"", None)
        defer_()

    def enter_precommit_wait(self, height: int, round_: int) -> None:
        """consensus/state.go:1190-1204."""
        rs = self.rs
        if rs.height != height or round_ < rs.round_ or (
            rs.round_ == round_ and rs.step >= RoundStep.PRECOMMIT_WAIT
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            raise RuntimeError(f"enter_precommit_wait({height}/{round_}) without +2/3 precommits")
        self.logger.info("enter_precommit_wait(%d/%d)", height, round_)
        rs.round_ = round_
        rs.step = RoundStep.PRECOMMIT_WAIT
        self.new_step()
        self._schedule_timeout(self.config.precommit(round_), height, round_, RoundStep.PRECOMMIT_WAIT)

    # -- step: commit ------------------------------------------------------

    def enter_commit(self, height: int, commit_round: int) -> None:
        """consensus/state.go:1206-1258."""
        rs = self.rs
        if rs.height != height or rs.step >= RoundStep.COMMIT:
            return
        self.logger.info("enter_commit(%d/%d)", height, commit_round)

        def defer_():
            rs.step = RoundStep.COMMIT
            rs.commit_round = commit_round
            rs.commit_time = time.time()
            self.new_step()
            self.try_finalize_commit(height)

        block_id = rs.votes.precommits(commit_round).two_thirds_majority()
        if block_id is None:
            raise RuntimeError("enter_commit expects +2/3 precommits")

        # locked block takes priority if it IS the committed block
        if rs.locked_block is not None and rs.locked_block.hashes_to(block_id.hash):
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or not rs.proposal_block.hashes_to(block_id.hash):
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.parts_header
            ):
                self.logger.info("commit is for a block we don't know about; fetching")
                rs.proposal_block = None
                from tendermint_tpu.types import PartSet

                rs.proposal_block_parts = PartSet.from_header(block_id.parts_header)
        defer_()

    def apply_commit_proof(self, agg, peer_id: str = "") -> bool:
        """Adopt a received AggregateCommit as this height's commit
        proof (the aggregate-format catchup path, docs/upgrade.md): the
        reactor already crypto-verified it against rs.validators before
        enqueueing, but the consensus thread re-verifies here — the WAL
        replays this message, and replay must re-derive every verdict
        rather than trust the recorded one. On success the height
        finalizes exactly like enter_commit, with the proof standing in
        for the +2/3 VoteSet."""
        rs = self.rs
        if agg.height() != rs.height or rs.step >= RoundStep.COMMIT:
            return False  # stale or already committing — not an error
        err = agg.validate_basic()
        if err is None:
            self._join_apply("commit_proof")
            try:
                agg.verify(self.state.chain_id, rs.validators)
            except CommitError as exc:
                err = str(exc)
        if err is not None:
            self.agg_commit_rejects += 1
            if self.flightrec is not None:
                self.flightrec.record(
                    "agg_commit_reject", height=agg.height(),
                    err=err, peer=peer_id or "self",
                )
            self.logger.warning(
                "rejected aggregate commit proof from %s: %s",
                peer_id or "self", err,
            )
            return False
        self.agg_commit_proofs += 1
        rs.commit_proof = agg
        if self.flightrec is not None:
            self.flightrec.record(
                "agg_commit_proof", height=agg.height(),
                signers=agg.num_signers(), peer=peer_id or "self",
            )
        self.logger.info(
            "commit proof at height %d: aggregate of %d/%d signers",
            rs.height, agg.num_signers(), agg.size(),
        )
        # adopt the committed block id (enter_commit's fetch logic)
        if rs.locked_block is not None and rs.locked_block.hashes_to(agg.block_id.hash):
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or not rs.proposal_block.hashes_to(agg.block_id.hash):
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                agg.block_id.parts_header
            ):
                rs.proposal_block = None
                from tendermint_tpu.types import PartSet

                rs.proposal_block_parts = PartSet.from_header(agg.block_id.parts_header)
        rs.step = RoundStep.COMMIT
        rs.commit_round = agg.round_()
        rs.commit_time = time.time()
        self.new_step()
        self.try_finalize_commit(rs.height)
        return True

    def _committed_block_id(self):
        """The BlockID this height commits to: the commit proof's when
        one was adopted (aggregate catchup), else the +2/3 precommit
        majority of the commit round."""
        rs = self.rs
        if rs.commit_proof is not None:
            return rs.commit_proof.block_id
        pc = rs.votes.precommits(rs.commit_round) if rs.votes is not None else None
        return pc.two_thirds_majority() if pc is not None else None

    def try_finalize_commit(self, height: int) -> None:
        """consensus/state.go:1236-1256."""
        rs = self.rs
        if rs.height != height:
            raise RuntimeError("try_finalize_commit: height mismatch")
        block_id = self._committed_block_id()
        if block_id is None or not block_id.hash:
            return
        if rs.proposal_block is None or not rs.proposal_block.hashes_to(block_id.hash):
            return  # haven't received the full block yet
        self.finalize_commit(height)

    def finalize_commit(self, height: int) -> None:
        """Save the block, write the WAL marker, apply via the execution
        pipeline, move to the next height (consensus/state.go:1258-1355).

        Round 14: stage 1 (validate + block save + #ENDHEIGHT) is always
        synchronous here; stage 2 (apply + snapshot hook + events) is
        deferred to the apply executor when the pipeline is enabled, and
        joined by the first H+1 step that needs the applied state."""
        rs = self.rs
        if rs.height != height or rs.step != RoundStep.COMMIT:
            return
        # a pending apply here means H-1's stage 2 is still in flight
        # while H fully committed — impossible via the vote path (every
        # H-vote joins first), but replay/test seams can call directly
        self._join_apply("finalize")
        block_id = self._committed_block_id()
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        if block_id is None or not block.hashes_to(block_id.hash):
            raise RuntimeError("cannot finalize: proposal block does not hash to commit hash")
        sm.validate_block(
            self.state, block, batch_verifier=self.verifier.commit_batch_verifier()
        )
        self.logger.info(
            "finalizing commit of block %d: hash=%s txs=%d",
            height, block.hash().hex()[:12], block.header.num_txs,
        )
        # gossip arrival mark (round 15): commit receipt — quorum AND the
        # full block are in hand; the fleet aggregator reads commit skew
        # off this instant across nodes
        self.trace.mark_arrival("commit")
        # trace: the commit-wait segment ends here; the finalize
        # sub-phases (save -> apply -> snapshot hook -> events, or
        # save -> submit when pipelined) partition the rest of the
        # height's wall time
        self.trace.mark("block_save")

        fail_point()

        if self.block_store.height() < block.header.height:
            if rs.commit_proof is not None:
                # catchup finalize: the verified aggregate IS the seen
                # commit (SC:h stores whatever quorum form was observed)
                seen_commit = rs.commit_proof
            else:
                seen_commit = rs.votes.precommits(rs.commit_round).make_commit()
            self.block_store.save_block(block, block_parts, seen_commit)
        # else: already saved (e.g. during replay); proceed to apply

        fail_point()

        if self.wal is not None:
            self.wal.write_end_height(height)
            if self.flightrec is not None:
                # the durability mark: everything before this instant
                # survives a power failure (docs/crash-recovery.md)
                self.flightrec.record("wal_endheight", height=height)

        fail_point()

        if self.txtrace is not None:
            # lifecycle mark: the block carrying a traced tx is now
            # chain history (stage 1 done — marker on disk); also
            # resets the first-K-per-height sampling window
            self.txtrace.commit(block.data.txs, height)

        state_copy = self.state.copy()
        event_cache = EventCache(self.evsw) if self.evsw is not None else _NullCache()

        if self._pipeline_enabled():
            # the committed block's evidence section is chain history the
            # moment the marker lands: never re-propose it (independent
            # of the apply; validated above in validate_block)
            if block.evidence.evidence:
                self.evidence_pool.mark_committed(block.evidence.evidence)
            # provisional state FIRST: the submit hands state_copy to the
            # executor, whose apply_block mutates it — copying after the
            # submit would race set_block_and_validators (a torn copy
            # double-rotates accum: same valset hash, wrong proposer)
            next_state = self._provisional_next_state(
                state_copy, block, block_parts
            )
            self._submit_deferred_apply(
                height, state_copy, event_cache, block, block_parts
            )
        else:
            self.pipeline_serial_commits += 1
            self.trace.mark("apply")
            sm.apply_block(
                state_copy,
                event_cache,
                self.proxy_app_conn,
                block,
                block_parts.header(),
                self.mempool,
                batch_verifier=self.verifier.commit_batch_verifier(),
            )

            fail_point()

            # the committed block's evidence section is now chain history:
            # never re-propose it, and adopt pieces other nodes detected
            # (validated above in validate_block)
            if block.evidence.evidence:
                self.evidence_pool.mark_committed(block.evidence.evidence)

            self._post_apply_tail(
                state_copy, block, event_cache, height, mark_trace=True
            )

            fail_point()
            next_state = state_copy

        now = time.monotonic()
        self.height_seconds_last = now - self._height_started
        self.height_seconds_max = max(
            self.height_seconds_max, self.height_seconds_last
        )
        self._height_started = now
        cpipeline.pipeline_hists()["height"].observe(self.height_seconds_last)
        # seal this height's trace on the SAME clock reading the gauge
        # used (segments must sum to height_seconds_last), then start
        # the next height's
        self.trace.finish(height, self.height_seconds_last, now=now)
        self.trace.begin(height + 1, now=now)

        self.update_to_state(next_state)
        self._state_provisional = self._pending_apply is not None
        self.done_height.set()
        self.done_height.clear()
        self.schedule_round_0(self.rs)

    # -- the pipelined execution plane (round 14) -------------------------

    def _pipeline_enabled(self) -> bool:
        """Stage-2 deferral policy. Replay is serial by contract (the WAL
        is a single-thread total order), and the legacy FAIL_TEST_INDEX
        crash model counts fail_point() hits on ONE thread — arming it
        forces the serial path so the i-th hit stays deterministic
        (state/fail.py; the pipeline's own crash boundaries are the named
        pipeline_point() tier)."""
        return (
            self.pipeline_apply
            and not self.replay_mode
            and os.environ.get("FAIL_TEST_INDEX") is None
        )

    def _submit_deferred_apply(
        self, height: int, state_copy, event_cache, block, block_parts
    ) -> None:
        """Stage 2: apply + app Commit + snapshot hook + events, on the
        ordered executor. The block save and WAL marker already landed —
        a crash before the apply completes is the store==state+1 image
        the restart handshake replays (docs/execution-pipeline.md)."""
        if self._apply_executor is None:
            self._apply_executor = cpipeline.ApplyExecutor()
        parts_header = block_parts.header()
        batch_verifier = self.verifier.commit_batch_verifier()
        pending = cpipeline.DeferredApply(height)

        def run():
            from tendermint_tpu.state.fail import pipeline_point

            pipeline_point("pre_apply")
            t0 = time.monotonic()
            sm.apply_block(
                state_copy,
                event_cache,
                self.proxy_app_conn,
                block,
                parts_header,
                self.mempool,
                batch_verifier=batch_verifier,
            )
            pipeline_point("post_apply")
            apply_s = time.monotonic() - t0
            # resolve the join NOW: the consensus thread only needs the
            # applied state. The snapshot hook + event flush below run as
            # the executor's tail — off the critical path entirely (the
            # next height's apply queues behind them on this worker, so
            # the app-quiesce guarantee still holds; the snapshot hook
            # observes the app exactly at H because the next DeliverTx
            # can only come from the next queued apply)
            pending._finish(value=(state_copy, apply_s))
            # apply(H) ran under consensus of H+1: attribute it there
            self.trace.note_overlap(height + 1, "overlap_apply_s", apply_s)
            t1 = time.monotonic()
            self._post_apply_tail(
                state_copy, block, event_cache, height, mark_trace=False
            )
            self.trace.note_overlap(
                height + 1, "overlap_hook_s", time.monotonic() - t1
            )
            return state_copy, apply_s

        self._pending_apply = self._apply_executor.submit(pending, run)
        self.pipeline_applies += 1

    def _post_apply_tail(self, state_copy, block, event_cache, height: int,
                         mark_trace: bool) -> None:
        """The post-apply work both finalize modes share: snapshot hook
        (best-effort — a producer failure must never wedge consensus)
        then NewBlock/NewBlockHeader + the cached tx events, post-commit.
        Serial mode runs it inline with trace segment marks; pipelined
        mode runs it as the executor's tail (EventSwitch is
        lock-protected; subscribers already handle cross-thread fires
        from the reactors)."""
        if self.txtrace is not None:
            # lifecycle mark: the block's (serial or deferred) apply
            # just completed — both modes route through this tail
            self.txtrace.stamp_present(block.data.txs, "apply")
        if mark_trace:
            self.trace.mark("snapshot_hook")
        if self.post_apply_hook is not None and not self.replay_mode:
            # snapshot production rides here: state_copy is the post-H
            # state and the app just committed H
            try:
                self.post_apply_hook(state_copy, block)
            except Exception:  # noqa: BLE001
                self.logger.exception("post-apply hook failed at %d", height)
        if mark_trace:
            self.trace.mark("events")
        if self.evsw is not None:
            self.evsw.fire_event(tev.EVENT_NEW_BLOCK, tev.EventDataNewBlock(block))
            self.evsw.fire_event(
                tev.EVENT_NEW_BLOCK_HEADER, tev.EventDataNewBlockHeader(block.header)
            )
        event_cache.flush()
        if self.txtrace is not None:
            # lifecycle terminus: the txs' DeliverTx events just flushed
            # to subscribers — seal the traces (visible latency)
            self.txtrace.delivered(block.data.txs)

    def _provisional_next_state(self, state_copy, block, block_parts):
        """The H+1 state ASSUMING no EndBlock valset diffs (the common
        case): last-block pointers advanced, accum rotated, app_hash
        still H−1's (header H's claim — the applied hash arrives at the
        join). A real diff is reconciled in _join_apply before any H+1
        vote could have been verified against the provisional set."""
        from tendermint_tpu.state.state import ABCIResponses

        prov = state_copy.copy()
        prov.set_block_and_validators(
            block.header, block_parts.header(), ABCIResponses.for_block(block)
        )
        return prov

    def _join_apply(self, reason: str) -> None:
        """Block until the deferred apply of rs.height-1 lands, then swap
        the applied state in. Called (consensus thread only) by every
        H+1 step that reads app_hash/the applied valset — propose,
        proposal verify, prevote validate, H+1 vote add, finalize. The
        wait is the pipeline_join_wait_seconds histogram; apply runtime
        minus the wait is the overlap the pipeline actually hid."""
        if self._apply_poisoned is not None:
            # a deferred apply failed earlier: consensus must stay
            # wedged (the serial design's semantics — advancing on a
            # stale app hash would fork from true execution)
            raise RuntimeError(
                "consensus halted: deferred apply failed"
            ) from self._apply_poisoned
        pending = self._pending_apply
        if pending is None:
            return
        t0 = time.monotonic()
        try:
            applied, apply_s = pending.result()
        except BaseException as exc:
            # a failed apply means consensus cannot advance past H-1:
            # surface it on the receive routine exactly where the serial
            # design would have raised, and POISON every later join so
            # the receive routine's catch-and-continue can't commit on
            # the stale provisional state
            self._pending_apply = None
            self._apply_poisoned = exc
            self.logger.error(
                "deferred apply of height %d failed (join at %s)",
                pending.height, reason,
            )
            raise
        wait_s = time.monotonic() - t0
        overlap_s = max(0.0, apply_s - wait_s)
        self._pending_apply = None
        self.pipeline_join_wait_last = wait_s
        self.pipeline_overlap_last = overlap_s
        hists = cpipeline.pipeline_hists()
        hists["join_wait"].observe(wait_s)
        hists["overlap"].observe(overlap_s)
        self.trace.note("pipeline_join_wait_s", wait_s)

        prov = self.state
        self.state = applied
        self._state_provisional = False
        if applied.validators.hash() != prov.validators.hash():
            # an EndBlock diff landed: the provisional set was wrong. No
            # H+1 vote or proposal was verified against it (every such
            # path joins first), so swapping the set and the empty vote
            # book is a complete reconciliation.
            self.pipeline_valset_reconciles += 1
            rs = self.rs
            rs.validators = applied.validators
            rs.last_validators = applied.last_validators
            fresh = HeightVoteSet(applied.chain_id, rs.height, applied.validators)
            fresh.set_round(rs.round_ + 1)
            rs.votes = fresh
            self.logger.warning(
                "pipelined apply of %d changed the validator set; "
                "reconciled rs for height %d at %s",
                pending.height, rs.height, reason,
            )

    # -- proposals ---------------------------------------------------------

    def default_set_proposal(self, proposal: Proposal) -> None:
        """consensus/state.go:1359-1392."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round_ != rs.round_:
            return
        if rs.step == RoundStep.COMMIT:
            return
        if proposal.pol_round != -1 and not (0 <= proposal.pol_round < proposal.round_):
            raise ValueError("invalid proposal POL round")
        # proposer selection + signature verify need the APPLIED set
        self._join_apply("set_proposal")
        proposer = rs.validators.get_proposer()
        sign_bytes = proposal.sign_bytes(self.state.chain_id)
        if proposal.signature is None or not self.verifier.verify_one(
            proposer.pub_key.raw, sign_bytes, proposal.signature.raw
        ):
            raise ValueError("invalid proposal signature")
        rs.proposal = proposal
        from tendermint_tpu.types import PartSet

        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet.from_header(proposal.block_parts_header)
        self.trace.mark_arrival("proposal")
        self.logger.info("received proposal %r", proposal)

    def add_proposal_block_part(self, height: int, part, verify: bool) -> bool:
        """consensus/state.go:1394-1457. Returns True if added."""
        rs = self.rs
        if rs.height != height:
            return False
        if rs.proposal_block_parts is None:
            return False  # no proposal yet; possible DoS — drop
        added = rs.proposal_block_parts.add_part(part)
        if added:
            # first part held for this height (build or gossip): the
            # cross-node spread of this instant IS the proposer->peer
            # propagation lag (mark_arrival keeps the first only)
            self.trace.mark_arrival("first_block_part")
            # round 20: announce the part so peers stop re-sending it —
            # the reactor broadcasts a HasBlockPart off this event (the
            # part-set analogue of the EVENT_VOTE -> HasVote broadcast)
            self._fire(
                tev.EVENT_PROPOSAL_BLOCK_PART,
                tev.EventDataBlockPart(height, rs.round_, part.index),
            )
        if added and rs.proposal_block_parts.is_complete():
            block_bytes = rs.proposal_block_parts.get_data()
            rs.proposal_block = Block.from_bytes(block_bytes)
            if self.txtrace is not None:
                # lifecycle mark: the proposal carrying a traced tx
                # arrived whole (the non-proposer half of "proposal")
                self.txtrace.stamp_present(
                    rs.proposal_block.data.txs, "proposal"
                )
            self.logger.info("received complete proposal block %s", rs.proposal_block.hash().hex()[:12])
            self._fire(tev.EVENT_COMPLETE_PROPOSAL, rs.round_state_event())
            if rs.step <= RoundStep.PROPOSE and self.is_proposal_complete():
                self.enter_prevote(height, rs.round_)
            elif rs.step == RoundStep.COMMIT:
                self.try_finalize_commit(height)
        return added

    # -- votes -------------------------------------------------------------

    def try_add_vote(self, vote: Vote, peer_id: str) -> None:
        """consensus/state.go:1430-1457: conflicting votes are evidence,
        stale/unexpected votes are ignored."""
        try:
            self.add_vote(vote, peer_id)
        except ConflictingVotesError as e:
            if (
                self.priv_validator is not None
                and vote.validator_address == self.priv_validator.get_address()
            ):
                self.logger.error(
                    "found conflicting vote from ourselves! %d/%d/%d",
                    vote.height, vote.round_, vote.type_,
                )
                return
            # Reference punts here with a TODO (state.go:1443); we
            # validate + record the pair so byzantine drills and the
            # `evidence` RPC can assert double-signing was seen.
            self.logger.warning("found conflicting vote: %r vs %r", e.vote_a, e.vote_b)
            self._record_duplicate_vote_evidence(e.vote_a, e.vote_b)
        except UnexpectedStepError:
            pass  # vote for an old height/step — harmless
        except VoteError as e:
            fr = self.flightrec
            if fr is not None:
                fr.record("vote_reject", height=vote.height,
                          round=vote.round_, type=vote.type_,
                          err=f"{type(e).__name__}: {e}",
                          peer=peer_id or "self")
            self.logger.warning("bad vote from %s: %s", peer_id or "self", e)

    def _record_duplicate_vote_evidence(self, vote_a: Vote, vote_b: Vote) -> None:
        """Validate and pool a conflicting-vote pair (never raises — the
        vote path must survive malformed evidence)."""
        try:
            from tendermint_tpu.types.evidence import DuplicateVoteEvidence

            # a late precommit for the previous height conflicts inside
            # rs.last_commit (add_vote's height-1 branch) — its signer
            # lives in LAST height's validator set, which may no longer
            # contain it (exit-then-double-sign); looking it up in the
            # current set would silently drop provable evidence
            vals = self.rs.validators
            if vote_a.height == self.rs.height - 1 and self.rs.last_validators:
                vals = self.rs.last_validators
            _idx, val = vals.get_by_address(vote_a.validator_address)
            if val is None:
                return
            ev = DuplicateVoteEvidence.new(val.pub_key, vote_a, vote_b)
            if self.evidence_pool.add(
                ev, self.state.chain_id,
                batch_verifier=self.verifier.commit_batch_verifier(),
            ):
                self.logger.warning(
                    "recorded duplicate-vote evidence: val %s at %d/%d/%d",
                    vote_a.validator_address.hex()[:12], vote_a.height,
                    vote_a.round_, vote_a.type_,
                )
                self._fire(tev.EVENT_EVIDENCE, ev.to_json())
        except Exception:  # noqa: BLE001
            self.logger.exception("evidence recording failed")

    def add_vote(self, vote: Vote, peer_id: str) -> bool:
        """consensus/state.go:1459-1565."""
        rs = self.rs

        # precommit for the previous height (late commit vote)
        if vote.height + 1 == rs.height:
            if not (vote.type_ == VOTE_TYPE_PRECOMMIT and rs.step == RoundStep.NEW_HEIGHT):
                return False
            if rs.last_commit is None:
                return False
            added = self._split_add(rs.last_commit, vote, peer_id=peer_id)
            if added:
                self.logger.info("added to last_commit: %r", rs.last_commit)
                self._fire(tev.EVENT_VOTE, tev.EventDataVote(vote))
                if self.config.skip_timeout_commit and rs.last_commit.has_all():
                    # all votes in — skip the commit timeout (state.go:1477-1484)
                    self.enter_new_round(rs.height, 0)
            return added

        if vote.height != rs.height:
            self.logger.debug("vote ignored: wrong height %d vs %d", vote.height, rs.height)
            return False

        # a current-height vote verifies against rs.validators: join so
        # the set (and rs.votes) is the applied one — this is what makes
        # the provisional set crypto-invisible (no H+1 vote is ever
        # checked against it)
        self._join_apply("add_vote")
        added = self._split_add(rs.votes, vote, peer_id=peer_id,
                                height_set=True)
        if not added:
            return False
        self._fire(tev.EVENT_VOTE, tev.EventDataVote(vote))

        if vote.type_ == VOTE_TYPE_PREVOTE:
            self._handle_added_prevote(vote)
        elif vote.type_ == VOTE_TYPE_PRECOMMIT:
            self._handle_added_precommit(vote)
        return added

    def _split_add(self, vote_set, vote: Vote, peer_id: str = "",
                   height_set: bool = False) -> bool:
        """The round-16 split-add flow (docs/committee.md): synchronous
        structural checks produce a pending entry, its signature verdict
        comes from the micro-batch the receive routine dispatched over
        the drained run (VoteBatcher.prepare) — a singleton CPU verify on
        any miss — and commit applies it with add_vote's exact error
        taxonomy, so one bad signature rejects only its own vote. Replay
        and vote_batching=False never see a dispatched batch, making
        every lane a deterministic singleton by construction.

        Round 17: a begin_add exact-duplicate from a PEER is the 2NxN
        vote-gossip redundancy — counted process-flat
        (consensus_vote_duplicates) and per sender
        (p2p_peer_vote_duplicates_total) so the queued gossip-dedup PR
        has a before number. Unwanted-round drops (catchup budget) and
        our own re-delivered votes (empty peer_id) are NOT gossip
        redundancy and stay uncounted."""
        from tendermint_tpu.consensus.height_vote_set import UNWANTED_ROUND

        if height_set:
            pending = vote_set.begin_add(vote, peer_id)  # HeightVoteSet
        else:
            pending = vote_set.begin_add(vote)  # last_commit VoteSet
        if pending is UNWANTED_ROUND:
            return False  # untracked round dropped (add_vote's False)
        if pending is None:
            if peer_id:
                self._note_vote_duplicate(peer_id)
            return False  # exact duplicate (add_vote's False)
        added = pending.commit(self.vote_batcher.verdict(pending.item()))
        if added and peer_id:
            self.vote_accepted += 1
            self._stamp_vote_recv(vote)
        return added

    def _stamp_vote_recv(self, vote: Vote) -> None:
        """Record when a gossiped vote landed (the reactor's lazy-relay
        screen reads it). Bounded: entries only matter for one gossip
        tick, so on overflow everything older than a couple seconds is
        dropped in one sweep."""
        now = time.monotonic()
        self.vote_recv_mono[
            (vote.height, vote.round_, vote.type_, vote.validator_index)
        ] = now
        if len(self.vote_recv_mono) > 4096:
            cutoff = now - 2.0
            self.vote_recv_mono = {
                k: t for k, t in self.vote_recv_mono.items() if t >= cutoff
            }

    def _note_vote_duplicate(self, peer_id: str) -> None:
        """Count one already-seen gossiped vote: the flat gauge, the
        labeled per-peer counter, and a sampled flight-recorder event.
        Metric failures must never cost the vote path."""
        self.vote_duplicates += 1
        try:
            from tendermint_tpu.p2p.telemetry import peer_metrics

            fams = peer_metrics(self.trace.metrics_registry)
            fams["vote_duplicates"].labels(peer=peer_id).inc()
        except Exception:  # noqa: BLE001
            pass
        fr = self.flightrec
        if fr is not None:
            fr.note_vote_dup(peer_id)

    def _handle_added_prevote(self, vote: Vote) -> None:
        """consensus/state.go:1500-1534."""
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round_)
        self.logger.debug("added prevote %r -> %r", vote, prevotes)

        # unlock on a newer polka (state.go:1507-1521)
        block_id = prevotes.two_thirds_majority()
        if block_id is not None and block_id.hash:
            # gossip arrival mark (round 15): +2/3 prevotes for a block
            self.trace.mark_arrival("prevote_quorum")
        if (
            rs.locked_block is not None
            and rs.locked_round < vote.round_ <= rs.round_
            and block_id is not None
            and not rs.locked_block.hashes_to(block_id.hash)
        ):
            self.logger.info("unlocking because of POL at round %d", vote.round_)
            rs.locked_round = -1
            rs.locked_block = None
            rs.locked_block_parts = None
            self._fire(tev.EVENT_UNLOCK, rs.round_state_event())

        if rs.round_ <= vote.round_ and prevotes.has_two_thirds_any():
            # round skip / advance (state.go:1523-1533)
            if prevotes.has_two_thirds_majority():
                self.enter_precommit(rs.height, vote.round_)
            else:
                self.enter_new_round(rs.height, vote.round_)  # if vote.round > rs.round
                self.enter_prevote_wait(rs.height, vote.round_)
        elif rs.proposal is not None and rs.proposal.pol_round >= 0 and rs.proposal.pol_round == vote.round_:
            if self.is_proposal_complete():
                self.enter_prevote(rs.height, rs.round_)

    def _handle_added_precommit(self, vote: Vote) -> None:
        """consensus/state.go:1535-1557."""
        rs = self.rs
        precommits = rs.votes.precommits(vote.round_)
        self.logger.debug("added precommit %r -> %r", vote, precommits)
        block_id = precommits.two_thirds_majority()
        if block_id is not None and block_id.hash:
            # gossip arrival mark (round 15): the commit-able quorum —
            # after a partition heals, the first height's observation
            # carries the whole outage (the scrape-visible quorum spike)
            self.trace.mark_arrival("precommit_quorum")
        if block_id is not None:
            # executed as defers in the reference: latest first
            self.enter_new_round(rs.height, vote.round_)
            self.enter_precommit(rs.height, vote.round_)
            if block_id.hash:
                self.enter_commit(rs.height, vote.round_)
                if self.config.skip_timeout_commit and precommits.has_all():
                    self.enter_new_round(rs.height, 0)
            else:
                self.enter_precommit_wait(rs.height, vote.round_)
        elif rs.round_ <= vote.round_ and precommits.has_two_thirds_any():
            self.enter_new_round(rs.height, vote.round_)
            self.enter_precommit(rs.height, vote.round_)
            self.enter_precommit_wait(rs.height, vote.round_)

    # -- signing -----------------------------------------------------------

    def sign_vote(self, type_: int, hash_: bytes, header) -> Vote:
        """consensus/state.go:1567-1581."""
        rs = self.rs
        addr = self.priv_validator.get_address()
        val_index, _ = rs.validators.get_by_address(addr)
        from tendermint_tpu.types.block_id import PartSetHeader

        vote = Vote(
            validator_address=addr,
            validator_index=val_index,
            height=rs.height,
            round_=rs.round_,
            type_=type_,
            block_id=BlockID(hash_, header or PartSetHeader()),
        )
        return self.priv_validator.sign_vote(self.state.chain_id, vote)

    def sign_add_vote(self, type_: int, hash_: bytes, header) -> Vote | None:
        """Sign and inject into our own queue (consensus/state.go:1583-1599)."""
        rs = self.rs
        if self.priv_validator is None or not rs.validators.has_address(
            self.priv_validator.get_address()
        ):
            return None
        try:
            vote = self.sign_vote(type_, hash_, header)
        except Exception:
            if not self.replay_mode:
                self.logger.exception("error signing vote %d/%d", rs.height, rs.round_)
            return None
        self.send_internal_message(MsgInfo(msgs.VoteMessage(vote)))
        self.logger.info("signed and pushed vote %r", vote)
        return vote


class _NullCache:
    def fire_event(self, event, data):
        pass

    def flush(self):
        pass
