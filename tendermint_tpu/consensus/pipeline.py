"""Pipelined execution plane (round 14, docs/execution-pipeline.md).

``finalize_commit`` used to run save -> WAL marker -> apply -> snapshot
hook -> events INLINE on the consensus receive routine, so the whole node
idled through the ABCI apply of every block before the next height could
start. The header contract never required that: header H+1 carries block
H's app hash, so apply(H) only has to finish by the first point of H+1
that actually reads ``app_hash``/the applied validator set — propose, or
validating a received proposal — not before H+1's NewHeight/vote gossip
begins (the deferred-app-hash design Tendermint later shipped as ABCI++).

This module holds the moving parts consensus/state.py stages onto:

- ``ApplyExecutor``: ONE daemon worker thread applying blocks strictly in
  submission order.  A single worker is a correctness feature, not a
  limitation — apply(H+1) must observe the app exactly at H, and the
  statesync snapshot hook (which runs here, off the consensus thread)
  keeps its "app is quiesced at H" guarantee because the next DeliverTx
  can only come from the next queued apply.  The thread is a daemon on
  purpose: a wedged ABCI app must not block process exit (the round-9
  dead-disk shutdown rule, applied to the app plane).

- ``DeferredApply``: the join handle for one height's stage-2 work.  The
  consensus thread parks on ``result()`` at the first H+1 step that needs
  the applied state; the wait is the ``pipeline_join_wait_seconds``
  histogram, and ``apply_s - wait`` — the portion of the apply that ran
  hidden under consensus — is ``pipeline_overlap_seconds``.

- the process-wide latency instruments (create-or-get, like the WAL and
  devd histograms): ``consensus_height_seconds`` (the liveness gauge pair
  ``height_seconds_last/max`` grown into a real log-bucket distribution),
  ``pipeline_join_wait_seconds`` and ``pipeline_overlap_seconds``.

Durability/ordering invariants live in consensus/state.py and
docs/execution-pipeline.md: the block save and the WAL ``#ENDHEIGHT``
marker are written SYNCHRONOUSLY before the apply is submitted, so a
crash with the marker on disk but the deferred apply unfinished is a
legal image — the restart handshake replays the saved block against the
app (the same store==state+1 case the serial design already recovered).
"""

from __future__ import annotations

import logging
import threading
import time

from tendermint_tpu.libs import telemetry

logger = logging.getLogger("consensus.pipeline")

_hist_mtx = threading.Lock()
_hist_cache: dict = {}


def pipeline_hists() -> dict:
    """Materialize (create-or-get) the pipeline's process-wide latency
    histograms on the default registry. Called from node telemetry
    wiring so a scrape's family set is stable from the first height."""
    with _hist_mtx:
        if not _hist_cache:
            reg = telemetry.default_registry()
            _hist_cache["height"] = reg.histogram(
                "consensus_height_seconds",
                "wall seconds per committed height (the "
                "height_seconds_last/max gauges as a distribution)",
            )
            _hist_cache["join_wait"] = reg.histogram(
                "pipeline_join_wait_seconds",
                "seconds the consensus thread blocked joining the "
                "deferred apply of the previous height",
            )
            _hist_cache["overlap"] = reg.histogram(
                "pipeline_overlap_seconds",
                "deferred-apply seconds hidden under consensus of the "
                "next height (apply wall time minus join wait)",
            )
        return dict(_hist_cache)


class DeferredApply:
    """Join handle for one height's stage-2 (executor-side) work.

    ``result()`` returns ``(applied_state, apply_s)`` or re-raises the
    executor-side exception; ``wait()`` is the non-raising shutdown
    variant."""

    __slots__ = ("height", "_evt", "_value", "_exc")

    def __init__(self, height: int):
        self.height = height
        self._evt = threading.Event()
        self._value = None
        self._exc: BaseException | None = None

    # executor side -------------------------------------------------------

    def _finish(self, value=None, exc: BaseException | None = None) -> None:
        self._value = value
        self._exc = exc
        self._evt.set()

    # consensus side ------------------------------------------------------

    def done(self) -> bool:
        return self._evt.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._evt.wait(timeout)

    def result(self, timeout: float | None = None):
        if not self._evt.wait(timeout):
            raise TimeoutError(
                f"deferred apply of height {self.height} did not complete"
            )
        if self._exc is not None:
            raise self._exc
        return self._value


class ApplyExecutor:
    """Single daemon worker applying submitted thunks strictly in order.

    Not a thread pool: ordering IS the contract (see module docstring).
    concurrent.futures is deliberately not used — its workers are
    non-daemon since py3.9 and atexit-joined, so a wedged apply would
    hang interpreter shutdown."""

    def __init__(self, name: str = "cs.applyExecutor"):
        self._queue: list[tuple[DeferredApply, object]] = []
        self._cond = threading.Condition()
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name
        )
        self._thread.start()

    def submit(self, pending: DeferredApply, fn) -> DeferredApply:
        with self._cond:
            if self._stopping:
                raise RuntimeError("apply executor stopped")
            self._queue.append((pending, fn))
            self._cond.notify()
        return pending

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if self._stopping and not self._queue:
                    return
                pending, fn = self._queue.pop(0)
            try:
                value = fn()
                if not pending.done():
                    pending._finish(value=value)
            except BaseException as exc:  # noqa: BLE001 — delivered at join
                if pending.done():
                    # the thunk resolved the join early (apply landed)
                    # and then its post-apply tail (hook/events) failed —
                    # same severity as a serial-mode subscriber error,
                    # log-only: the applied state is already consistent
                    logger.exception(
                        "post-apply tail of height %d failed", pending.height
                    )
                else:
                    logger.exception(
                        "deferred apply of height %d failed", pending.height
                    )
                    pending._finish(exc=exc)

    def stop(self, timeout: float = 5.0) -> None:
        """Drain-and-stop: queued applies still run (state/app land on a
        consistent height for the restart handshake), then the worker
        exits. A wedged apply is abandoned after `timeout` — shutdown
        never blocks on a stuck app (the thread is a daemon)."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            logger.warning("apply executor did not drain in %.1fs", timeout)
