"""RoundStepType + RoundState: the consensus-internal state snapshot
(reference: consensus/state.go:45-106)."""

from __future__ import annotations


class RoundStep:
    NEW_HEIGHT = 1  # wait til commit_time + timeout_commit
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8

    _NAMES = {
        1: "NewHeight",
        2: "NewRound",
        3: "Propose",
        4: "Prevote",
        5: "PrevoteWait",
        6: "Precommit",
        7: "PrecommitWait",
        8: "Commit",
    }

    @classmethod
    def name(cls, step: int) -> str:
        return f"RoundStep{cls._NAMES.get(step, '?')}"


class RoundState:
    """Mutable snapshot owned by the receive routine; readers get copies
    via ConsensusState.get_round_state()."""

    def __init__(self):
        self.height = 0
        self.round_ = 0
        self.step = RoundStep.NEW_HEIGHT
        self.start_time = 0.0
        self.commit_time = 0.0  # wall time when +2/3 commit was found
        self.validators = None  # ValidatorSet
        self.proposal = None  # Proposal | None
        self.proposal_block = None  # Block | None
        self.proposal_block_parts = None  # PartSet | None
        self.locked_round = -1
        self.locked_block = None
        self.locked_block_parts = None
        self.votes = None  # HeightVoteSet
        self.commit_round = -1
        self.last_commit = None  # VoteSet of last height's precommits
        self.last_validators = None  # ValidatorSet
        # a VERIFIED AggregateCommit for this height, received via the
        # catchup gossip path (AggregateCommitMessage): under the
        # aggregate commit format individual precommits cannot be
        # re-gossiped, so a lagging node finalizes from this proof
        # instead of a +2/3 VoteSet (consensus/state.apply_commit_proof)
        self.commit_proof = None  # AggregateCommit | None

    def round_state_event(self):
        from tendermint_tpu.types.events import EventDataRoundState

        return EventDataRoundState(
            height=self.height, round_=self.round_, step=RoundStep.name(self.step)
        )

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "step": self.step,
            "start_time": self.start_time,
            "proposal": self.proposal.to_json() if self.proposal else None,
            "locked_round": self.locked_round,
            "locked_block_hash": (
                self.locked_block.hash().hex().upper() if self.locked_block else ""
            ),
            "votes": self.votes.to_json() if self.votes else None,
        }

    def __repr__(self):
        return f"RoundState{{{self.height}/{self.round_}/{RoundStep.name(self.step)}}}"
