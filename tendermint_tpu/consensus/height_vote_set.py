"""HeightVoteSet: all prevote/precommit VoteSets for one height
(reference: consensus/height_vote_set.go).

Keeps both vote types for rounds 0..round, plus up to 2 "catchup" rounds
created when a peer sends votes for future rounds (DOS bound,
consensus/height_vote_set.go:18-24,118-139). POL lookup scans rounds for
a prevote +2/3 (consensus/height_vote_set.go:143-153).
"""

from __future__ import annotations

import threading

from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    BlockID,
    ValidatorSet,
    Vote,
    VoteSet,
)

MAX_CATCHUP_ROUNDS = 2

# begin_add's distinguishable drop (round 17): a vote for a round this
# set refuses to track (catchup budget spent) is NOT an already-seen
# duplicate — the redundancy counters must only count true re-deliveries
UNWANTED_ROUND = object()


class _RoundVoteSet:
    __slots__ = ("prevotes", "precommits")

    def __init__(self, prevotes: VoteSet, precommits: VoteSet):
        self.prevotes = prevotes
        self.precommits = precommits


class HeightVoteSet:
    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self.height = height
        self.val_set = val_set
        self._mtx = threading.RLock()
        self._round = 0
        self._round_vote_sets: dict[int, _RoundVoteSet] = {}
        self._peer_catchup_rounds: dict[str, list[int]] = {}
        self._add_round(0)

    def round(self) -> int:
        with self._mtx:
            return self._round

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            raise RuntimeError(f"add_round for existing round {round_}")
        self._round_vote_sets[round_] = _RoundVoteSet(
            VoteSet(self.chain_id, self.height, round_, VOTE_TYPE_PREVOTE, self.val_set),
            VoteSet(self.chain_id, self.height, round_, VOTE_TYPE_PRECOMMIT, self.val_set),
        )

    def set_round(self, round_: int) -> None:
        """Create vote sets through round+1 (the reference seeds one round
        ahead, consensus/height_vote_set.go:84-103)."""
        with self._mtx:
            if self._round != 0 and round_ < self._round:
                raise RuntimeError("set_round must increase round")
            for r in range(self._round, round_ + 2):
                if r not in self._round_vote_sets:
                    self._add_round(r)
            self._round = round_

    # -- votes -------------------------------------------------------------

    def add_vote(self, vote: Vote, peer_id: str = "", verifier=None) -> bool:
        """consensus/height_vote_set.go:105-116. Returns True if added.
        Raises VoteError for invalid votes; votes for unwanted rounds from
        peers beyond the catchup budget are silently dropped (returns
        False, mirroring ErrGotVoteFromUnwantedRound)."""
        with self._mtx:
            vs = self._resolve_vote_set(vote, peer_id)
            if vs is None:
                return False
        return vs.add_vote(vote, verifier=verifier)

    def begin_add(self, vote: Vote, peer_id: str = ""):
        """Split-add entry (round 16, types/vote_set.py PendingVote):
        resolves the round's VoteSet — creating a catchup round within
        the per-peer budget exactly as add_vote would — and runs its
        structural half. None = exact duplicate (add_vote's False);
        the UNWANTED_ROUND sentinel = dropped untracked-round vote
        (also add_vote's False, but NOT a gossip re-delivery — the
        round-17 duplicate counters key off the distinction); commit
        via the returned entry's .commit(ok)."""
        with self._mtx:
            vs = self._resolve_vote_set(vote, peer_id)
            if vs is None:
                return UNWANTED_ROUND
        return vs.begin_add(vote)

    def _resolve_vote_set(self, vote: Vote, peer_id: str):
        """The add-side round lookup + catchup-budget bookkeeping
        (callers hold self._mtx)."""
        if not self._is_vote_type_tracked(vote.type_):
            return None
        vs = self._get_vote_set(vote.round_, vote.type_)
        if vs is None:
            rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
            if len(rounds) < MAX_CATCHUP_ROUNDS:
                self._add_round(vote.round_)
                vs = self._get_vote_set(vote.round_, vote.type_)
                rounds.append(vote.round_)
            else:
                return None  # punish peer?
        return vs

    @staticmethod
    def _is_vote_type_tracked(t: int) -> bool:
        return t in (VOTE_TYPE_PREVOTE, VOTE_TYPE_PRECOMMIT)

    def _get_vote_set(self, round_: int, type_: int) -> VoteSet | None:
        rvs = self._round_vote_sets.get(round_)
        if rvs is None:
            return None
        return rvs.prevotes if type_ == VOTE_TYPE_PREVOTE else rvs.precommits

    def prevotes(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_vote_set(round_, VOTE_TYPE_PREVOTE)

    def precommits(self, round_: int) -> VoteSet | None:
        with self._mtx:
            return self._get_vote_set(round_, VOTE_TYPE_PRECOMMIT)

    def pol_info(self) -> tuple[int, BlockID | None]:
        """Highest round with a prevote +2/3, searching down from current
        (consensus/height_vote_set.go:143-153). Returns (-1, None) if none."""
        with self._mtx:
            for r in range(self._round, -1, -1):
                vs = self._get_vote_set(r, VOTE_TYPE_PREVOTE)
                if vs is not None:
                    block_id = vs.two_thirds_majority()
                    if block_id is not None:
                        return r, block_id
            return -1, None

    def set_peer_maj23(self, round_: int, type_: int, peer_id: str, block_id: BlockID) -> None:
        """consensus/height_vote_set.go:209-219."""
        with self._mtx:
            if not self._is_vote_type_tracked(type_):
                return
            vs = self._get_vote_set(round_, type_)
            if vs is not None:
                vs.set_peer_maj23(peer_id, block_id)

    def to_json(self):
        with self._mtx:
            return {
                "round": self._round,
                "round_votes": {
                    str(r): {
                        "prevotes": repr(rvs.prevotes),
                        "precommits": repr(rvs.precommits),
                    }
                    for r, rvs in sorted(self._round_vote_sets.items())
                },
            }

    def __repr__(self):
        return f"HeightVoteSet{{h:{self.height} r:{self._round}}}"
