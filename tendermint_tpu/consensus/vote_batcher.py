"""Consensus-thread vote micro-batching (round 16, docs/committee.md).

At 100-400 validators LIVE consensus receives ~2N gossiped votes per
height; before this round each one paid its own signature verify on the
receive routine — ~800 serial Ed25519 calls per height at N=400, on the
exact thread whose latency bounds the chain. The VoteBatcher drains the
run of votes the receive routine just pulled off its input queue, groups
them by (height, round, type), and dispatches each group as ONE
``Verifier.verify_batch_async`` gateway call (streamed devd chunks when a
daemon serves, the native AVX batch verifier on the CPU floor) while the
routine gets on with handling the messages strictly in order.

Contract:

- The prepare-time screen is ADVISORY: ``VoteSet.begin_add`` remains the
  authoritative structural check at handling time (handling vote k-1 can
  change vote k's context — a quorum mid-run commits the height). A vote
  the screen skipped, or whose group stayed below the min-batch floor,
  simply verifies as a singleton at ``verdict`` time — identical result,
  CPU latency path.
- Per-lane verdicts preserve per-vote error attribution: one forged
  signature inside a batch rejects exactly that vote (commit_add raises
  for its lane only) and peer-errors only its sender.
- A batch whose transport fails resolves to "unknown" for every lane;
  each vote then re-verifies singleton — transport loss is latency,
  never a wrong or dropped verdict (the gateway _PendingBatch rule).
- Singleton fallback: below ``TENDERMINT_VOTE_BATCH_MIN`` (default 4)
  no batch is dispatched; WAL replay never reaches prepare at all
  (consensus/replay.py feeds messages one at a time outside the receive
  routine), so replay determinism is untouched by construction.

The pending-batch machinery is the gateway's round-6 prime plane, not a
copy: prepare dispatches each group through
``Verifier.prime_cache_async`` (whose _PendingBatch always drains the
transport and FIFO-bounds unconsumed lanes) and ``verdict`` pops lanes
via ``Verifier.pop_primed`` — this module only adds the grouping policy
and the counters/histogram.

Observability: ``consensus_vote_batches`` / ``consensus_vote_singletons``
flat gauges on the canonical map plus the
``consensus_vote_verify_batch_seconds`` histogram (dispatch -> per-lane
verdicts, one observe per micro-batch) on GET /metrics.
"""

from __future__ import annotations

import logging
import threading

from tendermint_tpu.libs import telemetry
from tendermint_tpu.libs.envknob import env_number
from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, VOTE_TYPE_PREVOTE

logger = logging.getLogger("consensus.vote_batcher")

Item = tuple[bytes, bytes, bytes]  # (pubkey, sign_bytes, signature)

_hist_cache: dict = {}
_hist_mtx = threading.Lock()


def vote_batch_hists() -> dict:
    """Materialize (create-or-get) the vote-plane latency histogram on
    the default registry — called from node telemetry wiring so the
    scrape family set is stable from the first height (the
    pipeline_hists convention)."""
    with _hist_mtx:
        if not _hist_cache:
            _hist_cache["batch"] = telemetry.default_registry().histogram(
                "consensus_vote_verify_batch_seconds",
                "wall seconds from vote micro-batch dispatch to per-lane "
                "verdicts (one observe per batched gateway call the "
                "consensus receive routine drained)",
            )
        return dict(_hist_cache)


class VoteBatcher:
    """The consensus thread's micro-batch front for vote signatures.

    ``verifier_fn`` is a zero-arg getter for the gateway Verifier (the
    consensus state's verifier is test-swappable after construction, so
    the batcher must never pin an instance)."""

    def __init__(self, verifier_fn, min_batch: int | None = None):
        self._verifier_fn = verifier_fn
        if min_batch is None:
            min_batch = int(
                env_number("TENDERMINT_VOTE_BATCH_MIN", 4, cast=int)
            )
        self.min_batch = max(2, min_batch)
        # flat counters for the canonical metrics map (node/telemetry.py)
        self.batches = 0          # micro-batches dispatched
        self.batched_sigs = 0     # signature lanes those batches carried
        self.singletons = 0       # verdicts that fell to the one-sig path
        self._hist = vote_batch_hists()["batch"]

    # -- dispatch (receive routine, on a drained run) ----------------------

    def prepare(self, votes: list, rs, chain_id: str) -> None:
        """Advisory verify-ahead over a drained run of gossiped votes.
        Groups the structurally-plausible lanes by (height, round, type)
        and dispatches one async gateway batch per group at or above the
        min-batch floor. Never a correctness dependency: every screen
        here is re-run authoritatively by begin_add at handling time."""
        groups: dict[tuple, list[Item]] = {}
        seen: set[Item] = set()
        sb_cache: dict[tuple, bytes] = {}
        for v in votes:
            if v.signature is None:
                continue
            vs = self._target_vote_set(v, rs)
            if vs is None:
                continue
            # validator lookup FIRST: it bounds-checks the index, which
            # VoteSet.get_by_index below does not — an adversarial index
            # must fall through to begin_add's error taxonomy, not raise
            addr, val = vs.val_set.get_by_index(v.validator_index)
            if val is None or addr != v.validator_address:
                continue
            if vs.get_by_index(v.validator_index) is not None:
                continue  # duplicate gossip: begin_add screens before verify
            sbk = (v.height, v.round_, v.type_, v.block_id.key())
            sb = sb_cache.get(sbk)
            if sb is None:
                sb = sb_cache[sbk] = v.sign_bytes(chain_id)
            item = (val.pub_key.raw, sb, v.signature.raw)
            if item in seen:
                continue
            seen.add(item)
            groups.setdefault((v.height, v.round_, v.type_), []).append(item)
        verifier = self._verifier_fn()
        for items in groups.values():
            if len(items) < self.min_batch:
                continue  # singleton fallback below the floor
            # the gateway prime plane owns the in-flight machinery: the
            # _PendingBatch always drains the transport, FIFO-bounds
            # never-consumed lanes (votes screened out at handling
            # time), and un-primes every lane on a failed resolve
            verifier.prime_cache_async(items, on_done=self._hist.observe)
            self.batches += 1
            self.batched_sigs += len(items)

    def _target_vote_set(self, v, rs):
        """The VoteSet this vote would land in, per add_vote's routing:
        current-height prevote/precommit sets, or the previous height's
        last_commit for commit-time stragglers (the catchup-gossip flood
        a big committee produces right after every commit)."""
        if v.height == rs.height and rs.votes is not None:
            return (
                rs.votes.prevotes(v.round_)
                if v.type_ == VOTE_TYPE_PREVOTE
                else rs.votes.precommits(v.round_)
                if v.type_ == VOTE_TYPE_PRECOMMIT
                else None
            )
        lc = rs.last_commit
        if (
            lc is not None
            and v.height + 1 == rs.height
            and v.type_ == VOTE_TYPE_PRECOMMIT
            and v.round_ == lc.round_
        ):
            return lc
        return None

    # -- verdicts (handling time) ------------------------------------------

    def verdict(self, item: Item) -> bool:
        """The signature verdict for one pending vote: its primed
        micro-batch lane when the prepare pass covered it (single-use —
        blocks for the batch on first need), else a singleton verify."""
        verifier = self._verifier_fn()
        ok = verifier.pop_primed(item)
        if ok is not None:
            return ok
        self.singletons += 1
        return verifier.verify_one(*item)
