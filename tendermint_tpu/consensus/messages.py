"""Consensus wire/WAL messages (reference: consensus/reactor.go:1181-1363).

A tagged-union JSON codec: each message type registers under a short tag
(the analogue of go-wire's type bytes, consensus/reactor.go:1198-1210).
The same encoding serves the WAL and the p2p channels.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.types import BlockID, Heartbeat, Part, Proposal, Vote
from tendermint_tpu.types.block_id import PartSetHeader

_REGISTRY: dict[str, type] = {}


def register(tag: str):
    def deco(cls):
        cls.TAG = tag
        _REGISTRY[tag] = cls
        return cls

    return deco


def msg_to_json(msg) -> dict:
    return {"type": msg.TAG, "data": msg.to_json()}


def msg_from_json(obj: dict):
    cls = _REGISTRY.get(obj["type"])
    if cls is None:
        raise ValueError(f"unknown consensus message type {obj['type']!r}")
    return cls.from_json(obj["data"])


@register("new_round_step")
@dataclass
class NewRoundStepMessage:
    """Broadcast on every step transition (consensus/reactor.go:1225-1251)."""

    height: int
    round_: int
    step: int
    seconds_since_start_time: int
    last_commit_round: int

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "step": self.step,
            "seconds_since_start_time": self.seconds_since_start_time,
            "last_commit_round": self.last_commit_round,
        }

    @classmethod
    def from_json(cls, o):
        return cls(o["height"], o["round"], o["step"], o["seconds_since_start_time"], o["last_commit_round"])


@register("commit_step")
@dataclass
class CommitStepMessage:
    """consensus/reactor.go:1256-1268."""

    height: int
    block_parts_header: PartSetHeader
    block_parts: BitArray

    def to_json(self):
        return {
            "height": self.height,
            "block_parts_header": self.block_parts_header.to_json(),
            "block_parts": self.block_parts.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(
            o["height"],
            PartSetHeader.from_json(o["block_parts_header"]),
            BitArray.from_json(o["block_parts"]),
        )


@register("proposal")
@dataclass
class ProposalMessage:
    proposal: Proposal

    def to_json(self):
        return {"proposal": self.proposal.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(Proposal.from_json(o["proposal"]))


@register("proposal_pol")
@dataclass
class ProposalPOLMessage:
    """Sent when catching a peer up to a POL round (consensus/reactor.go:1289-1300)."""

    height: int
    proposal_pol_round: int
    proposal_pol: BitArray

    def to_json(self):
        return {
            "height": self.height,
            "proposal_pol_round": self.proposal_pol_round,
            "proposal_pol": self.proposal_pol.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(o["height"], o["proposal_pol_round"], BitArray.from_json(o["proposal_pol"]))


@register("block_part")
@dataclass
class BlockPartMessage:
    height: int
    round_: int
    part: Part

    def to_json(self):
        return {"height": self.height, "round": self.round_, "part": self.part.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(o["height"], o["round"], Part.from_json(o["part"]))


@register("vote")
@dataclass
class VoteMessage:
    vote: Vote

    def to_json(self):
        return {"vote": self.vote.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(Vote.from_json(o["vote"]))


@register("has_vote")
@dataclass
class HasVoteMessage:
    """Tells peers our vote bit-arrays changed (consensus/reactor.go:1327-1339)."""

    height: int
    round_: int
    type_: int
    index: int

    def to_json(self):
        return {"height": self.height, "round": self.round_, "type": self.type_, "index": self.index}

    @classmethod
    def from_json(cls, o):
        return cls(o["height"], o["round"], o["type"], o["index"])


@register("vote_set_maj23")
@dataclass
class VoteSetMaj23Message:
    """Claim of +2/3 for a block (consensus/reactor.go:1344-1355)."""

    height: int
    round_: int
    type_: int
    block_id: BlockID

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "type": self.type_,
            "block_id": self.block_id.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(o["height"], o["round"], o["type"], BlockID.from_json(o["block_id"]))


@register("vote_set_bits")
@dataclass
class VoteSetBitsMessage:
    """Response to VoteSetMaj23: which of those votes we have
    (consensus/reactor.go:1360-1372)."""

    height: int
    round_: int
    type_: int
    block_id: BlockID
    votes: BitArray

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "type": self.type_,
            "block_id": self.block_id.to_json(),
            "votes": self.votes.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(
            o["height"], o["round"], o["type"],
            BlockID.from_json(o["block_id"]), BitArray.from_json(o["votes"]),
        )


@register("proposal_heartbeat")
@dataclass
class ProposalHeartbeatMessage:
    heartbeat: Heartbeat

    def to_json(self):
        return {"heartbeat": self.heartbeat.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(Heartbeat.from_json(o["heartbeat"]))
