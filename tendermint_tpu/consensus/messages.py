"""Consensus wire/WAL messages (reference: consensus/reactor.go:1181-1363).

A tagged-union JSON codec: each message type registers under a short tag
(the analogue of go-wire's type bytes, consensus/reactor.go:1198-1210).
The same encoding serves the WAL and the p2p channels.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.libs.bitarray import BitArray
from tendermint_tpu.types import BlockID, Heartbeat, Part, Proposal, Vote
from tendermint_tpu.types.block_id import PartSetHeader

_REGISTRY: dict[str, type] = {}


def register(tag: str):
    def deco(cls):
        cls.TAG = tag
        _REGISTRY[tag] = cls
        return cls

    return deco


def msg_to_json(msg) -> dict:
    return {"type": msg.TAG, "data": msg.to_json()}


def msg_from_json(obj: dict):
    """Decode one peer message. The input is attacker-controlled: the
    envelope and every scalar field are type- and range-checked here (the
    go-wire codec got this for free from typed byte decoding); anything
    out of contract raises ValueError, which the reactor's receive()
    treats as a peer error."""
    if not isinstance(obj, dict) or not isinstance(obj.get("type"), str):
        raise ValueError("malformed consensus message envelope")
    cls = _REGISTRY.get(obj["type"])
    if cls is None:
        raise ValueError(f"unknown consensus message type {obj['type']!r}")
    data = obj.get("data")
    if not isinstance(data, dict):
        raise ValueError("malformed consensus message body")
    return cls.from_json(data)


# -- field validators (attacker-facing bounds; shared with the nested
# wire types via codec/jsonval) ---------------------------------------------

from tendermint_tpu.codec.jsonval import (  # noqa: E402
    MAX_HEIGHT as _MAX_HEIGHT,
    MAX_INDEX as _MAX_INDEX,
    MAX_ROUND as _MAX_ROUND,
    dict_field as _dict_field,
    int_field as _int_field,
)

_MAX_BITS = 1 << 20  # vote / part bit-arrays


def _bitarray_field(o, key, max_bits=_MAX_BITS):
    v = _dict_field(o, key)
    bits = v.get("bits")
    if type(bits) is not int or not (0 <= bits <= max_bits):
        raise ValueError(f"bad {key!r} size: {bits!r}")
    return BitArray.from_json(v)


@register("new_round_step")
@dataclass
class NewRoundStepMessage:
    """Broadcast on every step transition (consensus/reactor.go:1225-1251)."""

    height: int
    round_: int
    step: int
    seconds_since_start_time: int
    last_commit_round: int

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "step": self.step,
            "seconds_since_start_time": self.seconds_since_start_time,
            "last_commit_round": self.last_commit_round,
        }

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            _int_field(o, "round", 0, _MAX_ROUND),
            _int_field(o, "step", 0, 16),
            _int_field(o, "seconds_since_start_time", -_MAX_ROUND, _MAX_ROUND),
            _int_field(o, "last_commit_round", -1, _MAX_ROUND),
        )


@register("commit_step")
@dataclass
class CommitStepMessage:
    """consensus/reactor.go:1256-1268."""

    height: int
    block_parts_header: PartSetHeader
    block_parts: BitArray

    def to_json(self):
        return {
            "height": self.height,
            "block_parts_header": self.block_parts_header.to_json(),
            "block_parts": self.block_parts.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            PartSetHeader.from_json(_dict_field(o, "block_parts_header")),
            _bitarray_field(o, "block_parts"),
        )


@register("proposal")
@dataclass
class ProposalMessage:
    proposal: Proposal

    def to_json(self):
        return {"proposal": self.proposal.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(Proposal.from_json(_dict_field(o, "proposal")))


@register("proposal_pol")
@dataclass
class ProposalPOLMessage:
    """Sent when catching a peer up to a POL round (consensus/reactor.go:1289-1300)."""

    height: int
    proposal_pol_round: int
    proposal_pol: BitArray

    def to_json(self):
        return {
            "height": self.height,
            "proposal_pol_round": self.proposal_pol_round,
            "proposal_pol": self.proposal_pol.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            _int_field(o, "proposal_pol_round", 0, _MAX_ROUND),
            _bitarray_field(o, "proposal_pol"),
        )


@register("block_part")
@dataclass
class BlockPartMessage:
    height: int
    round_: int
    part: Part

    def to_json(self):
        return {"height": self.height, "round": self.round_, "part": self.part.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            _int_field(o, "round", 0, _MAX_ROUND),
            Part.from_json(_dict_field(o, "part")),
        )


@register("vote")
@dataclass
class VoteMessage:
    vote: Vote

    def to_json(self):
        return {"vote": self.vote.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(Vote.from_json(_dict_field(o, "vote")))


@register("has_vote")
@dataclass
class HasVoteMessage:
    """Tells peers our vote bit-arrays changed (consensus/reactor.go:1327-1339)."""

    height: int
    round_: int
    type_: int
    index: int

    def to_json(self):
        return {"height": self.height, "round": self.round_, "type": self.type_, "index": self.index}

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            _int_field(o, "round", 0, _MAX_ROUND),
            _int_field(o, "type", 0, 255),
            _int_field(o, "index", 0, _MAX_INDEX),
        )


@register("has_block_part")
@dataclass
class HasBlockPartMessage:
    """Tells peers our proposal part-set gained a part (beyond
    reference): the round-20 part-gossip dedup screen. A node that just
    assembled part `index` announces it on the STATE channel so every
    OTHER peer's mirror marks the bit and its gossip_data loop skips
    re-sending a part the node already holds — without this, k peers
    holding a part all race to push it and k-1 copies are pure
    redundancy (the part-set analogue of the 2NxN vote problem)."""

    height: int
    round_: int
    index: int

    def to_json(self):
        return {"height": self.height, "round": self.round_, "index": self.index}

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            _int_field(o, "round", 0, _MAX_ROUND),
            _int_field(o, "index", 0, _MAX_INDEX),
        )


@register("agg_commit")
@dataclass
class AggregateCommitMessage:
    """Catch a lagging peer up under the aggregate commit format
    (docs/upgrade.md): individual precommits no longer exist once a
    commit has been half-aggregated, so the per-vote catchup gossip
    (reactor.go:609-645) is impossible — the whole AggregateCommit
    ships instead, and the receiver finalizes from it as a commit
    proof (consensus/state.apply_commit_proof) after verifying the
    aggregate against its own validator set. A forged or sub-quorum
    aggregate is a peer error (stop_peer_for_error)."""

    height: int
    commit: object  # AggregateCommit (typed lazily: types <-/-> consensus)

    def to_json(self):
        return {"height": self.height, "commit": self.commit.to_json()}

    @classmethod
    def from_json(cls, o):
        from tendermint_tpu.types.agg_commit import AggregateCommit

        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            AggregateCommit.from_json(_dict_field(o, "commit")),
        )


@register("vote_set_maj23")
@dataclass
class VoteSetMaj23Message:
    """Claim of +2/3 for a block (consensus/reactor.go:1344-1355)."""

    height: int
    round_: int
    type_: int
    block_id: BlockID

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "type": self.type_,
            "block_id": self.block_id.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            _int_field(o, "round", 0, _MAX_ROUND),
            _int_field(o, "type", 0, 255),
            BlockID.from_json(_dict_field(o, "block_id")),
        )


@register("vote_set_bits")
@dataclass
class VoteSetBitsMessage:
    """Response to VoteSetMaj23: which of those votes we have
    (consensus/reactor.go:1360-1372)."""

    height: int
    round_: int
    type_: int
    block_id: BlockID
    votes: BitArray

    def to_json(self):
        return {
            "height": self.height,
            "round": self.round_,
            "type": self.type_,
            "block_id": self.block_id.to_json(),
            "votes": self.votes.to_json(),
        }

    @classmethod
    def from_json(cls, o):
        return cls(
            _int_field(o, "height", 0, _MAX_HEIGHT),
            _int_field(o, "round", 0, _MAX_ROUND),
            _int_field(o, "type", 0, 255),
            BlockID.from_json(_dict_field(o, "block_id")),
            _bitarray_field(o, "votes"),
        )


@register("proposal_heartbeat")
@dataclass
class ProposalHeartbeatMessage:
    heartbeat: Heartbeat

    def to_json(self):
        return {"heartbeat": self.heartbeat.to_json()}

    @classmethod
    def from_json(cls, o):
        return cls(Heartbeat.from_json(_dict_field(o, "heartbeat")))
