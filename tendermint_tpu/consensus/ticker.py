"""TimeoutTicker: the consensus timer (reference: consensus/ticker.go).

One background thread owns a single pending timeout. schedule_timeout
replaces it iff the new (H,R,S) is not older than the pending one
(consensus/ticker.go:94-131: stale ticks ignored, newer ticks overwrite).
Fired timeouts land on `chan`, consumed by the receive routine.

MockTicker is the test seam (consensus/common_test.go:426-470): it fires
only on NewHeight timeouts, immediately, so tests single-step the state
machine by injecting votes rather than waiting on wall clocks.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass

from tendermint_tpu.consensus.round_state import RoundStep
from tendermint_tpu.libs.service import BaseService


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round_: int
    step: int

    def hrs(self) -> tuple[int, int, int]:
        return (self.height, self.round_, self.step)

    def to_json(self):
        return {
            "duration": self.duration,
            "height": self.height,
            "round": self.round_,
            "step": self.step,
        }

    @classmethod
    def from_json(cls, o):
        return cls(o["duration"], o["height"], o["round"], o["step"])


class TickerI:
    def start(self) -> bool:
        raise NotImplementedError

    def stop(self) -> bool:
        raise NotImplementedError

    @property
    def chan(self) -> "queue.Queue[TimeoutInfo]":
        raise NotImplementedError

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        raise NotImplementedError


class TimeoutTicker(BaseService, TickerI):
    def __init__(self):
        BaseService.__init__(self, "TimeoutTicker")
        self._chan: queue.Queue[TimeoutInfo] = queue.Queue(maxsize=10)
        self._tick: queue.Queue[TimeoutInfo] = queue.Queue()
        self._thread: threading.Thread | None = None
        self._stop_evt = threading.Event()

    @property
    def chan(self):
        return self._chan

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        self._tick.put(ti)

    def on_start(self) -> None:
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._timeout_routine, daemon=True, name="TimeoutTicker")
        self._thread.start()

    def on_stop(self) -> None:
        self._stop_evt.set()
        self._tick.put(None)  # wake the routine
        if self._thread:
            self._thread.join(timeout=2)

    def _timeout_routine(self) -> None:
        pending: TimeoutInfo | None = None
        deadline = 0.0
        import time

        while not self._stop_evt.is_set():
            if pending is None:
                ti = self._tick.get()
                if ti is None:
                    continue
                pending, deadline = ti, time.monotonic() + ti.duration
                continue
            wait = deadline - time.monotonic()
            if wait <= 0:
                self._chan.put(pending)
                pending = None
                continue
            try:
                ti = self._tick.get(timeout=wait)
            except queue.Empty:
                continue  # deadline check on next loop
            if ti is None:
                continue
            # newer (or equal-H/R, later-step) tick replaces; stale ignored
            if ti.hrs() >= pending.hrs():
                pending, deadline = ti, time.monotonic() + ti.duration
            else:
                self.logger.debug("ignoring stale tick %s < %s", ti, pending)


class MockTicker(TickerI):
    """Fires only NewHeight timeouts, synchronously on schedule
    (consensus/common_test.go:426-470). Everything else is driven by
    injected votes in tests."""

    def __init__(self):
        self._chan: queue.Queue[TimeoutInfo] = queue.Queue(maxsize=10)
        self._only_once = False
        self._fired = False
        self._mtx = threading.Lock()

    @property
    def chan(self):
        return self._chan

    def start(self) -> bool:
        return True

    def stop(self) -> bool:
        return True

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if self._only_once and self._fired:
                return
            if ti.step == RoundStep.NEW_HEIGHT:
                self._chan.put(ti)
                self._fired = True
