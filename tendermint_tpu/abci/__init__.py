"""ABCI: the application-blockchain interface (SURVEY.md 2.2, reference dep
`tendermint/abci`).

The consensus engine is generic BFT middleware; the replicated state
machine itself is an "application" spoken to over this interface:
Info/SetOption/Query on the query connection, CheckTx on the mempool
connection, InitChain/BeginBlock/DeliverTx/EndBlock/Commit on the
consensus connection (three connections so the three planes never
serialize on one socket — proxy/multi_app_conn.go:12-18).

Includes the example apps every test tier depends on
(proxy/client.go:64-76): kvstore ("dummy"), persistent kvstore, counter,
nilapp.
"""

from tendermint_tpu.abci.types import (
    CODE_OK,
    Application,
    Header as ABCIHeader,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ResponseQuery,
    ABCIValidator,
)

__all__ = [
    "CODE_OK",
    "Application",
    "ABCIHeader",
    "ResponseCheckTx",
    "ResponseCommit",
    "ResponseDeliverTx",
    "ResponseEndBlock",
    "ResponseInfo",
    "ResponseQuery",
    "ABCIValidator",
]
