"""ABCI clients: local (in-process, mutexed) and socket (JSON-lines over
TCP), mirroring the reference's abci client library (local_client.go /
socket_client.go as wired by proxy/client.go:14-58).

The async surface matches what the reference's execution pipeline needs:
`deliver_tx_async` queues and returns a ReqRes whose callback fires on
response (state/execution.go:96-101 streams DeliverTx while consensus
proceeds); *_sync calls block.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Callable

from tendermint_tpu.abci.types import (
    ABCIValidator,
    Application,
    Header,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ResponseQuery,
)
from tendermint_tpu.libs.service import BaseService


class ReqRes:
    """A pending request/response pair with a completion callback
    (abci client ReqRes)."""

    def __init__(self, req_type: str):
        self.req_type = req_type
        self.response = None
        self._done = False
        # Event allocated only when someone actually blocks in wait():
        # the local client completes synchronously and the async mempool
        # path is callback-driven, so a CheckTx burst was paying one
        # Condition construction per tx for an Event nothing waited on
        self._done_evt: threading.Event | None = None
        self._cb: Callable | None = None
        self._mtx = threading.Lock()

    def set_callback(self, cb: Callable) -> None:
        with self._mtx:
            if self._done:
                cb(self.response)
                return
            self._cb = cb

    def complete(self, response) -> None:
        with self._mtx:
            self.response = response
            self._done = True
            if self._done_evt is not None:
                self._done_evt.set()
            cb = self._cb
        if cb:
            cb(response)

    def done(self) -> bool:
        with self._mtx:
            return self._done

    def wait(self, timeout: float | None = None):
        with self._mtx:
            if self._done:
                return self.response
            if self._done_evt is None:
                self._done_evt = threading.Event()
            evt = self._done_evt
        evt.wait(timeout)
        return self.response


class ABCIClient(BaseService):
    """Common interface of local and socket clients."""

    def set_response_callback(self, cb: Callable[[str, object], None]) -> None:
        raise NotImplementedError

    def error(self) -> Exception | None:
        return None

    # sync
    def echo_sync(self, msg: str) -> str:
        raise NotImplementedError

    def info_sync(self) -> ResponseInfo:
        raise NotImplementedError

    def set_option_sync(self, key: str, value: str) -> str:
        raise NotImplementedError

    def query_sync(self, data: bytes, path: str = "", height: int = 0, prove: bool = False) -> ResponseQuery:
        raise NotImplementedError

    def flush_sync(self) -> None:
        raise NotImplementedError

    def check_tx_sync(self, tx: bytes) -> ResponseCheckTx:
        raise NotImplementedError

    def deliver_tx_sync(self, tx: bytes) -> ResponseDeliverTx:
        raise NotImplementedError

    def init_chain_sync(self, validators: list[ABCIValidator]) -> None:
        raise NotImplementedError

    def begin_block_sync(self, block_hash: bytes, header: Header) -> None:
        raise NotImplementedError

    def end_block_sync(self, height: int) -> ResponseEndBlock:
        raise NotImplementedError

    def commit_sync(self) -> ResponseCommit:
        raise NotImplementedError

    # async
    def check_tx_async(self, tx: bytes) -> ReqRes:
        raise NotImplementedError

    def check_tx_many_async(self, txs: list[bytes]) -> list[ReqRes]:
        """Grouped CheckTx dispatch — the mempool's batched signature
        gate admits whole batches at once, and per-tx dispatch overhead
        (locks, allocations) caps burst throughput well below the
        verifier's rate. Default is the per-tx loop; clients that can
        amortize (LocalClient takes its app lock once) override."""
        return [self.check_tx_async(tx) for tx in txs]

    def deliver_tx_async(self, tx: bytes) -> ReqRes:
        raise NotImplementedError

    def deliver_txs_async(self, txs: list[bytes]) -> list[ReqRes]:
        """Grouped DeliverTx dispatch (round 14) — the execution
        pipeline hands the whole block's txs at once so a batch-capable
        app (the kvstore sharded apply) sees them together and a local
        client pays ONE lock round trip. Default is the per-tx loop,
        which for the socket client is already pipelined in order."""
        return [self.deliver_tx_async(tx) for tx in txs]

    def flush_async(self) -> ReqRes:
        raise NotImplementedError


class LocalClient(ABCIClient):
    """In-process client: a mutex around the Application, exactly the
    reference's local client concurrency model (one connection = one
    serialized stream of calls)."""

    def __init__(self, app: Application, mtx: threading.RLock | None = None):
        super().__init__("abci.LocalClient")
        self.app = app
        self._app_mtx = mtx or threading.RLock()
        self._res_cb: Callable | None = None

    def set_response_callback(self, cb: Callable) -> None:
        self._res_cb = cb

    def _notify(self, req_type: str, req, res):
        if self._res_cb:
            self._res_cb(req_type, req, res)

    # -- sync --------------------------------------------------------------

    def echo_sync(self, msg: str) -> str:
        return msg

    def info_sync(self) -> ResponseInfo:
        with self._app_mtx:
            return self.app.info()

    def set_option_sync(self, key: str, value: str) -> str:
        with self._app_mtx:
            return self.app.set_option(key, value)

    def query_sync(self, data: bytes, path: str = "", height: int = 0, prove: bool = False) -> ResponseQuery:
        with self._app_mtx:
            return self.app.query(data, path, height, prove)

    def flush_sync(self) -> None:
        pass

    def check_tx_sync(self, tx: bytes) -> ResponseCheckTx:
        with self._app_mtx:
            res = self.app.check_tx(tx)
        self._notify("check_tx", tx, res)
        return res

    def deliver_tx_sync(self, tx: bytes) -> ResponseDeliverTx:
        with self._app_mtx:
            res = self.app.deliver_tx(tx)
        self._notify("deliver_tx", tx, res)
        return res

    def init_chain_sync(self, validators: list[ABCIValidator]) -> None:
        with self._app_mtx:
            self.app.init_chain(validators)

    def begin_block_sync(self, block_hash: bytes, header: Header) -> None:
        with self._app_mtx:
            self.app.begin_block(block_hash, header)

    def end_block_sync(self, height: int) -> ResponseEndBlock:
        with self._app_mtx:
            return self.app.end_block(height)

    def commit_sync(self) -> ResponseCommit:
        with self._app_mtx:
            return self.app.commit()

    # -- async (executed inline; callback semantics preserved) -------------

    def check_tx_async(self, tx: bytes) -> ReqRes:
        rr = ReqRes("check_tx")
        rr.complete(self.check_tx_sync(tx))
        return rr

    def check_tx_many_async(self, txs: list[bytes]) -> list[ReqRes]:
        # one app-lock round trip for the whole batch (vs one per tx);
        # response notifications keep per-tx order, after the lock drops
        # — same ordering check_tx_sync produces for sequential calls
        with self._app_mtx:
            reses = [self.app.check_tx(tx) for tx in txs]
        out = []
        for tx, res in zip(txs, reses):
            self._notify("check_tx", tx, res)
            rr = ReqRes("check_tx")
            rr.complete(res)
            out.append(rr)
        return out

    def deliver_tx_async(self, tx: bytes) -> ReqRes:
        rr = ReqRes("deliver_tx")
        rr.complete(self.deliver_tx_sync(tx))
        return rr

    def deliver_txs_async(self, txs: list[bytes]) -> list[ReqRes]:
        # one app-lock round trip for the whole block; an app exposing
        # deliver_txs (kvstore sharded apply, round 14) gets the batch
        # wholesale, others run the same serial loop under the lock.
        # Notifications keep per-tx order, after the lock drops — same
        # ordering sequential deliver_tx_sync calls produce.
        with self._app_mtx:
            batch = getattr(self.app, "deliver_txs", None)
            if batch is not None:
                reses = batch(list(txs))
            else:
                reses = [self.app.deliver_tx(tx) for tx in txs]
        out = []
        for tx, res in zip(txs, reses):
            self._notify("deliver_tx", tx, res)
            rr = ReqRes("deliver_tx")
            rr.complete(res)
            out.append(rr)
        return out

    def flush_async(self) -> ReqRes:
        rr = ReqRes("flush")
        rr.complete(None)
        return rr


# ---------------------------------------------------------------------------
# socket transport: length-free JSON lines (one request/response per line)
# ---------------------------------------------------------------------------

_RES_TYPES = {
    "info": ResponseInfo,
    "check_tx": ResponseCheckTx,
    "deliver_tx": ResponseDeliverTx,
    "commit": ResponseCommit,
    "query": ResponseQuery,
    "end_block": ResponseEndBlock,
}


class SocketClient(ABCIClient):
    """Remote app over TCP. Requests are pipelined in order on one socket;
    responses come back in order (the ABCI socket protocol's ordering
    contract). JSON-lines framing replaces the reference's varint framing —
    this framework defines its own wire (no cross-compat requirement)."""

    def __init__(self, addr: str):
        super().__init__("abci.SocketClient")
        host, port = addr.rsplit(":", 1)
        self._addr = (host, int(port))
        self._sock: socket.socket | None = None
        self._rfile = None
        self._wmtx = threading.Lock()
        self._pending: list[ReqRes] = []
        self._pending_mtx = threading.Lock()
        self._res_cb: Callable | None = None
        self._err: Exception | None = None

    def on_start(self) -> None:
        self._sock = socket.create_connection(self._addr, timeout=10)
        self._sock.settimeout(None)
        self._rfile = self._sock.makefile("rb")
        threading.Thread(target=self._recv_loop, daemon=True, name="abci-recv").start()

    def on_stop(self) -> None:
        try:
            if self._sock:
                self._sock.close()
        except OSError:
            pass

    def error(self) -> Exception | None:
        return self._err

    def set_response_callback(self, cb: Callable) -> None:
        self._res_cb = cb

    def _send(self, req: dict) -> ReqRes:
        rr = ReqRes(req["type"])
        data = (json.dumps(req) + "\n").encode()
        with self._wmtx:
            with self._pending_mtx:
                self._pending.append(rr)
            self._sock.sendall(data)
        return rr

    def _recv_loop(self) -> None:
        try:
            while True:
                line = self._rfile.readline()
                if not line:
                    break
                obj = json.loads(line)
                with self._pending_mtx:
                    rr = self._pending.pop(0)
                res = self._decode(rr.req_type, obj)
                if self._res_cb and rr.req_type in ("check_tx", "deliver_tx"):
                    # callback contract: tx as raw bytes, and the GLOBAL
                    # callback fires before per-request completion — same
                    # as LocalClient. The mempool's admission path relies
                    # on this order: a lane-full rejection mutates the
                    # response before any broadcast_tx waiter sees it.
                    tx_hex = obj.get("_tx")
                    tx = bytes.fromhex(tx_hex) if tx_hex else None
                    self._res_cb(rr.req_type, tx, res)
                rr.complete(res)
        except Exception as e:
            self._err = e
        # receive loop is done (EOF or error): release every in-flight
        # waiter now instead of letting each block out its full timeout
        if self._err is None:
            self._err = ConnectionError("abci socket closed")
        with self._pending_mtx:
            pending, self._pending = self._pending, []
        for rr in pending:
            rr.complete(None)

    @staticmethod
    def _decode(req_type: str, obj: dict):
        cls = _RES_TYPES.get(req_type)
        if cls is None:
            return obj.get("value")
        return cls.from_json(obj["value"])

    # -- calls -------------------------------------------------------------

    def _call_sync(self, req: dict, timeout: float = 30):
        rr = self._send(req)
        res = rr.wait(timeout)
        if self._err:
            raise self._err
        if res is None and not rr.done():
            raise TimeoutError(f"abci {req['type']} timed out after {timeout}s")
        return res

    def echo_sync(self, msg: str) -> str:
        return self._call_sync({"type": "echo", "msg": msg})

    def info_sync(self) -> ResponseInfo:
        return self._call_sync({"type": "info"})

    def set_option_sync(self, key: str, value: str) -> str:
        return self._call_sync({"type": "set_option", "key": key, "value": value})

    def query_sync(self, data: bytes, path: str = "", height: int = 0, prove: bool = False) -> ResponseQuery:
        return self._call_sync(
            {"type": "query", "data": data.hex(), "path": path, "height": height, "prove": prove}
        )

    def flush_sync(self) -> None:
        self._call_sync({"type": "flush"})

    def check_tx_sync(self, tx: bytes) -> ResponseCheckTx:
        return self._call_sync({"type": "check_tx", "tx": tx.hex()})

    def deliver_tx_sync(self, tx: bytes) -> ResponseDeliverTx:
        return self._call_sync({"type": "deliver_tx", "tx": tx.hex()})

    def init_chain_sync(self, validators: list[ABCIValidator]) -> None:
        self._call_sync(
            {"type": "init_chain", "validators": [v.to_json() for v in validators]}
        )

    def begin_block_sync(self, block_hash: bytes, header: Header) -> None:
        self._call_sync(
            {"type": "begin_block", "hash": block_hash.hex(), "header": header.to_json()}
        )

    def end_block_sync(self, height: int) -> ResponseEndBlock:
        return self._call_sync({"type": "end_block", "height": height})

    def commit_sync(self) -> ResponseCommit:
        return self._call_sync({"type": "commit"})

    def check_tx_async(self, tx: bytes) -> ReqRes:
        return self._send({"type": "check_tx", "tx": tx.hex()})

    def deliver_tx_async(self, tx: bytes) -> ReqRes:
        return self._send({"type": "deliver_tx", "tx": tx.hex()})

    def flush_async(self) -> ReqRes:
        return self._send({"type": "flush"})


class ABCIServer(BaseService):
    """Serves one Application over TCP (abci socket server). Each
    connection gets its own serialized request stream; the app mutex makes
    concurrent connections safe (the 3-connection proxy relies on this)."""

    def __init__(self, app: Application, addr: str):
        super().__init__("abci.Server")
        host, port = addr.rsplit(":", 1)
        self.app = app
        self._app_mtx = threading.RLock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        req = json.loads(line)
                    except json.JSONDecodeError:
                        return
                    res = outer._dispatch(req)
                    out = json.dumps(res) + "\n"
                    self.wfile.write(out.encode())
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, int(port)), Handler)
        self.addr = f"{host}:{self._server.server_address[1]}"

    def on_start(self) -> None:
        threading.Thread(
            target=self._server.serve_forever, daemon=True, name="abci-server"
        ).start()

    def on_stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def _dispatch(self, req: dict) -> dict:
        with self._app_mtx:
            return dispatch_request(self.app, req)


def dispatch_request(app: Application, req: dict) -> dict:
    """One ABCI request (the JSON wire dicts SocketClient/GRPCClient
    build) against an Application. Caller holds the app mutex. Shared by
    the socket server and the gRPC server (abci/grpc.py)."""
    t = req["type"]
    if t == "echo":
        return {"value": req.get("msg", "")}
    if t == "flush":
        return {"value": None}
    if t == "info":
        return {"value": app.info().to_json()}
    if t == "set_option":
        return {"value": app.set_option(req["key"], req["value"])}
    if t == "query":
        return {
            "value": app.query(
                bytes.fromhex(req.get("data", "")),
                req.get("path", ""),
                req.get("height", 0),
                req.get("prove", False),
            ).to_json()
        }
    if t == "check_tx":
        return {"value": app.check_tx(bytes.fromhex(req["tx"])).to_json(), "_tx": req["tx"]}
    if t == "deliver_tx":
        return {"value": app.deliver_tx(bytes.fromhex(req["tx"])).to_json(), "_tx": req["tx"]}
    if t == "init_chain":
        app.init_chain([ABCIValidator.from_json(v) for v in req.get("validators", [])])
        return {"value": None}
    if t == "begin_block":
        app.begin_block(bytes.fromhex(req["hash"]), Header.from_json(req["header"]))
        return {"value": None}
    if t == "end_block":
        return {"value": app.end_block(req["height"]).to_json()}
    if t == "commit":
        return {"value": app.commit().to_json()}
    return {"value": None, "error": f"unknown request {t}"}
