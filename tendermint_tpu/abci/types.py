"""ABCI message types and the Application interface.

Mirrors the reference's abci/types surface (the v0.5-era protocol that
Tendermint v0.11 speaks): Info, SetOption, CheckTx, DeliverTx, BeginBlock,
EndBlock, Commit, Query, InitChain, Echo, Flush. Code 0 is OK; any other
code is app-defined rejection.
"""

from __future__ import annotations

from dataclasses import dataclass, field

CODE_OK = 0
CODE_BAD_NONCE = 4  # counter-app style ordering violation
CODE_UNAUTHORIZED = 3
CODE_UNSUPPORTED = 5  # query feature the app cannot serve (e.g. prove=True)
CODE_MEMPOOL_FULL = 6  # shed at a mempool lane cap / load-shed ladder (round 23)


def proofs_unsupported_response(app, key: bytes) -> "ResponseQuery":
    """The CLEAR `prove=True`-against-a-non-proving-app refusal (round
    13): apps without an authenticated state tree must answer with this
    instead of silently omitting the proof field — a light client that
    trusted the bare value would be reading unverified state."""
    return ResponseQuery(
        code=CODE_UNSUPPORTED,
        key=key,
        log=(
            f"proofs unsupported: {type(app).__name__} does not maintain "
            "an authenticated state tree"
        ),
    )


@dataclass
class ABCIValidator:
    """Validator diff entry for EndBlock (power 0 removes)."""

    pub_key_json: list  # typed pubkey json [type, hexbytes]
    power: int

    def to_json(self):
        return {"pub_key": self.pub_key_json, "power": self.power}

    @classmethod
    def from_json(cls, obj):
        return cls(obj["pub_key"], obj["power"])


@dataclass
class Header:
    """Minimal block header passed to BeginBlock (abci Header message)."""

    chain_id: str = ""
    height: int = 0
    time_ns: int = 0
    num_txs: int = 0
    app_hash: bytes = b""

    def to_json(self):
        return {
            "chain_id": self.chain_id,
            "height": self.height,
            "time": self.time_ns,
            "num_txs": self.num_txs,
            "app_hash": self.app_hash.hex().upper(),
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            obj.get("chain_id", ""),
            obj.get("height", 0),
            obj.get("time", 0),
            obj.get("num_txs", 0),
            bytes.fromhex(obj.get("app_hash", "")),
        )


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    last_block_height: int = 0
    last_block_app_hash: bytes = b""

    def to_json(self):
        return {
            "data": self.data,
            "version": self.version,
            "last_block_height": self.last_block_height,
            "last_block_app_hash": self.last_block_app_hash.hex().upper(),
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            obj.get("data", ""),
            obj.get("version", ""),
            obj.get("last_block_height", 0),
            bytes.fromhex(obj.get("last_block_app_hash", "")),
        )


@dataclass
class ResponseCheckTx:
    code: int = CODE_OK
    data: bytes = b""
    log: str = ""
    # app-visible priority hint: >0 routes the tx to the mempool's
    # priority lane, <0 to the bulk lane, 0 (the default) to the default
    # lane. Key-absent on the wire when 0 so pre-existing CheckTx JSON
    # stays byte-identical (same pattern as the aggregate-commit fields).
    priority: int = 0

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_OK

    def to_json(self):
        obj = {"code": self.code, "data": self.data.hex().upper(), "log": self.log}
        if self.priority:
            obj["priority"] = self.priority
        return obj

    @classmethod
    def from_json(cls, obj):
        return cls(
            obj.get("code", 0),
            bytes.fromhex(obj.get("data", "")),
            obj.get("log", ""),
            obj.get("priority", 0),
        )


@dataclass
class ResponseDeliverTx:
    code: int = CODE_OK
    data: bytes = b""
    log: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_OK

    def to_json(self):
        return {"code": self.code, "data": self.data.hex().upper(), "log": self.log}

    @classmethod
    def from_json(cls, obj):
        return cls(obj.get("code", 0), bytes.fromhex(obj.get("data", "")), obj.get("log", ""))


@dataclass
class ResponseCommit:
    code: int = CODE_OK
    data: bytes = b""  # the new app hash
    log: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == CODE_OK

    def to_json(self):
        return {"code": self.code, "data": self.data.hex().upper(), "log": self.log}

    @classmethod
    def from_json(cls, obj):
        return cls(obj.get("code", 0), bytes.fromhex(obj.get("data", "")), obj.get("log", ""))


@dataclass
class ResponseQuery:
    code: int = CODE_OK
    index: int = -1
    key: bytes = b""
    value: bytes = b""
    proof: bytes = b""
    height: int = 0
    log: str = ""

    def to_json(self):
        return {
            "code": self.code,
            "index": self.index,
            "key": self.key.hex().upper(),
            "value": self.value.hex().upper(),
            "proof": self.proof.hex().upper(),
            "height": self.height,
            "log": self.log,
        }

    @classmethod
    def from_json(cls, obj):
        return cls(
            obj.get("code", 0),
            obj.get("index", -1),
            bytes.fromhex(obj.get("key", "")),
            bytes.fromhex(obj.get("value", "")),
            bytes.fromhex(obj.get("proof", "")),
            obj.get("height", 0),
            obj.get("log", ""),
        )


@dataclass
class ResponseEndBlock:
    diffs: list[ABCIValidator] = field(default_factory=list)

    def to_json(self):
        return {"diffs": [d.to_json() for d in self.diffs]}

    @classmethod
    def from_json(cls, obj):
        return cls([ABCIValidator.from_json(d) for d in obj.get("diffs", [])])


class Application:
    """The interface ABCI apps implement (abci BaseApplication).
    All methods are synchronous; the local client adds the mutex, the
    socket server adds the wire."""

    def info(self) -> ResponseInfo:
        return ResponseInfo()

    def set_option(self, key: str, value: str) -> str:
        return ""

    def query(self, data: bytes, path: str = "", height: int = 0, prove: bool = False) -> ResponseQuery:
        if prove:
            return proofs_unsupported_response(self, data)
        return ResponseQuery()

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        return ResponseCheckTx()

    def init_chain(self, validators: list[ABCIValidator]) -> None:
        pass

    def begin_block(self, block_hash: bytes, header: Header) -> None:
        pass

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        return ResponseDeliverTx()

    def end_block(self, height: int) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    # -- state-sync snapshot hooks (beyond the v0.5 ABCI surface: the
    # reference era predates statesync; these mirror the later
    # ListSnapshots/ApplySnapshotChunk shape at whole-state granularity) --

    def snapshot(self) -> bytes | None:
        """Deterministic byte serialization of the app's COMMITTED state
        at its current height, or None when the app does not support
        snapshots (the statesync producer then skips it). Must be a pure
        read: called synchronously between Commit and the next
        BeginBlock."""
        return None

    def restore(
        self, data: bytes, height: int | None = None, app_hash: bytes | None = None
    ) -> None:
        """Replace the app's state wholesale with a snapshot()'s bytes.
        Only valid on a fresh app (height 0). `height`/`app_hash`, when
        given, are the LIGHT-VERIFIED values the snapshot must land on —
        the app MUST validate `data` against them (and against its own
        internal consistency, e.g. recomputing the app hash from the
        restored state) and raise ValueError BEFORE mutating or
        persisting anything: `data` is attacker input until it checks
        out. The restorer re-checks the resulting Info() as a final
        gate, but by then a badly-written app has already applied."""
        raise NotImplementedError(f"{type(self).__name__} cannot restore snapshots")
