"""NilApp: accepts everything, stores nothing (abci nilapp; reference
proxy/client.go:75)."""

from tendermint_tpu.abci.types import Application


class NilApp(Application):
    pass
