"""KVStore app — the reference's "dummy" Merkle key-value store, the app
behind the 4-node testnet north star and most consensus tests
(consensus/common_test.go:26-27).

Txs are "key=value" (or raw bytes stored as key=key). The app hash is the
Merkle root over sorted kv pairs, so all correct nodes agree on state.
The persistent variant survives restarts (handshake/replay tests) and
accepts validator-set change txs: "val:<pubkey_hex>/<power>" — the
reference's persistent_dummy behavior.
"""

from __future__ import annotations

import json
import os

from tendermint_tpu.abci.types import (
    ABCIValidator,
    Application,
    CODE_OK,
    CODE_UNAUTHORIZED,
    Header,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ResponseQuery,
)
from tendermint_tpu.merkle.simple import simple_hash_from_map

VAL_TX_PREFIX = b"val:"


class KVStoreApp(Application):
    def __init__(self):
        self.state: dict[str, bytes] = {}
        self.height = 0
        self.app_hash = b""

    def info(self) -> ResponseInfo:
        return ResponseInfo(
            data=f"{{\"size\":{len(self.state)}}}",
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        return ResponseCheckTx(code=CODE_OK)

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k, v = tx, tx
        # latin-1 is a lossless byte<->str bijection: distinct byte keys
        # stay distinct (the reference dummy app keys on raw bytes)
        self.state[k.decode("latin-1")] = v
        return ResponseDeliverTx(code=CODE_OK)

    def commit(self) -> ResponseCommit:
        self.height += 1
        self.app_hash = (
            simple_hash_from_map(self.state) if self.state else b""
        )
        return ResponseCommit(code=CODE_OK, data=self.app_hash)

    def query(self, data: bytes, path: str = "", height: int = 0, prove: bool = False) -> ResponseQuery:
        key = data.decode("latin-1")
        value = self.state.get(key)
        if value is None:
            return ResponseQuery(code=CODE_OK, key=data, log="does not exist")
        return ResponseQuery(code=CODE_OK, key=data, value=value, log="exists")

    # -- state-sync hooks --------------------------------------------------

    def snapshot(self) -> bytes | None:
        """Canonical JSON of the committed (height, app_hash, state) —
        sorted keys, so two replicas at the same height serialize
        byte-identically (the statesync manifest digests depend on it)."""
        return json.dumps(
            {
                "height": self.height,
                "app_hash": self.app_hash.hex(),
                "state": {k: v.hex() for k, v in self.state.items()},
            },
            sort_keys=True,
        ).encode()

    def restore(
        self, data: bytes, height: int | None = None, app_hash: bytes | None = None
    ) -> None:
        if self.height != 0 or self.state:
            raise ValueError("restore only valid on a fresh app")
        obj = json.loads(data)
        # shape-check before touching fields: a non-dict here would raise
        # AttributeError, which escapes the restorer's ValueError net
        if not isinstance(obj, dict) or not isinstance(obj.get("state"), dict):
            raise ValueError("snapshot app state must be an object")
        new_height = obj["height"]
        claimed_hash = bytes.fromhex(obj["app_hash"])
        state = {k: bytes.fromhex(v) for k, v in obj["state"].items()}
        if not isinstance(new_height, int) or isinstance(new_height, bool) or new_height < 1:
            raise ValueError(f"bad snapshot height {new_height!r}")
        # the app hash is a pure function of the state map: recompute it
        # rather than trust the snapshot's claim — a payload whose hash
        # and state disagree must refuse here, before anything mutates
        recomputed = simple_hash_from_map(state) if state else b""
        if recomputed != claimed_hash:
            raise ValueError("snapshot app_hash does not match its state")
        if height is not None and new_height != height:
            raise ValueError(
                f"snapshot is at height {new_height}, expected {height}"
            )
        if app_hash is not None and claimed_hash != app_hash:
            raise ValueError("snapshot app_hash does not match the verified hash")
        self.height = new_height
        self.app_hash = claimed_hash
        self.state = state


class PersistentKVStoreApp(KVStoreApp):
    """KVStore plus disk persistence and validator-set changes via
    val-txs; the backbone of the crash-restart test tier
    (test/persist/*.sh in the reference)."""

    def __init__(self, db_dir: str):
        super().__init__()
        self.db_path = os.path.join(db_dir, "kvstore_app.json")
        os.makedirs(db_dir, exist_ok=True)
        self.val_diffs: list[ABCIValidator] = []
        self.validators: dict[str, int] = {}  # pubkey hex -> power
        self._load()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.db_path):
            return
        with open(self.db_path) as f:
            obj = json.load(f)
        self.height = obj["height"]
        self.app_hash = bytes.fromhex(obj["app_hash"])
        self.state = {k: bytes.fromhex(v) for k, v in obj["state"].items()}
        self.validators = obj.get("validators", {})

    def _save(self) -> None:
        tmp = self.db_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "height": self.height,
                    "app_hash": self.app_hash.hex(),
                    "state": {k: v.hex() for k, v in self.state.items()},
                    "validators": self.validators,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.db_path)

    # -- validator updates -------------------------------------------------

    def init_chain(self, validators: list[ABCIValidator]) -> None:
        for v in validators:
            self.validators[v.pub_key_json[1]] = v.power

    def begin_block(self, block_hash: bytes, header: Header) -> None:
        self.val_diffs = []

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        if tx.startswith(VAL_TX_PREFIX):
            err = self._parse_val_tx(tx) is None
            if err:
                return ResponseCheckTx(code=CODE_UNAUTHORIZED, log="bad val tx")
        return ResponseCheckTx(code=CODE_OK)

    def _parse_val_tx(self, tx: bytes):
        try:
            body = tx[len(VAL_TX_PREFIX) :].decode()
            pubkey_hex, power_s = body.split("/")
            bytes.fromhex(pubkey_hex)
            return pubkey_hex.upper(), int(power_s)
        except (ValueError, IndexError):
            return None

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        if tx.startswith(VAL_TX_PREFIX):
            parsed = self._parse_val_tx(tx)
            if parsed is None:
                return ResponseDeliverTx(code=CODE_UNAUTHORIZED, log="bad val tx")
            pubkey_hex, power = parsed
            if power == 0:
                self.validators.pop(pubkey_hex, None)
            else:
                self.validators[pubkey_hex] = power
            from tendermint_tpu.crypto.keys import TYPE_ED25519

            self.val_diffs.append(ABCIValidator([TYPE_ED25519, pubkey_hex], power))
            return ResponseDeliverTx(code=CODE_OK)
        return super().deliver_tx(tx)

    def end_block(self, height: int) -> ResponseEndBlock:
        return ResponseEndBlock(diffs=list(self.val_diffs))

    def commit(self) -> ResponseCommit:
        res = super().commit()
        self._save()
        return res

    # -- state-sync hooks: the persistent variant also carries its
    # validator registry, and a restore lands on disk immediately so a
    # restart handshakes at the snapshot height instead of replaying a
    # chain whose pre-snapshot blocks the restored node never had ------

    def snapshot(self) -> bytes | None:
        obj = json.loads(super().snapshot())
        obj["validators"] = self.validators
        return json.dumps(obj, sort_keys=True).encode()

    def restore(
        self, data: bytes, height: int | None = None, app_hash: bytes | None = None
    ) -> None:
        obj = json.loads(data)
        if not isinstance(obj, dict):
            raise ValueError("snapshot app state must be an object")
        validators = obj.get("validators", {})
        if not isinstance(validators, dict):
            raise ValueError("snapshot validators must be an object")
        for k, power in validators.items():
            if not isinstance(power, int) or isinstance(power, bool) or power < 1:
                raise ValueError(f"bad validator power {power!r}")
            try:
                bytes.fromhex(k)
            except (TypeError, ValueError):
                raise ValueError("bad validator pubkey in snapshot")
        super().restore(data, height=height, app_hash=app_hash)
        self.validators = validators
        self._save()
