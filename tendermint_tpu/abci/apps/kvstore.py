"""KVStore app — the reference's "dummy" Merkle key-value store, the app
behind the 4-node testnet north star and most consensus tests
(consensus/common_test.go:26-27).

Txs are "key=value" (or raw bytes stored as key=key). Round 13: the app
hash is the root of an AUTHENTICATED state tree (statetree.VersionedTree
— a canonical merkleized treap, docs/state-tree.md) instead of a full
simple_hash_from_map rebuild per commit: commits recompute only the
O(changed * log n) dirty nodes (batched through the gateway hash plane
when wired), `query(prove=True)` answers with a real membership/absence
proof a light client verifies against a header's app_hash, and the
versioned roots power delta snapshots (statesync/producer.py). The
plain `state` dict stays as the serialization/iteration mirror; the
tree is the commitment.

The persistent variant survives restarts (handshake/replay tests) and
accepts validator-set change txs: "val:<pubkey_hex>/<power>" — the
reference's persistent_dummy behavior.
"""

from __future__ import annotations

import json
import os
import threading

from tendermint_tpu.abci.types import (
    ABCIValidator,
    Application,
    CODE_OK,
    CODE_UNAUTHORIZED,
    Header,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ResponseQuery,
)
from tendermint_tpu.libs.envknob import env_number
from tendermint_tpu.statetree import VersionedTree
from tendermint_tpu.statetree.tree import TreeError

VAL_TX_PREFIX = b"val:"
# round 13: "rm:<key>" deletes a key (beyond the reference dummy, which
# never deletes — an authenticated tree without delete coverage would
# leave the absence-proof/delta-delete planes untested end to end)
DEL_TX_PREFIX = b"rm:"
# round 23 (docs/serving.md): app-visible mempool lane hints. A "pri:"
# key routes to the priority lane, "bulk:" to the bulk lane; delivery is
# untouched (the prefix stays part of the key, so blocks are
# byte-identical whether or not the mempool honors the hint).
PRI_TX_PREFIX = b"pri:"
BULK_TX_PREFIX = b"bulk:"


def tx_priority_hint(tx: bytes) -> int:
    if tx.startswith(PRI_TX_PREFIX):
        return 1
    if tx.startswith(BULK_TX_PREFIX):
        return -1
    return 0

# round 14 (docs/execution-pipeline.md): keyspace-sharded parallel apply.
# TENDERMINT_KVSTORE_SHARDS=N (>1) routes whole-block DeliverTx batches
# through deliver_txs(): keys shard by their canonical key_priority
# prefix, N workers fold each shard's ops IN TX ORDER to a final per-key
# op, priorities batch through the gateway's RIPEMD plane, and ONE
# deterministic merge (sorted key order) mutates state + tree — the
# canonical-treap shape is a pure function of the final key set, so the
# commit root is byte-identical to the serial per-tx apply (asserted in
# tests/test_pipeline.py and benches/bench_pipeline.py). Default 0 =
# the serial loop.
SHARDS_DEFAULT = int(env_number("TENDERMINT_KVSTORE_SHARDS", 0, cast=int))
SHARD_MIN_TXS = max(2, int(env_number("TENDERMINT_KVSTORE_SHARD_MIN", 32,
                                      cast=int)))


class KVStoreApp(Application):
    def __init__(self):
        self.state: dict[str, bytes] = {}
        self.height = 0
        self.app_hash = b""
        # the authenticated commitment over the state map: one immutable
        # root per committed height. node/node.py (and DevChain) inject
        # the gateway Hasher post-construction so dirty-node recompute
        # batches onto the device plane.
        self.tree = VersionedTree()
        # round 14: sharded parallel apply shape (see module docstring);
        # assignable per instance for benches/tests
        self.shards = SHARDS_DEFAULT
        self.shard_min_txs = SHARD_MIN_TXS
        self.sharded_batches = 0  # deliver_txs batches that took the
        #                           parallel path (observability/tests)

    def info(self) -> ResponseInfo:
        return ResponseInfo(
            data=f"{{\"size\":{len(self.state)}}}",
            last_block_height=self.height,
            last_block_app_hash=self.app_hash,
        )

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        return ResponseCheckTx(code=CODE_OK, priority=tx_priority_hint(tx))

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        if tx.startswith(DEL_TX_PREFIX):
            k = tx[len(DEL_TX_PREFIX):]
            self.state.pop(k.decode("latin-1"), None)
            self.tree.delete(k)
            return ResponseDeliverTx(code=CODE_OK)
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k, v = tx, tx
        # latin-1 is a lossless byte<->str bijection: distinct byte keys
        # stay distinct (the reference dummy app keys on raw bytes)
        self.state[k.decode("latin-1")] = v
        self.tree.set(k, v)
        return ResponseDeliverTx(code=CODE_OK)

    # -- sharded parallel apply (round 14) --------------------------------

    def _shardable_op(self, tx: bytes):
        """("set", key, value) | ("del", key, None) for a pure key-value
        tx, or None for a tx the sharded fold cannot commute (those apply
        via deliver_tx, in tx order, during the merge)."""
        if tx.startswith(DEL_TX_PREFIX):
            return ("del", tx[len(DEL_TX_PREFIX):], None)
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
            return ("set", k, v)
        return ("set", tx, tx)

    def _batch_priorities(self, keys: list[bytes]) -> dict[bytes, bytes]:
        """Canonical key_priority for every key in ONE batched RIPEMD
        pass (gateway plane when the tree carries a hasher: native x16 /
        streamed devd) instead of one hashlib call per key — the
        measured win of the sharded path at wide blocks.

        Trade-off, accepted: shard ROUTING needs a priority for every
        touched key (the shard-by-key_priority-prefix contract), while
        the serial path only hashes keys NEW to the tree — on an
        update-heavy block without a gateway hasher this batch does more
        raw hashing than serial; with one wired it still wins on the
        batched dispatch."""
        from tendermint_tpu.merkle.statetree_proof import _PRIO_PREFIX

        preimages = [_PRIO_PREFIX + k for k in keys]
        hasher = getattr(self.tree, "hasher", None)
        if hasher is not None and len(preimages) >= 16:
            digests = hasher.part_leaf_hashes(preimages)
        else:
            from tendermint_tpu.crypto.hashing import ripemd160

            digests = [ripemd160(p) for p in preimages]
        return dict(zip(keys, digests))

    def deliver_txs(self, txs: list[bytes],
                    deliver_one=None) -> list[ResponseDeliverTx]:
        """Whole-block DeliverTx (state/execution.py routes here through
        AppConnConsensus.deliver_txs_async when the app offers it).
        Serial loop below the shard floor; above it, the keyspace-sharded
        parallel fold + deterministic merge described in the module
        docstring. Final state, responses, AND the committed tree root
        are byte-identical to the serial per-tx path.

        `deliver_one` overrides the per-tx fallback/non-shardable path —
        a subclass that pre-processes the batch (signedkv strips verified
        envelopes) passes the PLAIN kv apply so its own deliver_tx's
        per-tx preprocessing is not re-entered on the stripped bytes."""
        deliver_one = deliver_one if deliver_one is not None else self.deliver_tx
        n = int(self.shards)
        if n <= 1 or len(txs) < self.shard_min_txs:
            return [deliver_one(tx) for tx in txs]
        self.sharded_batches += 1
        plan = [self._shardable_op(tx) for tx in txs]
        keys = sorted({op[1] for op in plan if op is not None})
        prios = self._batch_priorities(keys)
        shard_of = {k: prios[k][0] % n for k in keys}
        buckets: list[list] = [[] for _ in range(n)]
        for op in plan:
            if op is not None:
                buckets[shard_of[op[1]]].append(op)
        # parallel fold: each worker reduces its shard's ops — kept in
        # global tx order, and a key lives in exactly one shard, so
        # per-key order (the only order that matters in a kv store) is
        # the serial one
        folded: list[dict | None] = [None] * n
        def fold(si: int) -> None:
            final: dict = {}
            for kind, k, v in buckets[si]:
                final[k] = (kind, v)
            folded[si] = final
        workers = [
            threading.Thread(target=fold, args=(si,), name=f"kv.shard{si}")
            for si in range(1, n)
        ]
        for w in workers:
            w.start()
        fold(0)
        for w in workers:
            w.join()

        from tendermint_tpu.state.fail import pipeline_point

        pipeline_point("mid_parallel_apply")

        # responses in tx order; non-shardable txs (validator txs in the
        # persistent variant) apply HERE, in tx order — they touch state
        # disjoint from the kv fold, so the interleave is immaterial
        responses = []
        for tx, op in zip(txs, plan):
            if op is None:
                responses.append(deliver_one(tx))
            else:
                responses.append(ResponseDeliverTx(code=CODE_OK))
        # deterministic merge: one mutation per final key, sorted key
        # order (the treap shape is a function of the key SET; the order
        # only has to be deterministic)
        merged: dict = {}
        for final in folded:
            merged.update(final)  # shard key ranges are disjoint
        for k in sorted(merged):
            kind, v = merged[k]
            if kind == "del":
                self.state.pop(k.decode("latin-1"), None)
                self.tree.delete(k)
            else:
                self.state[k.decode("latin-1")] = v
                self.tree.set(k, v, prio=prios[k])
        return responses

    def commit(self) -> ResponseCommit:
        self.height += 1
        self.app_hash = self.tree.commit(self.height)
        return ResponseCommit(code=CODE_OK, data=self.app_hash)

    def query(self, data: bytes, path: str = "", height: int = 0, prove: bool = False) -> ResponseQuery:
        key = data.decode("latin-1")
        if not prove:
            value = self.state.get(key)
            if value is None:
                return ResponseQuery(code=CODE_OK, key=data, log="does not exist")
            return ResponseQuery(code=CODE_OK, key=data, value=value, log="exists")
        # proof-backed read: prove against a COMMITTED root (the proof's
        # height binds to header (height+1).app_hash on the light side)
        version = int(height) if height else self.height
        if version < 1:
            return ResponseQuery(
                code=CODE_UNAUTHORIZED, key=data,
                log="no committed state to prove against",
            )
        try:
            proof = self.tree.prove(data, version)
        except TreeError as exc:
            return ResponseQuery(
                code=CODE_UNAUTHORIZED, key=data, height=version,
                log=f"cannot prove at height {version}: {exc}",
            )
        proof_bytes = json.dumps(proof.to_json(), sort_keys=True).encode()
        if proof.value is None:
            return ResponseQuery(
                code=CODE_OK, key=data, proof=proof_bytes, height=version,
                log="does not exist",
            )
        return ResponseQuery(
            code=CODE_OK, key=data, value=proof.value, proof=proof_bytes,
            height=version, log="exists",
        )

    # -- state-sync hooks --------------------------------------------------

    def snapshot(self) -> bytes | None:
        """Canonical JSON of the committed (height, app_hash, state) —
        sorted keys, so two replicas at the same height serialize
        byte-identically (the statesync manifest digests depend on it)."""
        return json.dumps(
            {
                "height": self.height,
                "app_hash": self.app_hash.hex(),
                "state": {k: v.hex() for k, v in self.state.items()},
            },
            sort_keys=True,
        ).encode()

    def restore(
        self, data: bytes, height: int | None = None, app_hash: bytes | None = None
    ) -> None:
        if self.height != 0 or self.state:
            raise ValueError("restore only valid on a fresh app")
        obj = json.loads(data)
        # shape-check before touching fields: a non-dict here would raise
        # AttributeError, which escapes the restorer's ValueError net
        if not isinstance(obj, dict) or not isinstance(obj.get("state"), dict):
            raise ValueError("snapshot app state must be an object")
        new_height = obj["height"]
        claimed_hash = bytes.fromhex(obj["app_hash"])
        state = {k: bytes.fromhex(v) for k, v in obj["state"].items()}
        if not isinstance(new_height, int) or isinstance(new_height, bool) or new_height < 1:
            raise ValueError(f"bad snapshot height {new_height!r}")
        # the app hash is a pure function of the state map (the tree's
        # shape is canonical in the key set): recompute it rather than
        # trust the snapshot's claim — a payload whose hash and state
        # disagree must refuse here, before anything mutates
        tree = VersionedTree.from_entries(
            {k.encode("latin-1"): v for k, v in state.items()},
            new_height,
            hasher=self.tree.hasher, keep_recent=self.tree.keep_recent,
        )
        recomputed = tree.root_hash()
        if recomputed != claimed_hash:
            raise ValueError("snapshot app_hash does not match its state")
        if height is not None and new_height != height:
            raise ValueError(
                f"snapshot is at height {new_height}, expected {height}"
            )
        if app_hash is not None and claimed_hash != app_hash:
            raise ValueError("snapshot app_hash does not match the verified hash")
        self.height = new_height
        self.app_hash = claimed_hash
        self.state = state
        self.tree = tree

    def restore_delta(
        self,
        upserts: dict[bytes, bytes],
        deletes: list[bytes],
        height: int,
        app_hash: bytes,
        aux: dict | None = None,
    ) -> None:
        """Advance a restored app from its current height to `height` by
        applying a verified delta. The recomputed tree root MUST equal
        the light-verified `app_hash`; on mismatch the tree rolls back
        to its base and nothing is applied or persisted (the delta-
        restore contract, docs/state-tree.md)."""
        base = self.height
        if base < 1:
            raise ValueError("delta restore needs a restored base state")
        if not isinstance(height, int) or height <= base:
            raise ValueError(
                f"stale delta: app at height {base}, delta targets {height}"
            )
        self.tree.rollback_to(base)  # drop any stray staging first
        for k, v in sorted(upserts.items()):
            self.tree.set(k, v)
        for k in deletes:
            self.tree.delete(k)
        root = self.tree.commit(height)
        if root != app_hash:
            self.tree.rollback_to(base)
            raise ValueError(
                "delta does not reproduce the verified app hash at "
                f"height {height}"
            )
        for k, v in upserts.items():
            self.state[k.decode("latin-1")] = v
        for k in deletes:
            self.state.pop(k.decode("latin-1"), None)
        self.height = height
        self.app_hash = root


class PersistentKVStoreApp(KVStoreApp):
    """KVStore plus disk persistence and validator-set changes via
    val-txs; the backbone of the crash-restart test tier
    (test/persist/*.sh in the reference)."""

    def __init__(self, db_dir: str):
        super().__init__()
        self.db_path = os.path.join(db_dir, "kvstore_app.json")
        os.makedirs(db_dir, exist_ok=True)
        self.val_diffs: list[ABCIValidator] = []
        self.validators: dict[str, int] = {}  # pubkey hex -> power
        self._load()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.db_path):
            return
        with open(self.db_path) as f:
            obj = json.load(f)
        self.height = obj["height"]
        self.app_hash = bytes.fromhex(obj["app_hash"])
        self.state = {k: bytes.fromhex(v) for k, v in obj["state"].items()}
        self.validators = obj.get("validators", {})
        # rebuild the commitment tree at the persisted height; the
        # canonical shape guarantees the rebuilt root IS the persisted
        # app hash — a mismatch means the home predates the state tree
        # (or rotted) and continuing would diverge at the next commit
        if self.height > 0:
            self.tree = VersionedTree.from_entries(
                {k.encode("latin-1"): v for k, v in self.state.items()},
                self.height,
                hasher=self.tree.hasher, keep_recent=self.tree.keep_recent,
            )
            if self.tree.root_hash() != self.app_hash:
                raise ValueError(
                    f"{self.db_path}: persisted app_hash does not match the "
                    "state tree root (pre-state-tree home?)"
                )

    def _save(self) -> None:
        tmp = self.db_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "height": self.height,
                    "app_hash": self.app_hash.hex(),
                    "state": {k: v.hex() for k, v in self.state.items()},
                    "validators": self.validators,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.db_path)

    # -- validator updates -------------------------------------------------

    def init_chain(self, validators: list[ABCIValidator]) -> None:
        for v in validators:
            self.validators[v.pub_key_json[1]] = v.power

    def begin_block(self, block_hash: bytes, header: Header) -> None:
        self.val_diffs = []

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        if tx.startswith(VAL_TX_PREFIX):
            err = self._parse_val_tx(tx) is None
            if err:
                return ResponseCheckTx(code=CODE_UNAUTHORIZED, log="bad val tx")
        return ResponseCheckTx(code=CODE_OK, priority=tx_priority_hint(tx))

    def _parse_val_tx(self, tx: bytes):
        try:
            body = tx[len(VAL_TX_PREFIX) :].decode()
            pubkey_hex, power_s = body.split("/")
            bytes.fromhex(pubkey_hex)
            return pubkey_hex.upper(), int(power_s)
        except (ValueError, IndexError):
            return None

    def _shardable_op(self, tx: bytes):
        # validator txs mutate the registry + val_diffs (order-sensitive
        # among themselves): excluded from the kv fold, applied in tx
        # order during the merge via deliver_tx
        if tx.startswith(VAL_TX_PREFIX):
            return None
        return super()._shardable_op(tx)

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        if tx.startswith(VAL_TX_PREFIX):
            parsed = self._parse_val_tx(tx)
            if parsed is None:
                return ResponseDeliverTx(code=CODE_UNAUTHORIZED, log="bad val tx")
            pubkey_hex, power = parsed
            if power == 0:
                self.validators.pop(pubkey_hex, None)
            else:
                self.validators[pubkey_hex] = power
            from tendermint_tpu.crypto.keys import TYPE_ED25519

            self.val_diffs.append(ABCIValidator([TYPE_ED25519, pubkey_hex], power))
            return ResponseDeliverTx(code=CODE_OK)
        return super().deliver_tx(tx)

    def end_block(self, height: int) -> ResponseEndBlock:
        return ResponseEndBlock(diffs=list(self.val_diffs))

    def commit(self) -> ResponseCommit:
        res = super().commit()
        self._save()
        return res

    # -- state-sync hooks: the persistent variant also carries its
    # validator registry, and a restore lands on disk immediately so a
    # restart handshakes at the snapshot height instead of replaying a
    # chain whose pre-snapshot blocks the restored node never had ------

    def snapshot(self) -> bytes | None:
        obj = json.loads(super().snapshot())
        obj["validators"] = self.validators
        return json.dumps(obj, sort_keys=True).encode()

    def snapshot_aux(self) -> dict | None:
        """App-private sidecar state a DELTA snapshot must carry beyond
        the tree diff (the registry is not part of the kv commitment).
        The restorer cross-checks it against the header-verified
        validator set before restore_delta applies it."""
        return {"validators": dict(self.validators)}

    @staticmethod
    def _check_validators_obj(validators) -> None:
        if not isinstance(validators, dict):
            raise ValueError("snapshot validators must be an object")
        for k, power in validators.items():
            if not isinstance(power, int) or isinstance(power, bool) or power < 1:
                raise ValueError(f"bad validator power {power!r}")
            try:
                bytes.fromhex(k)
            except (TypeError, ValueError):
                raise ValueError("bad validator pubkey in snapshot")

    def restore(
        self, data: bytes, height: int | None = None, app_hash: bytes | None = None
    ) -> None:
        obj = json.loads(data)
        if not isinstance(obj, dict):
            raise ValueError("snapshot app state must be an object")
        validators = obj.get("validators", {})
        self._check_validators_obj(validators)
        super().restore(data, height=height, app_hash=app_hash)
        self.validators = validators
        self._save()

    def restore_delta(
        self,
        upserts: dict[bytes, bytes],
        deletes: list[bytes],
        height: int,
        app_hash: bytes,
        aux: dict | None = None,
    ) -> None:
        validators = None
        if aux is not None:
            if not isinstance(aux, dict):
                raise ValueError("bad delta aux")
            validators = aux.get("validators")
            if validators is not None:
                self._check_validators_obj(validators)
        super().restore_delta(upserts, deletes, height, app_hash, aux=aux)
        if validators is not None:
            self.validators = validators
        self._save()
