"""Counter app — txs must arrive in strict serial order when serial mode is
on; used by the mempool-vs-commit concurrency tests
(consensus/mempool_test.go in the reference)."""

from __future__ import annotations

import struct

from tendermint_tpu.abci.types import (
    Application,
    CODE_BAD_NONCE,
    CODE_OK,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseInfo,
    ResponseQuery,
)


def _tx_value(tx: bytes) -> int:
    """Big-endian integer, up to 8 bytes."""
    if len(tx) > 8:
        raise ValueError("tx too long")
    return int.from_bytes(tx, "big")


class CounterApp(Application):
    def __init__(self, serial: bool = False):
        self.serial = serial
        self.tx_count = 0
        self.check_count = 0

    def info(self) -> ResponseInfo:
        return ResponseInfo(data=f"{{\"hashes\":{self.tx_count},\"txs\":{self.tx_count}}}")

    def set_option(self, key: str, value: str) -> str:
        if key == "serial" and value == "on":
            self.serial = True
            return "ok"
        return ""

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        if self.serial:
            try:
                value = _tx_value(tx)
            except ValueError:
                return ResponseCheckTx(code=CODE_BAD_NONCE, log="tx too long")
            if value < self.check_count:
                return ResponseCheckTx(
                    code=CODE_BAD_NONCE,
                    log=f"invalid nonce: got {value}, expected >= {self.check_count}",
                )
            self.check_count += 1
        return ResponseCheckTx(code=CODE_OK)

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        if self.serial:
            try:
                value = _tx_value(tx)
            except ValueError:
                return ResponseDeliverTx(code=CODE_BAD_NONCE, log="tx too long")
            if value != self.tx_count:
                return ResponseDeliverTx(
                    code=CODE_BAD_NONCE,
                    log=f"invalid nonce: got {value}, expected {self.tx_count}",
                )
        self.tx_count += 1
        return ResponseDeliverTx(code=CODE_OK)

    def commit(self) -> ResponseCommit:
        self.check_count = self.tx_count
        if self.tx_count == 0:
            return ResponseCommit(code=CODE_OK, data=b"")
        return ResponseCommit(code=CODE_OK, data=struct.pack(">Q", self.tx_count))

    def query(self, data: bytes, path: str = "", height: int = 0, prove: bool = False) -> ResponseQuery:
        if prove:
            from tendermint_tpu.abci.types import proofs_unsupported_response

            return proofs_unsupported_response(self, data)
        if path == "hash" or data == b"hash":
            return ResponseQuery(code=CODE_OK, value=str(self.tx_count).encode())
        if path == "tx" or data == b"tx":
            return ResponseQuery(code=CODE_OK, value=str(self.tx_count).encode())
        return ResponseQuery(code=CODE_OK, log=f"unexpected query path {path}")
