"""Example ABCI applications (reference: the abci package's dummy /
persistent_dummy / counter / nilapp, selected by name at
proxy/client.go:64-76)."""

from tendermint_tpu.abci.apps.kvstore import KVStoreApp, PersistentKVStoreApp
from tendermint_tpu.abci.apps.counter import CounterApp
from tendermint_tpu.abci.apps.nilapp import NilApp
from tendermint_tpu.abci.apps.signedkv import SignedKVStoreApp

__all__ = [
    "KVStoreApp", "PersistentKVStoreApp", "CounterApp", "NilApp",
    "SignedKVStoreApp",
]
