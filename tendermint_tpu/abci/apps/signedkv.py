"""Signed KVStore: the sig-carrying demo app behind mempool batch
signature pre-verification (BASELINE config 5).

Tx format: `pubkey(32) || sig(64) || payload` where payload is the
kvstore's "key=value" and sig is Ed25519 over the payload. The reference
has no such app — its mempool sends every tx straight to the app, which
would verify one signature at a time on CPU (mempool/mempool.go:166-205).
Here the app publishes `tx_sig_parser`, the node wires the mempool's
SigBatcher to it (node/node.py), and a CheckTx burst's signatures verify
in ONE gateway batch (the TPU kernel when wide) before any app dispatch.

DeliverTx ALWAYS verifies: blocks arrive from peers whose mempool this
node never saw, so consensus-path txs cannot trust pre-verification.
CheckTx verifies only when `verify_in_app` (i.e. when no mempool
pre-verification is wired) — otherwise the signature work would be done
twice and the batch win measured away.
"""

from __future__ import annotations

from tendermint_tpu.abci.types import (
    CODE_UNAUTHORIZED,
    ResponseCheckTx,
    ResponseDeliverTx,
)
from tendermint_tpu.abci.apps.kvstore import KVStoreApp, tx_priority_hint

SIG_TX_OVERHEAD = 96  # pubkey(32) + sig(64)


def parse_sig_tx(tx: bytes):
    """(pubkey, payload, signature) — the gateway's Item order — or None
    for a tx too short to carry the envelope (rejected in CheckTx)."""
    if len(tx) <= SIG_TX_OVERHEAD:
        return None
    return (tx[:32], tx[SIG_TX_OVERHEAD:], tx[32:SIG_TX_OVERHEAD])


def make_sig_tx(seed: bytes, payload: bytes) -> bytes:
    """Signed tx from a 32-byte Ed25519 seed (test/bench helper)."""
    from tendermint_tpu.crypto import ed25519 as ed

    return ed.public_key(seed) + ed.sign(seed, payload) + payload


class SignedKVStoreApp(KVStoreApp):
    tx_sig_parser = staticmethod(parse_sig_tx)

    def __init__(self, verify_in_app: bool = True):
        super().__init__()
        self.verify_in_app = verify_in_app
        self.check_tx_calls = 0  # observable by tests/benches
        # round 14: the whole-block DeliverTx batch verifies through
        # this gateway Verifier (None = the process default)
        self.deliver_verifier = None

    def _verify(self, tx: bytes) -> bool:
        item = parse_sig_tx(tx)
        if item is None:
            return False
        from tendermint_tpu.crypto import ed25519 as ed

        pub, payload, sig = item
        return ed.verify(pub, payload, sig)

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        self.check_tx_calls += 1
        if parse_sig_tx(tx) is None:
            return ResponseCheckTx(code=CODE_UNAUTHORIZED, log="malformed signed tx")
        if self.verify_in_app and not self._verify(tx):
            return ResponseCheckTx(code=CODE_UNAUTHORIZED, log="invalid signature")
        # lane hint rides the inner payload: a signed "pri:..." kv tx
        # lands in the priority lane just like its unsigned counterpart
        return ResponseCheckTx(priority=tx_priority_hint(tx[SIG_TX_OVERHEAD:]))

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        if not self._verify(tx):
            return ResponseDeliverTx(code=CODE_UNAUTHORIZED, log="invalid signature")
        return super().deliver_tx(tx[SIG_TX_OVERHEAD:])

    def deliver_txs(self, txs: list[bytes]) -> list[ResponseDeliverTx]:
        """Whole-block DeliverTx (round 14): the block's signatures
        verify in ONE gateway batch (the numpy/device kernel — off the
        per-tx pure-Python path, and GIL-releasing so a pipelined apply
        genuinely overlaps the next height's consensus work), then the
        surviving payloads ride the kvstore fold (sharded when armed).
        Verdicts and responses are identical to the per-tx loop."""
        if len(txs) < 2:
            return [self.deliver_tx(tx) for tx in txs]
        from tendermint_tpu.ops import gateway

        verifier = self.deliver_verifier or gateway.default_verifier()
        items = [parse_sig_tx(tx) for tx in txs]
        idx = [i for i, it in enumerate(items) if it is not None]
        verdicts = verifier.verify_batch([items[i] for i in idx]) if idx else []
        ok = {i: bool(v) for i, v in zip(idx, verdicts)}
        responses: list[ResponseDeliverTx | None] = [None] * len(txs)
        payloads = []
        for i, tx in enumerate(txs):
            if ok.get(i):
                payloads.append(tx[SIG_TX_OVERHEAD:])
            else:
                responses[i] = ResponseDeliverTx(
                    code=CODE_UNAUTHORIZED, log="invalid signature"
                )
        # the payloads are already verified + stripped: the fold's per-tx
        # fallback must apply them as PLAIN kv bytes, not re-enter this
        # class's signed deliver_tx (which would reject them all)
        payload_res = iter(super().deliver_txs(
            payloads, deliver_one=lambda t: KVStoreApp.deliver_tx(self, t)
        ))
        for i in range(len(txs)):
            if responses[i] is None:
                responses[i] = next(payload_res)
        return responses
