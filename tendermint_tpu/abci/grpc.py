"""ABCI over gRPC (reference: the types.proto ABCIApplication service and
the gRPC client/server wired by proxy/client.go:40-58 and
abci/server/grpc_server.go).

Transport redesign, same surface: the reference serializes with protobuf
messages; this framework's wire is its canonical JSON (the documented
ABCI framing redesign — see abci/client.py), carried here in gRPC
unary-unary methods registered under the same service/method names the
reference exposes (/tendermint.abci.ABCIApplication/CheckTx, ...). gRPC
provides the HTTP/2 transport, deadlines, and multiplexing; request and
response bodies are the exact dicts the socket transport uses, so both
remote transports share one dispatch (client.dispatch_request) and one
response decode table.

The ordering contract ABCI requires (responses complete in request
order per connection — the mempool recheck path depends on it) is
preserved by serializing async calls through a single worker thread, the
same trade the reference's gRPC client makes (grpc_client.go notes it is
the slower, simpler option next to the pipelined socket client).
"""

from __future__ import annotations

import queue
import threading
from concurrent import futures as _futures
from typing import Callable

from tendermint_tpu.abci.client import (
    _RES_TYPES,
    ABCIClient,
    ReqRes,
    dispatch_request,
)
from tendermint_tpu.abci.types import (
    ABCIValidator,
    Application,
    Header,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ResponseQuery,
)
from tendermint_tpu.libs.grpcutil import bind_insecure, json_deserializer as _de, json_serializer as _ser
from tendermint_tpu.libs.service import BaseService

SERVICE = "tendermint.abci.ABCIApplication"

# request-type tag <-> gRPC method name (the reference service's methods)
_METHOD_FOR = {
    "echo": "Echo",
    "flush": "Flush",
    "info": "Info",
    "set_option": "SetOption",
    "deliver_tx": "DeliverTx",
    "check_tx": "CheckTx",
    "query": "Query",
    "commit": "Commit",
    "init_chain": "InitChain",
    "begin_block": "BeginBlock",
    "end_block": "EndBlock",
}


class GRPCServer(BaseService):
    """Serves one Application over gRPC; same dispatch + app-mutex model
    as the socket ABCIServer."""

    def __init__(self, app: Application, addr: str):
        super().__init__("abci.GRPCServer")
        import grpc

        self.app = app
        self._app_mtx = threading.RLock()
        self._server = grpc.server(_futures.ThreadPoolExecutor(max_workers=4))

        def handler_for(req_type: str):
            def handle(request: dict, context) -> dict:
                request = dict(request)
                request["type"] = req_type
                with self._app_mtx:
                    return dispatch_request(self.app, request)

            return grpc.unary_unary_rpc_method_handler(
                handle, request_deserializer=_de, response_serializer=_ser
            )

        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    SERVICE,
                    {m: handler_for(t) for t, m in _METHOD_FOR.items()},
                ),
            )
        )
        self.addr = bind_insecure(self._server, addr)

    def on_start(self) -> None:
        self._server.start()

    def on_stop(self) -> None:
        self._server.stop(grace=0.5)


class GRPCClient(ABCIClient):
    """Remote app over gRPC; drop-in for SocketClient (the `abci: grpc`
    config path, proxy/client.go:40-58)."""

    def __init__(self, addr: str, timeout: float = 30.0):
        super().__init__("abci.GRPCClient")
        self._addr = addr
        self._timeout = timeout
        self._channel = None
        self._stubs: dict[str, Callable] = {}
        self._res_cb: Callable | None = None
        self._err: Exception | None = None
        # single worker preserves the per-connection ordering contract
        self._q: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None

    def on_start(self) -> None:
        import grpc

        self._channel = grpc.insecure_channel(self._addr)
        grpc.channel_ready_future(self._channel).result(timeout=10)
        for t, m in _METHOD_FOR.items():
            self._stubs[t] = self._channel.unary_unary(
                f"/{SERVICE}/{m}",
                request_serializer=_ser,
                response_deserializer=_de,
            )
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True, name="abci-grpc-worker"
        )
        self._worker.start()

    def on_stop(self) -> None:
        self._q.put(None)
        if self._channel is not None:
            self._channel.close()

    def error(self) -> Exception | None:
        return self._err

    def set_response_callback(self, cb: Callable) -> None:
        self._res_cb = cb

    # -- plumbing ----------------------------------------------------------

    def _call(self, req: dict):
        import grpc

        try:
            obj = self._stubs[req["type"]](req, timeout=self._timeout)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.DEADLINE_EXCEEDED:
                raise TimeoutError(
                    f"abci {req['type']} timed out after {self._timeout}s"
                ) from e
            raise
        cls = _RES_TYPES.get(req["type"])
        res = cls.from_json(obj["value"]) if cls else obj.get("value")
        if self._res_cb and req["type"] in ("check_tx", "deliver_tx"):
            self._res_cb(req["type"], bytes.fromhex(req["tx"]), res)
        return res

    def _worker_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            rr, req = item
            try:
                rr.complete(self._call(req))
            except Exception as e:  # noqa: BLE001 — one failed RPC kills
                # the client loudly, the SocketClient contract: a silent
                # half-broken client would wedge the mempool recheck cursor
                self._err = e
                rr.complete(None)
                while True:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        return
                    if nxt is None:
                        return
                    nxt[0].complete(None)

    def _call_sync(self, req: dict):
        # a dead client (worker killed by an async failure) fails every
        # subsequent call; a healthy one propagates only ITS OWN errors
        if self._err:
            raise self._err
        return self._call(req)

    def _call_async(self, req: dict) -> ReqRes:
        rr = ReqRes(req["type"])
        self._q.put((rr, req))
        return rr

    # -- calls (same wire dicts as SocketClient) ---------------------------

    def echo_sync(self, msg: str) -> str:
        return self._call_sync({"type": "echo", "msg": msg})

    def info_sync(self) -> ResponseInfo:
        return self._call_sync({"type": "info"})

    def set_option_sync(self, key: str, value: str) -> str:
        return self._call_sync({"type": "set_option", "key": key, "value": value})

    def query_sync(
        self, data: bytes, path: str = "", height: int = 0, prove: bool = False
    ) -> ResponseQuery:
        return self._call_sync(
            {"type": "query", "data": data.hex(), "path": path, "height": height, "prove": prove}
        )

    def flush_sync(self) -> None:
        # drain the async worker: flush's contract is "everything queued
        # before this point has completed" — a timeout must raise, not
        # silently succeed (the mempool recheck cursor depends on it)
        if self._err:
            raise self._err
        rr = ReqRes("flush")
        self._q.put((rr, {"type": "flush"}))
        rr.wait(self._timeout)
        if not rr.done():
            raise TimeoutError(f"abci flush timed out after {self._timeout}s")
        if self._err:
            raise self._err

    def check_tx_sync(self, tx: bytes) -> ResponseCheckTx:
        return self._call_sync({"type": "check_tx", "tx": tx.hex()})

    def deliver_tx_sync(self, tx: bytes) -> ResponseDeliverTx:
        return self._call_sync({"type": "deliver_tx", "tx": tx.hex()})

    def init_chain_sync(self, validators: list[ABCIValidator]) -> None:
        self._call_sync(
            {"type": "init_chain", "validators": [v.to_json() for v in validators]}
        )

    def begin_block_sync(self, block_hash: bytes, header: Header) -> None:
        self._call_sync(
            {"type": "begin_block", "hash": block_hash.hex(), "header": header.to_json()}
        )

    def end_block_sync(self, height: int) -> ResponseEndBlock:
        return self._call_sync({"type": "end_block", "height": height})

    def commit_sync(self) -> ResponseCommit:
        return self._call_sync({"type": "commit"})

    def check_tx_async(self, tx: bytes) -> ReqRes:
        return self._call_async({"type": "check_tx", "tx": tx.hex()})

    def deliver_tx_async(self, tx: bytes) -> ReqRes:
        return self._call_async({"type": "deliver_tx", "tx": tx.hex()})

    def flush_async(self) -> ReqRes:
        rr = ReqRes("flush")
        self._q.put((rr, {"type": "flush"}))
        return rr
