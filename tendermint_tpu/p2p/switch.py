"""Switch: reactor registry + peer lifecycle (reference: p2p/switch.go).

Reactors register channel descriptors; the switch owns dialing, accepting,
handshakes, peer filters, broadcast, and persistent-peer reconnection
(switch.go:15-18, 409-438: 30 attempts x 3s). `make_connected_switches`
wires N switches over in-process pipes for deterministic multi-node tests
(switch.go:502-547).
"""

from __future__ import annotations

import socket
import threading
import time

from tendermint_tpu.crypto.keys import PrivKeyEd25519, gen_priv_key_ed25519
from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn import ChannelDescriptor
from tendermint_tpu.p2p.netaddress import NetAddress
from tendermint_tpu.p2p.node_info import NodeInfo
from tendermint_tpu.p2p.peer import Peer, PeerConfig
from tendermint_tpu.p2p.peer_set import PeerSet
from tendermint_tpu.p2p.stream import SocketStream, pipe_pair

RECONNECT_ATTEMPTS = 30
RECONNECT_INTERVAL = 3.0


def _reconnect_policy() -> tuple[int, float]:
    """(attempts, interval_s), env-tunable so chaos harnesses can run
    tight partition-heal cycles without monkeypatching module globals
    (read per reconnect routine — the knobs apply to live switches)."""
    from tendermint_tpu.libs.envknob import env_number

    return (
        int(env_number("TENDERMINT_P2P_RECONNECT_ATTEMPTS", RECONNECT_ATTEMPTS, cast=int)),
        float(env_number("TENDERMINT_P2P_RECONNECT_INTERVAL_S", RECONNECT_INTERVAL)),
    )


class Reactor:
    """Interface (switch.go:20-28). Subclasses are BaseServices too."""

    def set_switch(self, sw: "Switch") -> None:
        self.switch = sw

    def get_channels(self) -> list[ChannelDescriptor]:
        raise NotImplementedError

    def add_peer(self, peer: Peer) -> None:
        pass

    def remove_peer(self, peer: Peer, reason) -> None:
        pass

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        pass


class Switch(BaseService):
    def __init__(
        self,
        config=None,
        peer_config: PeerConfig | None = None,
        node_priv_key: PrivKeyEd25519 | None = None,
    ):
        super().__init__(name="p2p.switch")
        self.config = config
        self.peer_config = peer_config or PeerConfig()
        self.reactors: dict[str, Reactor] = {}
        self.ch_descs: list[ChannelDescriptor] = []
        self.reactors_by_ch: dict[int, Reactor] = {}
        self.peers = PeerSet()
        self.dialing: set[str] = set()
        self.node_priv_key = node_priv_key or gen_priv_key_ed25519()
        self.node_info: NodeInfo | None = None
        # registry scoping the p2p_peer_* series (round 15): the node
        # sets this to its own registry (node/telemetry.build_registry)
        # so two in-process nodes keep separate per-peer counters; None
        # falls back to the process-wide default
        self.metrics_registry = None
        # black-box flight recorder (round 17, node/flightrec.py): the
        # node wires it so peer connect/drop land in the event ring;
        # None (bare switches) records nothing
        self.flightrec = None
        self.listeners: list = []
        self.filter_conn_by_addr = None  # callables raising on rejection
        self.filter_conn_by_pubkey = None
        self._reconnecting: set[str] = set()
        from tendermint_tpu.p2p.ip_range_counter import IPRangeCounter

        self.ip_ranges = IPRangeCounter()
        # defense-side adversary accounting (round 18): how much hostile
        # pressure this switch shed — eclipse dials refused at the
        # IP-range / max-peers gates, admission handshakes rejected
        # (timeouts, incompatible versions/formats, bad bytes), and
        # framing-contract violations that dropped a live peer
        # (oversized frames, recv-ceiling breaches, unknown channels).
        # Exported as p2p_adversary_* on both metric surfaces
        # (node/telemetry.py).
        self.adversary = {
            "ip_range_refused": 0,
            "max_peers_refused": 0,
            "handshake_rejects": 0,
            "frame_violations": 0,
            # commit-schedule disagreements specifically: a nonzero value
            # during a rolling upgrade means some peer runs a different
            # genesis upgrade schedule — the one misconfiguration that
            # would otherwise fork the net AT the flip height. Counted at
            # the add_peer refusal site so both inbound and outbound
            # handshakes land here (docs/upgrade.md).
            "schedule_refused": 0,
        }
        self._mtx = threading.Lock()

    def _note_adversary(self, kind: str) -> None:
        with self._mtx:
            self.adversary[kind] += 1

    def adversary_stats(self) -> dict:
        with self._mtx:
            return dict(self.adversary)

    # -- registry (before start) ------------------------------------------

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        for desc in reactor.get_channels():
            if desc.id in self.reactors_by_ch:
                raise ValueError(f"channel {desc.id:#x} already registered")
            self.ch_descs.append(desc)
            self.reactors_by_ch[desc.id] = reactor
        self.reactors[name] = reactor
        reactor.set_switch(self)
        return reactor

    def reactor(self, name: str) -> Reactor | None:
        return self.reactors.get(name)

    def set_node_info(self, info: NodeInfo) -> None:
        self.node_info = info
        info.channels = bytes(sorted(d.id for d in self.ch_descs))

    def set_node_key(self, priv: PrivKeyEd25519) -> None:
        self.node_priv_key = priv

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        if self.node_info is None:
            from tendermint_tpu.p2p.node_info import default_version
            from tendermint_tpu.version import VERSION

            self.set_node_info(
                NodeInfo(
                    pub_key=self.node_priv_key.pub_key(),
                    moniker="anonymous",
                    network="",
                    version=default_version(VERSION),
                )
            )
        for reactor in self.reactors.values():
            reactor.start()
        for listener in self.listeners:
            t = threading.Thread(
                target=self._listener_routine, args=(listener,), daemon=True,
                name="switch.listener",
            )
            t.start()

    def on_stop(self) -> None:
        for listener in self.listeners:
            try:
                listener.stop()
            except Exception:
                pass
        for peer in self.peers.list():
            self._stop_and_remove(peer, "switch stopping")
        for reactor in self.reactors.values():
            reactor.stop()

    # -- listeners ---------------------------------------------------------

    def add_listener(self, listener) -> None:
        self.listeners.append(listener)

    def start_listener(self, listener) -> None:
        """Add AND serve a listener on a running switch — the
        listener-churn arm of the network chaos tier (on_start owns the
        boot-time set; this is for listeners (re)created later)."""
        self.listeners.append(listener)
        threading.Thread(
            target=self._listener_routine, args=(listener,), daemon=True,
            name="switch.listener",
        ).start()

    def _listener_routine(self, listener) -> None:
        while self.is_running():
            sock = listener.accept()
            if sock is None:
                return  # listener closed
            # handshakes run off-thread: one stalled inbound connection
            # must not block the accept loop
            threading.Thread(
                target=self._accept_peer, args=(sock,), daemon=True,
                name="switch.accept_peer",
            ).start()

    def _accept_peer(self, sock: socket.socket) -> None:
        # inbound cap (switch.go:462-467): beyond max_num_peers an
        # attacker could exhaust fds/threads by dialing in a loop
        max_peers = getattr(self.config, "max_num_peers", 0) if self.config else 0
        if max_peers and self.peers.size() >= max_peers:
            self.logger.info(
                "rejecting inbound peer: at max_num_peers=%d", max_peers
            )
            self._note_adversary("max_peers_refused")
            try:
                sock.close()
            except OSError:
                pass
            return
        # per-IP-range cap (ip_range_counter): counted pre-handshake so a
        # single subnet can't flood the handshake threads either
        ip = ""
        try:
            ip = sock.getpeername()[0]
        except OSError:
            pass
        if ip and not self.ip_ranges.try_add(ip):
            self.logger.info("rejecting inbound peer %s: IP range at limit", ip)
            self._note_adversary("ip_range_refused")
            try:
                sock.close()
            except OSError:
                pass
            return
        stream = SocketStream(sock)
        stream.counted_ip = ip
        try:
            self.add_peer_from_stream(stream, outbound=False)
        except Exception as exc:  # noqa: BLE001 — one bad peer can't kill accept
            self.logger.info("inbound peer rejected: %s", exc)
            self._note_adversary("handshake_rejects")
            self._uncount_stream(stream)
            try:
                sock.close()
            except OSError:
                pass

    # -- peer admission -----------------------------------------------------

    def add_peer_from_stream(
        self,
        stream,
        outbound: bool,
        persistent: bool = False,
        dialed_addr: NetAddress | None = None,
    ) -> Peer:
        # bound the secret-connection + node-info handshakes: a stalled
        # remote must not hold this thread (or the dialing slot) forever
        sock = getattr(stream, "sock", None)
        if sock is not None:
            sock.settimeout(self.peer_config.handshake_timeout)
        try:
            peer = Peer(
                stream,
                outbound=outbound,
                channel_descs=self.ch_descs,
                on_receive=self._on_peer_receive,
                on_error=self._on_peer_error,
                config=self.peer_config,
                node_priv_key=self.node_priv_key,
                persistent=persistent,
            )
            peer.metrics_registry = self.metrics_registry
            peer.dialed_addr = dialed_addr
            peer = self.add_peer(peer)
        finally:
            if sock is not None:
                try:
                    sock.settimeout(None)
                except OSError:
                    pass
        return peer

    def add_peer(self, peer: Peer) -> Peer:
        """Handshake + filter + register + start (switch.go:216-260)."""
        if self.filter_conn_by_pubkey and self.peer_config.auth_enc:
            self.filter_conn_by_pubkey(peer.pub_key())
        info = peer.handshake(self.node_info)
        if info.pub_key.raw == self.node_info.pub_key.raw:
            peer.stream.close()
            raise ConnectionError("refusing self-connection")
        reason = self.node_info.compatible_with(info)
        if reason is not None:
            if reason.startswith("commit schedule mismatch"):
                self._note_adversary("schedule_refused")
            peer.stream.close()
            raise ConnectionError(f"incompatible peer: {reason}")
        # inbound connections respect max_num_peers at the registration
        # point (atomically, inside PeerSet.add) — the accept-loop check is
        # only a fast path, and many concurrent handshakes may be in
        # flight past it (switch.go:462-467)
        cap = 0
        if not peer.outbound and self.config is not None:
            cap = getattr(self.config, "max_num_peers", 0)
        if not self.peers.add(peer, cap=cap):
            peer.stream.close()
            raise ConnectionError(
                f"duplicate peer or at max_num_peers: {peer.id()[:12]}"
            )
        try:
            peer.start()
            for reactor in self.reactors.values():
                reactor.add_peer(peer)
        except Exception:
            self.peers.remove(peer)
            peer.stop()
            raise
        self.logger.info("added peer %s", peer)
        if self.flightrec is not None:
            self.flightrec.record("peer_add", peer=peer.id(),
                                  outbound=peer.outbound)
        return peer

    def _on_peer_receive(self, peer: Peer, ch_id: int, msg_bytes: bytes) -> None:
        reactor = self.reactors_by_ch.get(ch_id)
        if reactor is not None:
            reactor.receive(ch_id, peer, msg_bytes)

    def _on_peer_error(self, peer: Peer, exc: Exception) -> None:
        # framing-contract violations are adversary-shaped: an oversized
        # SecretConnection frame claim / AEAD tamper, a reassembly past
        # a channel's recv ceiling, an unknown channel or packet type —
        # as opposed to plain IO errors (hangups, resets), which stay
        # uncounted. Both classes are TYPED (conn.FrameViolation,
        # SecretConnectionError), never sniffed from message text.
        from tendermint_tpu.p2p.conn import FrameViolation
        from tendermint_tpu.p2p.secret_connection import SecretConnectionError

        if isinstance(exc, (SecretConnectionError, FrameViolation)):
            self._note_adversary("frame_violations")
        self.stop_peer_for_error(peer, exc)

    # -- dialing ------------------------------------------------------------

    def dial_peer_with_address(
        self, addr: NetAddress, persistent: bool = False
    ) -> Peer:
        key = str(addr)
        with self._mtx:
            if key in self.dialing:
                raise ConnectionError(f"already dialing {key}")
            self.dialing.add(key)
        try:
            if self.filter_conn_by_addr:
                self.filter_conn_by_addr(addr)
            sock = socket.create_connection(
                addr.dial_string(), timeout=self.peer_config.dial_timeout
            )
            return self.add_peer_from_stream(
                SocketStream(sock),
                outbound=True,
                persistent=persistent,
                dialed_addr=addr,
            )
        finally:
            with self._mtx:
                self.dialing.discard(key)

    def dial_seeds(self, seeds: list[str], addr_book=None) -> None:
        """Dial in random order, in parallel (switch.go:297-338)."""
        import random

        addrs = [NetAddress.from_string(s) for s in seeds]
        if addr_book is not None:
            for a in addrs:
                if not a.local():
                    addr_book.add_address(a, a)
        random.shuffle(addrs)
        for a in addrs:
            threading.Thread(
                target=self._dial_seed, args=(a,), daemon=True, name="switch.dial"
            ).start()

    def _dial_seed(self, addr: NetAddress) -> None:
        try:
            self.dial_peer_with_address(addr, persistent=True)
        except Exception as exc:  # noqa: BLE001
            # seeds are PERSISTENT peers: a transiently failed boot dial
            # (slow handshake under load, listener not accepting yet)
            # must retry like any dropped persistent peer — fire-once
            # left a permanently degraded mesh (round-12 chaos-tier
            # finding: a 4-node net missing one link can wedge consensus
            # in a 2-2 height split)
            self.logger.info(
                "error dialing seed %s: %s; entering reconnect loop", addr, exc
            )
            self._reconnect_routine(str(addr))

    # -- removal / errors ---------------------------------------------------

    def _uncount_stream(self, stream) -> None:
        """Release an inbound stream's IP-range count exactly once: the
        error path in _accept_peer and peer removal can race (a started
        peer may die while add_peer is still unwinding), and a double
        decrement would steal counts from other live peers.

        The marker lives on the RAW socket stream, which peer admission
        WRAPS (fuzz wrapper, secret connection — each keeps its inner
        stream as `.stream`): walk the chain to find it. Before round 12
        this looked only at the outermost object, so every successfully
        admitted auth_enc inbound peer leaked its count on removal — 16
        churn cycles from one /24 (i.e. any loopback testnet) and the
        node refused ALL inbound forever (the real-TCP chaos tier's
        first catch)."""
        with self._mtx:
            ip = ""
            obj, hops = stream, 0
            while obj is not None and hops < 4:
                ip = getattr(obj, "counted_ip", "")
                if ip:
                    obj.counted_ip = ""
                    break
                obj = getattr(obj, "stream", None)
                hops += 1
        if ip:
            self.ip_ranges.remove(ip)

    def _stop_and_remove(self, peer: Peer, reason) -> None:
        if self.flightrec is not None:
            self.flightrec.record(
                "peer_drop", peer=peer.id(),
                reason="graceful" if reason is None else str(reason)[:200],
            )
        self._uncount_stream(peer.stream)
        self.peers.remove(peer)
        peer.stop()
        for reactor in self.reactors.values():
            reactor.remove_peer(peer, reason)

    def stop_peer_for_error(self, peer: Peer, reason) -> None:
        if not self.peers.has(peer.id()):
            return
        # warning, not info: a peer dropped for cause is an operator-
        # relevant event (and surfaces in pytest's captured-log section
        # when a net test fails)
        self.logger.warning("stopping peer %s for error: %s", peer, reason)
        self._stop_and_remove(peer, reason)
        if peer.persistent and self.is_running():
            # reconnect to the address WE dialed, not anything the peer
            # claimed about itself
            addr = getattr(peer, "dialed_addr", None)
            if addr is not None:
                threading.Thread(
                    target=self._reconnect_routine,
                    args=(str(addr),),
                    daemon=True,
                    name="switch.reconnect",
                ).start()

    def stop_peer_gracefully(self, peer: Peer) -> None:
        self._stop_and_remove(peer, None)

    def _reconnect_routine(self, addr_str: str) -> None:
        with self._mtx:
            if addr_str in self._reconnecting:
                return
            self._reconnecting.add(addr_str)
        try:
            addr = NetAddress.from_string(addr_str)
            attempts, interval = _reconnect_policy()
            for i in range(attempts):
                if not self.is_running():
                    return
                time.sleep(interval)
                try:
                    self.dial_peer_with_address(addr, persistent=True)
                    return
                except Exception as exc:  # noqa: BLE001
                    self.logger.info(
                        "reconnect to %s attempt %d failed: %s", addr_str, i + 1, exc
                    )
        finally:
            with self._mtx:
                self._reconnecting.discard(addr_str)

    # -- messaging ----------------------------------------------------------

    def broadcast(self, ch_id: int, msg_bytes: bytes) -> None:
        """Fire-and-forget TrySend to every peer (switch.go:375-392).
        try_send is non-blocking (queue append or drop), so this runs
        inline — no thread per peer per message."""
        for peer in self.peers.list():
            peer.try_send(ch_id, msg_bytes)

    def num_peers(self) -> tuple[int, int, int]:
        outbound = sum(1 for p in self.peers.list() if p.outbound)
        total = self.peers.size()
        with self._mtx:
            dialing = len(self.dialing)
        return outbound, total - outbound, dialing


# -- test wiring (switch.go:502-547) -----------------------------------------


def make_connected_switches(
    n: int, init_switch, connect=None, switch_factory=None
) -> list[Switch]:
    """n started switches wired pairwise over in-process pipes.
    switch_factory overrides plain Switch() construction (e.g. to set a
    PeerConfig with transport fuzzing, switch.go:502-547's variants)."""
    if switch_factory is None:
        switch_factory = Switch
    switches = [init_switch(i, switch_factory()) for i in range(n)]
    for sw in switches:
        sw.start()
    if connect is None:
        connect = connect2_switches
    for i in range(n):
        for j in range(i + 1, n):
            connect(switches, i, j)
    return switches


def connect2_switches(switches: list[Switch], i: int, j: int) -> None:
    """Full peering of switches[i] <-> switches[j] over a pipe pair."""
    a, b = pipe_pair()
    errs: list = []

    def add(sw, stream, outbound):
        try:
            sw.add_peer_from_stream(stream, outbound=outbound)
        except Exception as exc:  # noqa: BLE001
            errs.append(exc)

    ti = threading.Thread(target=add, args=(switches[i], a, True), daemon=True)
    tj = threading.Thread(target=add, args=(switches[j], b, False), daemon=True)
    ti.start()
    tj.start()
    ti.join(20)
    tj.join(20)
    if errs:
        raise errs[0]
