"""Peer: one remote node (reference: p2p/peer.go).

Connection layering: raw stream -> [fuzz wrapper] -> [secret connection]
-> NodeInfo handshake -> MConnection. AuthEnc defaults on
(p2p/peer.go:54-77).
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

from tendermint_tpu.libs.service import BaseService
from tendermint_tpu.p2p.conn import ChannelDescriptor, MConnConfig, MConnection
from tendermint_tpu.p2p.node_info import MAX_NODE_INFO_SIZE, NodeInfo

_HS_LEN = struct.Struct(">I")


@dataclass
class PeerConfig:
    """p2p/peer.go:54-77."""

    auth_enc: bool = True
    handshake_timeout: float = 20.0
    dial_timeout: float = 3.0
    fuzz: bool = False
    fuzz_config: dict = field(default_factory=dict)
    mconfig: MConnConfig = field(default_factory=MConnConfig)


def _raw_sock(stream):
    """The raw socket under a wrapper chain (fuzz wrapper, secret
    connection — each keeps its inner stream as `.stream`), or None for
    socketless streams (in-process test fabrics)."""
    obj, hops = stream, 0
    while obj is not None and hops < 4:
        sock = getattr(obj, "sock", None)
        if sock is not None:
            return sock
        obj = getattr(obj, "stream", None)
        hops += 1
    return None


def exchange_node_info(stream, our_info: NodeInfo, timeout: float) -> NodeInfo:
    """Concurrent length-prefixed NodeInfo swap (p2p/peer.go:159-200).
    Write first, then read — both sides do the same, so no deadlock
    (payloads are far below socket buffer sizes).

    The deadline is ABSOLUTE (round 18): the switch's admission timeout
    used to bound each socket READ at `timeout`, so a byte-dribbling
    peer — one byte every timeout-minus-epsilon — could hold the
    admission thread for MAX_NODE_INFO_SIZE reads (a slow-loris against
    the handshake path). Every read now re-arms the socket with the
    REMAINING budget, exactly like the SecretConnection handshake; the
    prior socket timeout is restored on exit so the caller's own
    bookkeeping (Switch.add_peer_from_stream) is undisturbed."""
    import socket as _socket

    deadline = (
        time.monotonic() + timeout if timeout and timeout > 0 else None
    )
    sock = _raw_sock(stream)
    prior = None
    if sock is not None:
        try:
            prior = sock.gettimeout()
        except OSError:
            sock = None

    def arm() -> None:
        if deadline is None or sock is None:
            return
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise ConnectionError("node-info handshake timed out")
        try:
            sock.settimeout(remaining)
        except OSError:
            pass

    def read_exact(n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            arm()
            try:
                chunk = stream.read(n - len(buf))
            except _socket.timeout as exc:
                raise ConnectionError(
                    "node-info handshake timed out"
                ) from exc
            if not chunk:
                # SocketStream swallows OSError (incl. timeouts) into
                # b"" — distinguish deadline expiry from a peer hangup
                if deadline is not None and time.monotonic() >= deadline:
                    raise ConnectionError("node-info handshake timed out")
                raise ConnectionError(
                    "stream closed during node-info handshake"
                )
            buf += chunk
        return bytes(buf)

    try:
        raw = our_info.encode()
        arm()
        stream.write(_HS_LEN.pack(len(raw)) + raw)
        (ln,) = _HS_LEN.unpack(read_exact(_HS_LEN.size))
        if ln > MAX_NODE_INFO_SIZE:
            raise ValueError(f"node info too large: {ln}")
        return NodeInfo.decode(read_exact(ln))
    finally:
        if sock is not None:
            try:
                sock.settimeout(prior)
            except OSError:
                pass


class Peer(BaseService):
    def __init__(
        self,
        stream,
        outbound: bool,
        channel_descs: list[ChannelDescriptor],
        on_receive,  # (peer, ch_id, msg_bytes)
        on_error,  # (peer, exc)
        config: PeerConfig,
        node_priv_key,
        persistent: bool = False,
    ):
        super().__init__(name="peer")
        self.outbound = outbound
        self.persistent = persistent
        self.config = config
        self.node_info: NodeInfo | None = None
        self.data: dict = {}  # per-peer reactor state (e.g. PeerState)
        # registry scoping the p2p_peer_* series (round 15): the switch
        # sets this from its own metrics_registry before handshake; None
        # falls back to the process-wide registry
        self.metrics_registry = None

        if config.fuzz:
            from tendermint_tpu.p2p.fuzz import FuzzedStream

            stream = FuzzedStream(stream, **config.fuzz_config)
        if config.auth_enc:
            from tendermint_tpu.p2p.secret_connection import SecretConnection

            stream = SecretConnection(stream, node_priv_key)
        self.stream = stream

        self.mconn = MConnection(
            stream,
            channel_descs,
            on_receive=lambda ch, msg: on_receive(self, ch, msg),
            on_error=lambda exc: on_error(self, exc),
            config=config.mconfig,
        )

    # -- handshake (before start) -----------------------------------------

    def handshake(self, our_info: NodeInfo) -> NodeInfo:
        self.node_info = exchange_node_info(
            self.stream, our_info, self.config.handshake_timeout
        )
        if self.config.auth_enc:
            # the identity that signed the secret-connection challenge must
            # be the identity claimed in NodeInfo (p2p/peer.go:181-191)
            if self.stream.remote_pubkey().raw != self.node_info.pub_key.raw:
                raise ConnectionError("node info pubkey != secret conn pubkey")
        self.mconn._name = f"mconn:{self.id()[:8]}"
        # identity is known now: arm the per-peer instrument families
        # (p2p/telemetry.py) on whichever registry scopes this peer
        self.mconn.set_peer_label(self.id(), self.metrics_registry)
        return self.node_info

    # -- identity ----------------------------------------------------------

    def id(self) -> str:
        return self.node_info.id() if self.node_info else "?"

    def pub_key(self):
        if self.config.auth_enc:
            return self.stream.remote_pubkey()
        return self.node_info.pub_key if self.node_info else None

    # -- lifecycle ---------------------------------------------------------

    def on_start(self) -> None:
        # The switch arms an admission timeout on the RAW socket for the
        # handshakes (Switch.add_peer_from_stream) and restores it to
        # blocking only AFTER add_peer returns — but the mconn recv
        # routine starts HERE, inside add_peer, and CPython fixes a
        # recv's deadline at call entry, so its first blocking read
        # inherited the armed timeout. A link quiet past the remaining
        # budget (mconn pings only every 40 s; a loaded box delays the
        # remote's first gossip sends arbitrarily) then tripped the
        # timeout, which SocketStream.read reports as EOF — both sides
        # dropped "stream closed" with nothing wrong on the wire: the
        # round-16 full-suite fast-sync flake. Clearing the timeout
        # BEFORE the recv routine launches closes the race; the
        # handshakes this timeout actually bounds are all complete by
        # the time start() runs.
        obj, hops = self.stream, 0
        while obj is not None and hops < 4:
            sock = getattr(obj, "sock", None)
            if sock is not None:
                try:
                    sock.settimeout(None)
                except OSError:
                    pass
                break
            obj = getattr(obj, "stream", None)
            hops += 1
        self.mconn.start()

    def on_stop(self) -> None:
        self.mconn.stop()

    # -- messaging ---------------------------------------------------------

    def send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.send(ch_id, msg)

    def try_send(self, ch_id: int, msg: bytes) -> bool:
        return self.mconn.try_send(ch_id, msg)

    def can_send(self, ch_id: int) -> bool:
        return self.mconn.can_send(ch_id)

    def last_recv_age(self) -> float:
        """Seconds since ANY packet arrived on this connection — the
        per-peer staleness signal (p2p_peer_last_recv_age_seconds,
        refreshed at collect time by node/telemetry.py)."""
        return time.monotonic() - self.mconn.last_recv

    def get(self, key: str):
        return self.data.get(key)

    def set(self, key: str, value) -> None:
        self.data[key] = value

    def status(self) -> dict:
        st = self.mconn.status()
        st["node_info"] = self.node_info.to_json() if self.node_info else None
        return st

    def __repr__(self) -> str:
        arrow = "->" if self.outbound else "<-"
        return f"Peer{{{arrow} {self.id()[:12]}}}"
