"""Byte-stream abstraction under MConnection.

Streams expose blocking read(n)/write(b)/close(). TCP sockets and
in-process socketpairs (the net.Pipe() equivalent used by
make_connected_switches, reference p2p/switch.go:502-547) both satisfy it.
"""

from __future__ import annotations

import socket


class SocketStream:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (e.g. unix socketpair)

    def read(self, n: int) -> bytes:
        try:
            return self.sock.recv(n)
        except OSError:
            return b""

    def write(self, data: bytes) -> None:
        self.sock.sendall(data)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()

    def remote_addr(self) -> str:
        try:
            host, port = self.sock.getpeername()[:2]
            return f"{host}:{port}"
        except OSError:
            return "pipe"


def pipe_pair() -> tuple[SocketStream, SocketStream]:
    """In-process full-duplex stream pair (net.Pipe equivalent)."""
    a, b = socket.socketpair()
    return SocketStream(a), SocketStream(b)
