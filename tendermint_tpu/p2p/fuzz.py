"""Seeded adversarial stream wrapper (reference: p2p/fuzz.go).

Audited for the round-18 adversarial tier: the reference's silent
read/write DROP mode (`prob_drop_rw`) predated the secure transport and
was broken against `SecretConnection` — a silently dropped write
desyncs the AEAD counter nonces, so every LATER frame fails
authentication and the wrapper poisons its own connection forever.
Nothing real was being simulated either: TCP never loses stream bytes
silently (loss is retransmit latency, which `prob_sleep` models, and
which the WAN profiles in ops/netfaults model properly).

The drop mode is therefore replaced by `prob_corrupt`: a seeded
single-byte XOR on outbound writes. Layered where PeerConfig puts this
wrapper — UNDER the SecretConnection — a corrupted write is ciphertext
tamper on the wire, which the remote AEAD flags loudly
(p2p_secretconn_auth_failures_total + peer dropped for cause). That
makes FuzzedStream the adversarial tier's FRAME-CORRUPTION peer: a
hostile-but-fluent peer built over it speaks the real protocol while a
seeded fraction of its frames arrive tampered (docs/netchaos.md,
docs/secure-p2p.md threat model).

Delay modes (`prob_sleep`, `max_delay`) are unchanged — reads are only
ever delayed, never dropped, since dropping reads would desync framing
on our own side.
"""

from __future__ import annotations

import random
import time


class FuzzedStream:
    def __init__(
        self,
        stream,
        prob_corrupt: float = 0.0,
        prob_sleep: float = 0.0,
        max_delay: float = 0.1,
        seed: int | None = None,
    ):
        self.stream = stream
        self.prob_corrupt = prob_corrupt
        self.prob_sleep = prob_sleep
        self.max_delay = max_delay
        self.corrupted_writes = 0  # observable by harnesses/tests
        self._rng = random.Random(seed)

    def _maybe_sleep(self) -> None:
        if self._rng.random() < self.prob_sleep:
            time.sleep(self._rng.random() * self.max_delay)

    def read(self, n: int) -> bytes:
        # reads are only delayed: dropping them would desync framing
        self._maybe_sleep()
        return self.stream.read(n)

    def write(self, data: bytes) -> None:
        self._maybe_sleep()
        if data and self._rng.random() < self.prob_corrupt:
            buf = bytearray(data)
            buf[self._rng.randrange(len(buf))] ^= 0xFF
            data = bytes(buf)
            self.corrupted_writes += 1
        self.stream.write(data)

    def close(self) -> None:
        self.stream.close()

    def remote_addr(self) -> str:
        inner = getattr(self.stream, "remote_addr", None)
        return inner() if inner else "fuzzed"
