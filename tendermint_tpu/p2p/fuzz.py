"""Chaos-testing stream wrapper (reference: p2p/fuzz.go).

Randomly drops or delays reads/writes so reactor code is exercised under
packet loss and latency without a real flaky network.
"""

from __future__ import annotations

import random
import time


class FuzzedStream:
    def __init__(
        self,
        stream,
        prob_drop_rw: float = 0.0,
        prob_sleep: float = 0.0,
        max_delay: float = 0.1,
        seed: int | None = None,
    ):
        self.stream = stream
        self.prob_drop_rw = prob_drop_rw
        self.prob_sleep = prob_sleep
        self.max_delay = max_delay
        self._rng = random.Random(seed)

    def _fuzz(self) -> bool:
        """True => drop this op."""
        if self._rng.random() < self.prob_drop_rw:
            return True
        if self._rng.random() < self.prob_sleep:
            time.sleep(self._rng.random() * self.max_delay)
        return False

    def read(self, n: int) -> bytes:
        # dropping reads would desync framing; only delay them
        if self._rng.random() < self.prob_sleep:
            time.sleep(self._rng.random() * self.max_delay)
        return self.stream.read(n)

    def write(self, data: bytes) -> None:
        if self._fuzz():
            return  # silently dropped
        self.stream.write(data)

    def close(self) -> None:
        self.stream.close()

    def remote_addr(self) -> str:
        inner = getattr(self.stream, "remote_addr", None)
        return inner() if inner else "fuzzed"
