"""TCP listener (reference: p2p/listener.go, minus UPnP — there is no
NAT to traverse in the deployment targets; external address detection
falls back to the bound interface address)."""

from __future__ import annotations

import socket

from tendermint_tpu.p2p.netaddress import NetAddress


class Listener:
    def __init__(self, laddr: str):
        addr = NetAddress.from_string(laddr) if laddr else NetAddress("0.0.0.0", 0)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((addr.ip, addr.port))
        self.sock.listen(64)
        host, port = self.sock.getsockname()[:2]
        self._internal = NetAddress(host, port)
        self._closed = False

    def internal_address(self) -> NetAddress:
        return self._internal

    def external_address(self) -> NetAddress:
        """Best-effort: the address a remote would dial. With a wildcard
        bind, use the primary interface address."""
        if self._internal.ip not in ("0.0.0.0", "::"):
            return self._internal
        try:
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            probe.connect(("10.255.255.255", 1))
            ip = probe.getsockname()[0]
            probe.close()
        except OSError:
            ip = "127.0.0.1"
        return NetAddress(ip, self._internal.port)

    def accept(self) -> socket.socket | None:
        """Blocks for the next inbound socket; None only once closed.
        Transient accept errors (ECONNABORTED, fd exhaustion) are retried
        — they must not permanently stop inbound peering."""
        import time

        while not self._closed:
            try:
                sock, _ = self.sock.accept()
                return sock
            except OSError:
                if self._closed:
                    return None
                time.sleep(0.1)
        return None

    def stop(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self.sock.close()
            except OSError:
                pass
