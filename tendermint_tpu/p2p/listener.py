"""TCP listener with optional UPnP NAT traversal (reference:
p2p/listener.go:51-110 — try an IGD port mapping for the external
address, fall back to the bound interface address)."""

from __future__ import annotations

import logging
import socket

from tendermint_tpu.p2p.netaddress import NetAddress

logger = logging.getLogger("p2p.listener")


class Listener:
    def __init__(self, laddr: str, skip_upnp: bool = True):
        addr = NetAddress.from_string(laddr) if laddr else NetAddress("0.0.0.0", 0)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((addr.ip, addr.port))
        self.sock.listen(64)
        host, port = self.sock.getsockname()[:2]
        self._internal = NetAddress(host, port)
        self._closed = False
        self._upnp_external: NetAddress | None = None
        self._upnp_nat = None
        if not skip_upnp:
            self._try_upnp()

    def _try_upnp(self) -> None:
        """Map our port on a discovered IGD and learn the external IP
        (listener.go:51-74). Every failure means 'no NAT': log and move
        on — startup must not block on a network with no gateway."""
        from tendermint_tpu.p2p import upnp

        try:
            nat = upnp.discover(timeout=1.0)
            ext_ip = nat.get_external_address()
            nat.add_port_mapping(
                "tcp", self._internal.port, self._internal.port,
                "tendermint-tpu p2p", 0,
            )
            self._upnp_nat = nat
            self._upnp_external = NetAddress(ext_ip, self._internal.port)
            logger.info("UPnP mapped port %d, external %s", self._internal.port, ext_ip)
        except upnp.UPnPError as exc:
            logger.info("UPnP unavailable: %s", exc)

    def internal_address(self) -> NetAddress:
        return self._internal

    def external_address(self) -> NetAddress:
        """Best-effort: the address a remote would dial. UPnP-discovered
        external address first; with a wildcard bind, the primary
        interface address."""
        if self._upnp_external is not None:
            return self._upnp_external
        if self._internal.ip not in ("0.0.0.0", "::"):
            return self._internal
        try:
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            probe.connect(("10.255.255.255", 1))
            ip = probe.getsockname()[0]
            probe.close()
        except OSError:
            ip = "127.0.0.1"
        return NetAddress(ip, self._internal.port)

    def accept(self) -> socket.socket | None:
        """Blocks for the next inbound socket; None only once closed.
        Transient accept errors (ECONNABORTED, fd exhaustion) are retried
        — they must not permanently stop inbound peering."""
        import time

        while not self._closed:
            try:
                sock, _ = self.sock.accept()
                return sock
            except OSError:
                if self._closed:
                    return None
                time.sleep(0.1)
        return None

    def stop(self) -> None:
        if not self._closed:
            self._closed = True
            # shutdown-then-close (the PR-3 socket-teardown lesson, here
            # for LISTENING sockets): close() alone does not wake a
            # thread blocked in accept() — the in-flight syscall pins the
            # open file description, so the socket stays bound AND
            # listening until a connection happens to arrive, which both
            # leaks the accept thread and holds the port against a
            # listener restart (the chaos tier's churn arm rebinds the
            # same port on purpose)
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass
            if self._upnp_nat is not None:
                from tendermint_tpu.p2p import upnp

                try:
                    self._upnp_nat.delete_port_mapping("tcp", self._internal.port)
                except upnp.UPnPError:
                    pass
